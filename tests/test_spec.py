"""Speculative decoding: proposers, verify/acceptance, rollback accounting.

Everything runs on tiny models with few steps — tier-1 is near its timeout
budget, so every engine build here compiles only a handful of tiny-byte
bucket programs.
"""

import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
from dynamo_tpu.engine.spec import NgramProposer, SeqSpecState, SpecConfig, resolve_spec
from dynamo_tpu.llm.protocols.common import (
    BackendInput,
    SamplingOptions,
    StopConditions,
)
from dynamo_tpu.models import llama


def make_cfg(**kw):
    d = dict(model=llama.preset("tiny-byte"), tp=1, page_size=8, max_batch=2,
             max_context=128, prefill_chunk=32)
    d.update(kw)
    return JaxEngineConfig(**d)


def req(tokens, max_tokens=8, **kw):
    return BackendInput(token_ids=list(tokens),
                        stop=StopConditions(max_tokens=max_tokens), **kw)


def drain(core, want_seqs):
    got = {s: [] for s in want_seqs}
    done = set()
    for _ in range(800):
        for so in core.step():
            got[so.seq_id].append(so)
            if so.finish is not None:
                done.add(so.seq_id)
        if done >= set(want_seqs):
            return got
    raise AssertionError(f"not all finished: {done} vs {want_seqs}")


# ---------------------------------------------------------------------------
# host-side units (no jax)
# ---------------------------------------------------------------------------
def test_ngram_proposer_lookup():
    sc = SpecConfig(mode="ngram", k_max=4, ngram_max=3, ngram_min=1)
    p = NgramProposer(sc)
    # suffix [7, 8] occurred earlier, continued by [9, 10, 11, 12]
    st = SeqSpecState(tokens=[5, 6, 7, 8, 9, 10, 11, 12, 7, 8], k=4)
    assert p.propose("s", st, 4) == [9, 10, 11, 12]
    assert p.propose("s", st, 2) == [9, 10]
    # no earlier occurrence of any suffix n-gram -> no drafts
    st2 = SeqSpecState(tokens=[1, 2, 3, 4, 5], k=4)
    assert p.propose("s", st2, 4) == []
    # the MOST RECENT earlier occurrence wins (periodic tail); the
    # continuation is clipped at the context end
    st3 = SeqSpecState(tokens=[1, 9, 1, 9, 1, 9], k=3)
    assert p.propose("s", st3, 3) == [1, 9]


def test_spec_config_buckets_and_adaptive_k():
    sc = SpecConfig(mode="ngram", k_max=6, k_min=1)
    assert sc.k_buckets == [1, 2, 4, 6]
    assert sc.bucket(0) == 1 and sc.bucket(3) == 4 and sc.bucket(99) == 6
    assert sc.next_k(2, accepted=2, proposed=2) == 4      # grow
    assert sc.next_k(4, accepted=0, proposed=4) == 2      # shrink
    assert sc.next_k(4, accepted=2, proposed=4) == 4      # hold
    assert sc.next_k(1, accepted=0, proposed=1) == 1      # floor
    assert sc.next_k(6, accepted=6, proposed=6) == 6      # ceiling
    off = SpecConfig(mode="ngram", k_max=4, adapt=False)
    assert off.next_k(2, accepted=2, proposed=2) == 2


def test_resolve_spec_env_and_config(monkeypatch):
    cfg = make_cfg()
    assert resolve_spec(cfg) is None                      # off by default
    monkeypatch.setenv("DYN_SPEC", "ngram")
    monkeypatch.setenv("DYN_SPEC_K", "7")
    sc = resolve_spec(cfg)
    assert sc is not None and sc.mode == "ngram" and sc.k_max == 7
    # explicit config force-disables regardless of env
    assert resolve_spec(make_cfg(spec="off")) is None
    # explicit config overrides env
    sc2 = resolve_spec(make_cfg(spec="ngram", spec_k=2))
    assert sc2.k_max == 2
    monkeypatch.setenv("DYN_SPEC", "bogus")
    with pytest.raises(ValueError):
        resolve_spec(cfg)


def test_backend_input_spec_fields_roundtrip():
    bi = BackendInput(token_ids=[1, 2], no_spec=True, kv_salt=1234)
    d = bi.to_dict()
    back = BackendInput.from_dict(d)
    assert back.no_spec is True and back.kv_salt == 1234
    # absent fields default off (older peers on the wire)
    old = BackendInput.from_dict({"token_ids": [1]})
    assert old.no_spec is False and old.kv_salt == 0


# ---------------------------------------------------------------------------
# the core correctness invariant: greedy spec == greedy non-spec
# ---------------------------------------------------------------------------
# Module-scoped cores: program compiles dominate tier-1 cost, so every
# engine-level test below reuses these two (they drain back to empty
# between tests, the same discipline test_jax_engine's shared core uses).
@pytest.fixture(scope="module")
def base_core():
    return EngineCore(make_cfg())


@pytest.fixture(scope="module")
def spec_core():
    # k_max=2 keeps the verify-program bucket set at {1, 2}
    return EngineCore(make_cfg(spec="ngram", spec_k=2))


def test_greedy_spec_identical_ngram(base_core, spec_core):
    assert base_core.spec is None
    assert not base_core._verify_fns      # spec off: zero extra programs
    # repetitive prompt (real n-gram hits) + a second request reusing the
    # slot (exercises the fresh-lane counts reset) + a presence-penalty
    # request (opt-out lane: k=0 decode through the verify program)
    reqs = [
        ("a", req([5, 6, 7, 8] * 3, max_tokens=12)),
        ("b", req([9, 10, 11], max_tokens=8)),
        ("c", BackendInput(token_ids=[20, 21, 22],
                           stop=StopConditions(max_tokens=8),
                           sampling=SamplingOptions(presence_penalty=0.5))),
    ]
    for seq_id, r in reqs:
        base_core.submit(seq_id, r)
        spec_core.submit(seq_id, r)
    want = [s for s, _ in reqs]
    got_b = drain(base_core, want)
    got_s = drain(spec_core, want)
    for seq_id in want:
        tb = [g.token for g in got_b[seq_id]]
        ts = [g.token for g in got_s[seq_id]]
        assert tb == ts, f"{seq_id}: spec diverged: {tb} vs {ts}"
    assert spec_core.active == 0 and base_core.active == 0


def test_draft_model_proposer_sync_and_rollback():
    """The draft proposer's incremental KV sync must be path-independent:
    proposing, then committing DIFFERENT tokens (rejection + correction)
    and proposing again gives exactly what a fresh proposer fed the same
    final context proposes — i.e. stale drafted KV is correctly overwritten
    and rollback is pure bookkeeping. (The engine integration is proposer-
    agnostic — test_greedy_spec_identical_ngram covers that path — so the
    draft model is tested at the proposer seam, which is cheap.)"""
    from dynamo_tpu.engine.spec import DraftModelProposer

    sc = SpecConfig(mode="draft", k_max=2)
    cfg = make_cfg(max_batch=1, max_context=64)
    mk = lambda: DraftModelProposer(sc, cfg, s_buckets=[32, 64],
                                    c_buckets=[8])
    p1 = mk()
    st = SeqSpecState(tokens=[5, 6, 7, 8, 9], k=2)
    d1 = p1.propose("s", st, 2)
    assert len(d1) == 2
    assert all(0 <= t < cfg.model.vocab_size for t in d1)
    # simulate "both drafts rejected, corrected token committed instead"
    st.tokens += [int(d1[0]) ^ 1, 3]
    d2 = p1.propose("s", st, 2)
    p2 = mk()
    st_fresh = SeqSpecState(tokens=list(st.tokens), k=2)
    assert p2.propose("t", st_fresh, 2) == d2
    # per-seq state is released on drop
    p1.drop("s")
    assert p1.synced == {} and not p1.pool.seqs


def test_engine_builds_draft_proposer():
    """spec='draft' engine construction wires the draft proposer (no decode
    run here — the verify path is proposer-agnostic and covered above)."""
    from dynamo_tpu.engine.spec import DraftModelProposer

    core = EngineCore(make_cfg(spec="draft", spec_k=2, max_batch=1))
    assert isinstance(core.proposer, DraftModelProposer)
    assert core.proposer.mcfg.vocab_size == core.cfg.model.vocab_size


def test_no_spec_opt_out(spec_core):
    before = spec_core.spec_proposed_total
    spec_core.submit("o", req([5, 6, 7, 8] * 3, max_tokens=6, no_spec=True))
    drain(spec_core, ["o"])
    assert spec_core.spec_proposed_total == before


# ---------------------------------------------------------------------------
# rollback: rejected tokens leave pool accounting + sealed hashes untouched
# ---------------------------------------------------------------------------
def test_rollback_leaves_pool_accounting_identical(base_core, spec_core):
    def run(core):
        sealed = []
        core.pool.on_block_sealed = (
            lambda seq, blk, page, lora: sealed.append(blk.sequence_hash))
        accepted0 = core.spec_accepted_total if core.spec else 0
        proposed0 = core.spec_proposed_total if core.spec else 0
        try:
            # non-repetitive prompt: the n-gram proposer fires and is
            # mostly WRONG, so nearly every round rejects and rolls back
            core.submit("r", req([3, 1, 4, 1, 5, 9, 2, 6], max_tokens=18))
            toks = [g.token for g in drain(core, ["r"])["r"]]
            for _ in range(4):       # settle deferred releases
                core.step()
        finally:
            core.pool.on_block_sealed = None
        if core.spec:
            assert (core.spec_proposed_total - proposed0
                    > core.spec_accepted_total - accepted0)
        return toks, sealed

    t1, sealed1 = run(base_core)
    t2, sealed2 = run(spec_core)
    assert t1 == t2
    # block hashes sealed ONLY over accepted tokens: identical chains
    assert sealed1 == sealed2 and len(sealed1) >= 2
    # page accounting drained back to empty in both
    assert base_core.pool.free_pages == base_core.pool.num_pages - 1
    assert spec_core.pool.free_pages == spec_core.pool.num_pages - 1


# ---------------------------------------------------------------------------
# rejection sampling preserves the target distribution (seeded, exact bound)
# ---------------------------------------------------------------------------
def test_rejection_sampling_preserves_distribution():
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.engine.sampling import (
        STATIC_K,
        spec_accept,
        spec_unpack,
        spec_verify,
    )

    N = 4000          # trials (lanes of one spec_verify call)
    K = 1             # one draft position
    V = STATIC_K      # top-k window == vocab: the mask keeps both tokens
    p0 = 0.6          # target: {tok0: 0.6, tok1: 0.4}, rest ~0
    logits = np.full((N, K + 1, V), -1e9, np.float32)
    logits[:, :, 0] = np.log(p0)
    logits[:, :, 1] = np.log(1.0 - p0)
    drafts = np.zeros((N, K), np.int32)       # always draft tok0
    temp = np.ones(N, np.float32)
    top_p = np.ones(N, np.float32)
    top_k = np.zeros(N, np.int32)
    keys = jax.random.split(jax.random.key(1234), N)
    packed, _ = jax.jit(spec_verify)(
        jnp.asarray(logits), jnp.asarray(drafts), temp, top_p, top_k, keys)
    r = spec_unpack(np.asarray(packed), K)
    firsts = []
    for i in range(N):
        toks, _, _ = spec_accept([0], False, {k: v[i] for k, v in r.items()})
        firsts.append(toks[0])
    firsts = np.asarray(firsts)
    assert set(np.unique(firsts)) <= {0, 1}
    freq0 = float(np.mean(firsts == 0))
    # exact-count bound: 4 sigma of a Bernoulli(p0) mean over N trials
    bound = 4 * (p0 * (1 - p0) / N) ** 0.5
    assert abs(freq0 - p0) < bound, f"freq {freq0} vs target {p0} ± {bound}"


def test_spec_accept_greedy_semantics():
    from dynamo_tpu.engine.sampling import spec_accept

    lane = {"greedy_tok": np.array([7.0, 8.0, 9.0]),
            "logp_greedy": np.array([-0.1, -0.2, -0.3])}
    # full acceptance -> all drafts + bonus token
    toks, lps, acc = spec_accept([7, 8], True, lane)
    assert toks == [7, 8, 9] and acc == 2
    # first mismatch -> corrected token IS the argmax, rest discarded
    toks, _, acc = spec_accept([7, 5], True, lane)
    assert toks == [7, 8] and acc == 1
    toks, _, acc = spec_accept([5, 8], True, lane)
    assert toks == [7] and acc == 0
    # zero drafts degenerate to a plain single decode step
    toks, _, acc = spec_accept([], True, lane)
    assert toks == [7] and acc == 0


def test_spec_metrics_surface(spec_core):
    spec = spec_core
    spec.submit("m", req([5, 6, 7, 8] * 3, max_tokens=6))
    drain(spec, ["m"])
    u = spec.utilization()
    assert "spec_accept_rate" in u and 0.0 <= u["spec_accept_rate"] <= 1.0
    assert spec.spec_dispatch_total > 0
    # the rate rides ForwardPassMetrics to the router/planner
    from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
    m = ForwardPassMetrics(**u)
    assert m.spec_accept_rate == u["spec_accept_rate"]
    assert ForwardPassMetrics.from_dict(m.to_dict()).spec_accept_rate == \
        m.spec_accept_rate
