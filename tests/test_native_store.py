"""Native (C++) dynstore + C-ABI KV publisher.

Two proof obligations (VERDICT round 1, item 3):
1. the C++ store passes the existing distributed-runtime tests UNMODIFIED via
   the ``DYNAMO_TPU_STORE=native`` env switch;
2. the C ABI publisher (reference lib/bindings/c equivalent) feeds events a
   Python subscriber/indexer consumes unchanged.
"""

import asyncio
import json
import os
import shutil
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="native toolchain not available")


def _build():
    from dynamo_tpu.runtime.store_server import build_native

    return build_native()


async def _native_store():
    from dynamo_tpu.runtime.store_server import NativeStoreServer

    srv = NativeStoreServer()
    port = await srv.start()
    return srv, port


# ----------------------------------------------------------------------
# 1. the full existing store/runtime test module against the C++ server
# ----------------------------------------------------------------------

def test_runtime_suite_passes_against_native_store():
    """tests/test_runtime_distributed.py, unmodified, env-switched native."""
    _build()
    env = {**os.environ, "DYNAMO_TPU_STORE": "native"}
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-x", "-q",
         "tests/test_runtime_distributed.py"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"


# ----------------------------------------------------------------------
# 2. direct smoke of the native server (cheap, no subprocess-pytest)
# ----------------------------------------------------------------------

async def test_native_kv_watch_pubsub_queue():
    from dynamo_tpu.runtime.store_client import StoreClient

    _build()
    srv, port = await _native_store()
    try:
        c1 = await StoreClient(port=port).connect()
        c2 = await StoreClient(port=port).connect()

        # KV + prefix + create semantics
        await c1.put("a/b", b"1")
        assert await c2.get("a/b") == b"1"
        await c1.put("a/c", b"2")
        assert await c2.get_prefix("a/") == [("a/b", b"1"), ("a/c", b"2")]
        assert await c1.create("a/d", b"3")
        assert not await c1.create("a/d", b"3", or_validate=True)

        # watch: snapshot + live events
        events = []
        got = asyncio.Event()

        async def on_watch(key, value, deleted):
            events.append((key, value, deleted))
            got.set()

        snap = await c2.watch_prefix("a/", on_watch)
        assert ("a/b", b"1") in snap
        await c1.put("a/e", b"4")
        await asyncio.wait_for(got.wait(), 2.0)
        assert events[0] == ("a/e", b"4", False)

        # pub/sub fanout
        msgs = []
        mgot = asyncio.Event()

        async def on_msg(subject, payload):
            msgs.append((subject, payload))
            mgot.set()

        await c2.subscribe("ns.ev", on_msg)
        assert await c1.publish("ns.ev", b"hello") == 1
        await asyncio.wait_for(mgot.wait(), 2.0)
        assert msgs == [("ns.ev", b"hello")]

        # queue: push/pull/ack + blocking pull
        await c1.q_push("q1", b"m1")
        mid, payload = await c2.q_pull("q1")
        assert payload == b"m1"
        await c2.q_ack("q1", mid)
        assert await c1.q_len("q1") == 0

        pull = asyncio.create_task(c2.q_pull("q1"))
        await asyncio.sleep(0.1)
        assert not pull.done()  # parked server-side
        await c1.q_push("q1", b"m2")
        mid2, payload2 = await asyncio.wait_for(pull, 2.0)
        assert payload2 == b"m2"
        await c2.q_ack("q1", mid2)

        await c1.close()
        await c2.close()
    finally:
        await srv.stop()


async def test_native_lease_expiry_and_disconnect():
    from dynamo_tpu.runtime.store_client import StoreClient

    _build()
    srv, port = await _native_store()
    try:
        # TTL expiry deletes lease-bound keys
        c1 = await StoreClient(port=port).connect()
        lease = await c1.lease_grant(ttl=0.5, auto_keepalive=False)
        await c1.put("w/x", b"v", lease=lease)
        c2 = await StoreClient(port=port).connect()
        assert await c2.get("w/x") == b"v"
        await asyncio.sleep(1.0)
        assert await c2.get("w/x") is None

        # connection death expires its leases immediately (process death)
        c3 = await StoreClient(port=port).connect()
        lease3 = await c3.lease_grant(ttl=30.0, auto_keepalive=False)
        await c3.put("w/y", b"v3", lease=lease3)
        assert await c2.get("w/y") == b"v3"
        await c3.close()
        await asyncio.sleep(0.5)
        assert await c2.get("w/y") is None

        # ... but an UNBOUND (bind=False) lease survives its grantor's
        # death and expires only by TTL — the incident-bundle contract
        c3b = await StoreClient(port=port).connect()
        orphan = await c3b.lease_grant(ttl=1.5, auto_keepalive=False,
                                       bind=False)
        await c3b.put("w/z", b"vz", lease=orphan)
        await c3b.close()
        await asyncio.sleep(0.5)
        assert await c2.get("w/z") == b"vz"     # producer died, key lives
        await asyncio.sleep(1.5)
        assert await c2.get("w/z") is None      # TTL still enforced

        # unacked queue message requeues when its consumer dies
        c4 = await StoreClient(port=port).connect()
        await c2.q_push("qq", b"work")
        mid, _ = await c4.q_pull("qq")  # pulled but never acked
        await c4.close()
        await asyncio.sleep(0.3)
        mid2, payload = await asyncio.wait_for(c2.q_pull("qq"), 2.0)
        assert payload == b"work"
        await c2.q_ack("qq", mid2)

        await c1.close()
        await c2.close()
    finally:
        await srv.stop()


# ----------------------------------------------------------------------
# 3. C ABI publisher -> Python subscriber/indexer
# ----------------------------------------------------------------------

async def test_c_abi_publisher_feeds_python_indexer():
    from dynamo_tpu.llm.kv_router.native import NativeKvPublisher
    from dynamo_tpu.llm.kv_router.protocols import RouterEvent
    from dynamo_tpu.runtime.store_client import StoreClient

    _build()
    srv, port = await _native_store()
    pub = None
    try:
        c = await StoreClient(port=port).connect()
        received = []
        done = asyncio.Event()

        async def on_msg(subject, payload):
            received.append(json.loads(payload.decode()))
            if len(received) >= 3:
                done.set()

        await c.subscribe("testns.worker.kv_events", on_msg)

        loop = asyncio.get_running_loop()
        pub = await loop.run_in_executor(
            None, lambda: NativeKvPublisher(
                "127.0.0.1", port, "testns", "worker", worker_id=7))
        pub.publish_stored([(0xDEAD_BEEF_0000_0001, 0xABC0_0000_0000_0002)],
                           parent_hash=None)
        pub.publish_removed([0xDEAD_BEEF_0000_0001])
        # adapter-tagged store (C ABI lora_id parity with ref lib.rs:253-283)
        pub.publish_stored([(0x1111_0000_0000_0003, 0x2222_0000_0000_0004)],
                           parent_hash=None, lora_id=42)
        await asyncio.wait_for(done.wait(), 5.0)

        ev0 = RouterEvent.from_dict(received[0])
        assert ev0.worker_id == 7
        assert ev0.event.stored is not None
        assert ev0.event.stored.blocks[0].block_hash == 0xDEAD_BEEF_0000_0001
        assert ev0.event.stored.blocks[0].tokens_hash == 0xABC0_0000_0000_0002
        assert ev0.event.stored.parent_hash is None

        ev1 = RouterEvent.from_dict(received[1])
        assert ev1.event.removed is not None
        assert ev1.event.removed.block_hashes == [0xDEAD_BEEF_0000_0001]

        ev2 = RouterEvent.from_dict(received[2])
        assert ev2.event.stored is not None
        assert ev2.event.stored.lora_id == 42
        assert ev2.event.stored.blocks[0].block_hash == 0x1111_0000_0000_0003

        await c.close()
    finally:
        if pub is not None:
            pub.shutdown()
        await srv.stop()


# ----------------------------------------------------------------------
# 3. slow-consumer policy: a stuck subscriber is disconnected, not OOM
# ----------------------------------------------------------------------

async def test_native_slow_subscriber_disconnected():
    """A subscriber that never reads must be dropped once its write backlog
    exceeds the server cap (NATS slow-consumer semantics); publishers and
    healthy subscribers keep working throughout."""
    from dynamo_tpu.runtime.store_client import StoreClient
    from dynamo_tpu.runtime.wire import write_frame

    _build()
    srv, port = await _native_store()
    try:
        # stuck subscriber: subscribes, then never reads again
        sr, sw = await asyncio.open_connection("127.0.0.1", port)
        await write_frame(sw, {"op": "subscribe", "id": 1, "sub_id": 1,
                               "subject": "bench.slow"})
        await sr.readexactly(4)  # ack frame length only; then stop reading

        # healthy subscriber on the same subject
        healthy = await StoreClient(port=port).connect()
        got = []
        await healthy.subscribe("bench.slow", lambda s, p: got.append(len(p)))

        pub = await StoreClient(port=port).connect()
        payload = b"x" * (256 * 1024)
        # 128 * 256 KiB = 32 MiB >> the 8 MiB per-conn backlog cap
        for _ in range(128):
            await pub.publish("bench.slow", payload)

        # the stuck conn must be closed by the server: draining what the
        # kernel already buffered ends in EOF instead of blocking forever
        async def drain_to_eof():
            while await sr.read(1 << 20):
                pass

        await asyncio.wait_for(drain_to_eof(), 30.0)

        # the healthy subscriber saw everything and the plane still works
        for _ in range(200):
            if len(got) >= 128:
                break
            await asyncio.sleep(0.05)
        assert len(got) == 128
        assert await pub.publish("bench.slow", b"tail") >= 1
        await healthy.close()
        await pub.close()
        sw.close()
    finally:
        await srv.stop()
