"""Ring attention vs. dense reference on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.models.llama import attend
from dynamo_tpu.parallel.mesh import AXIS_SP, MeshConfig, make_mesh
from dynamo_tpu.parallel.ring_attention import ring_attention


def _dense(q, k, v, q_pos, k_pos, k_valid):
    mask = k_valid[:, None, :] & (k_pos[:, None, :] <= q_pos[:, :, None])
    return attend(q, k, v, mask)


def _mk(B, T, S, Hq, Hkv, Dh, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, Dh), jnp.float32)
    k = jax.random.normal(ks[1], (B, S, Hkv, Dh), jnp.float32)
    v = jax.random.normal(ks[2], (B, S, Hkv, Dh), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense_causal(sp):
    mesh = make_mesh(MeshConfig(sp=sp))
    B, T, S, Hq, Hkv, Dh = 2, 16, 32, 4, 2, 8
    q, k, v = _mk(B, T, S, Hq, Hkv, Dh)
    # prefill-chunk geometry: queries at positions [16, 32), context [0, 28)
    q_pos = jnp.broadcast_to(jnp.arange(16, 32, dtype=jnp.int32), (B, T))
    k_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    k_valid = k_pos < 28

    got = jax.jit(lambda *a: ring_attention(*a, mesh=mesh))(
        q, k, v, q_pos, k_pos, k_valid)
    want = _dense(q, k, v, q_pos, k_pos, k_valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_sp1_fallback():
    mesh = make_mesh(MeshConfig(sp=1))
    B, T, S, Hq, Hkv, Dh = 1, 8, 16, 4, 4, 8
    q, k, v = _mk(B, T, S, Hq, Hkv, Dh, seed=1)
    q_pos = jnp.broadcast_to(jnp.arange(8, 16, dtype=jnp.int32), (B, T))
    k_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    k_valid = jnp.ones((B, S), bool)
    got = ring_attention(q, k, v, q_pos, k_pos, k_valid, mesh=mesh)
    want = _dense(q, k, v, q_pos, k_pos, k_valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_ring_fully_masked_rows_finite():
    mesh = make_mesh(MeshConfig(sp=4))
    B, T, S, Hq, Hkv, Dh = 1, 8, 16, 2, 1, 8
    q, k, v = _mk(B, T, S, Hq, Hkv, Dh, seed=2)
    q_pos = jnp.zeros((B, T), jnp.int32)
    k_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    k_valid = jnp.zeros((B, S), bool)   # nothing to attend at all
    out = ring_attention(q, k, v, q_pos, k_pos, k_valid, mesh=mesh)
    assert np.isfinite(np.asarray(out)).all()


def test_ring_long_context_sharded_inputs():
    """Inputs pre-sharded over sp (the real long-context layout) work and
    match dense; exercises the jit + NamedSharding + shard_map composition."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = make_mesh(MeshConfig(sp=8))
    B, T, S, Hq, Hkv, Dh = 1, 64, 64, 4, 2, 8
    q, k, v = _mk(B, T, S, Hq, Hkv, Dh, seed=3)
    q_pos = jnp.broadcast_to(jnp.arange(S - T, S, dtype=jnp.int32), (B, T))
    k_pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    k_valid = jnp.ones((B, S), bool)
    sh4 = NamedSharding(mesh, P(None, AXIS_SP, None, None))
    sh2 = NamedSharding(mesh, P(None, AXIS_SP))
    args = (jax.device_put(q, sh4), jax.device_put(k, sh4),
            jax.device_put(v, sh4), jax.device_put(q_pos, sh2),
            jax.device_put(k_pos, sh2), jax.device_put(k_valid, sh2))
    got = jax.jit(lambda *a: ring_attention(*a, mesh=mesh))(*args)
    want = _dense(q, k, v, q_pos, k_pos, k_valid)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
