"""SDK layer: @service/@dynamo_endpoint/depends/.link(), the serve
orchestrator and the TPU allocator (VERDICT round-1 missing #4/L6)."""

import asyncio
import sys

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store_server import StoreServer
from dynamo_tpu.sdk import depends, dynamo_endpoint, async_on_start, service
from dynamo_tpu.sdk.allocator import AllocationError, TpuAllocator
from dynamo_tpu.sdk.service import collect_graph
from dynamo_tpu.sdk.serve_child import run_service


@service(namespace="t")
class Leaf:
    @dynamo_endpoint()
    async def generate(self, request, ctx):
        yield {"n": request["n"] * 2}


@service(namespace="t")
class Mid:
    leaf = depends(Leaf)
    started = False

    @async_on_start
    async def boot(self):
        type(self).started = True

    @dynamo_endpoint()
    async def generate(self, request, ctx):
        async for item in self.leaf.generate(request):
            yield {"n": item["n"] + 1}


@service(namespace="t")
class Entry:
    mid = depends(Mid)

    @dynamo_endpoint()
    async def generate(self, request, ctx):
        async for item in self.mid.generate(request):
            yield item


Entry.link(Mid).link(Leaf)


def test_spec_and_graph_collection():
    spec = Mid._dynamo_spec
    assert spec.name == "mid" and spec.namespace == "t"
    assert spec.endpoints == {"generate": "generate"}
    assert spec.on_start == ["boot"]
    assert list(spec.dependencies) == ["leaf"]
    # dependency-first order: leaves before their callers
    order = collect_graph(Entry)
    assert order.index(Leaf) < order.index(Mid) < order.index(Entry)


def test_allocator():
    a = TpuAllocator(total_chips=4, platform="tpu")
    assert a.allocate(2)["TPU_VISIBLE_DEVICES"] == "0,1"
    assert a.allocate(2)["TPU_VISIBLE_DEVICES"] == "2,3"
    with pytest.raises(AllocationError):
        a.allocate(1)
    assert a.allocate(0) == {"JAX_PLATFORMS": "cpu"}
    cpu = TpuAllocator(platform="cpu")
    env = cpu.allocate(8)
    assert "host_platform_device_count=8" in env["XLA_FLAGS"]


def test_unwired_dependency_raises():
    with pytest.raises(RuntimeError, match="not wired"):
        Entry().mid


async def test_three_stage_graph_in_process():
    """The full Entry->Mid->Leaf chain, each service brought up exactly the
    way serve_child does, exchanging data over the real data plane."""
    srv = StoreServer()
    port = await srv.start()
    store = f"127.0.0.1:{port}"
    tasks = []
    try:
        for cls in collect_graph(Entry):
            ev = asyncio.Event()
            tasks.append(asyncio.create_task(
                run_service(cls, store, ready_event=ev)))
            await asyncio.wait_for(ev.wait(), 15)
        assert Mid.started

        caller = await DistributedRuntime(store_port=port).connect()
        cl = await caller.namespace("t").component("entry") \
            .endpoint("generate").client().start()
        await cl.wait_for_instances(1)
        items = [x async for x in cl.generate({"n": 20})]
        assert items == [{"n": 41}]   # (20*2)+1 through the chain
        await caller.close()
    finally:
        for t in tasks:
            t.cancel()
        await srv.stop()


@pytest.mark.slow
def test_local_serve_subprocesses(tmp_path):
    """End-to-end orchestration: LocalServe spawns the hello_world graph as
    real processes (plus a dynstore) and the frontend answers."""
    import subprocess

    from dynamo_tpu.sdk.serve import LocalServe

    serve = LocalServe("examples.hello_world:Frontend", platform="cpu")
    try:
        serve.start(timeout=90)
        code = f"""
import asyncio
from dynamo_tpu.runtime.component import DistributedRuntime

async def main():
    drt = await DistributedRuntime(store_port={serve.store.split(':')[1]}).connect()
    cl = await (drt.namespace("hello").component("frontend")
                .endpoint("generate").client().start())
    await cl.wait_for_instances(1)
    out = [x async for x in cl.generate({{"text": "a b"}})]
    print("RESULT", out)
    await drt.close()

asyncio.run(main())
"""
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=60, cwd=".")
        assert "A-BACK" in r.stdout and "B-BACK" in r.stdout, \
            r.stdout + r.stderr
    finally:
        serve.stop()


def test_allocator_release_and_best_fit():
    """Slice-aware allocator (VERDICT r4 item #8, ref allocator.py:35-101):
    per-handle release returns chips to the pool, placement is contiguous
    best-fit over free runs, and placements() exposes the disjointness
    invariant."""
    a = TpuAllocator(total_chips=8, platform="tpu")
    w1 = a.allocate_handle(2, service="worker")     # [0,1]
    w2 = a.allocate_handle(4, service="worker")     # [2..5]
    w3 = a.allocate_handle(2, service="prefill")    # [6,7]
    sets = [set(x.chips) for x in (w1, w2, w3)]
    assert all(s1.isdisjoint(s2) for i, s1 in enumerate(sets)
               for s2 in sets[i + 1:])
    assert a.placements() == {"worker": [[0, 1], [2, 3, 4, 5]],
                              "prefill": [[6, 7]]}
    # restart path: release the middle worker, its run is reusable
    a.release(w2)
    w4 = a.allocate_handle(2, service="worker")
    assert w4.chips == [2, 3]
    # best-fit: with runs [4,5] free and a fresh 8-pool, a 2-chip ask takes
    # the SMALLEST fitting run, preserving big runs for big asks
    b = TpuAllocator(total_chips=8, platform="tpu")
    x1 = b.allocate_handle(3)        # [0,1,2]
    x2 = b.allocate_handle(1)        # [3]
    b.release(x1)                    # free runs: [0,1,2] and [4..7]
    y = b.allocate_handle(2)
    assert y.chips == [0, 1]         # smallest fitting run, not [4,5]
    # contiguity: a fragmented pool refuses a non-contiguous grant
    c = TpuAllocator(total_chips=4, platform="tpu")
    h1 = c.allocate_handle(1)        # [0]
    h2 = c.allocate_handle(1)        # [1]
    c.allocate_handle(1)             # [2]
    c.release(h1)
    c.release(h2)
    c2 = c.allocate_handle(2)        # [0,1] — contiguous pair exists
    assert c2.chips == [0, 1]
    with pytest.raises(AllocationError):
        c.allocate_handle(2)         # only [3] and nothing contiguous left


def test_serve_places_workers_on_disjoint_chip_sets():
    """The spawn loop hands every worker of every service its own chip
    range (the VERDICT r4 'serve places two workers on disjoint device
    sets' criterion, exercised through the allocator serve actually uses)."""
    a = TpuAllocator(total_chips=4, platform="tpu")
    envs = [a.allocate(2, service="Worker") for _ in range(2)]
    seen = [set(e["TPU_VISIBLE_DEVICES"].split(",")) for e in envs]
    assert seen[0].isdisjoint(seen[1])
    assert a.placements()["Worker"] == [[0, 1], [2, 3]]


def test_allocator_stale_handle_double_release_is_safe():
    """release() matches by identity: re-releasing a stale handle whose
    chips were re-granted to an EQUAL new allocation must not free the new
    owner's live grant."""
    a = TpuAllocator(total_chips=4, platform="tpu")
    w = a.allocate_handle(2, service="worker")
    a.release(w)
    w2 = a.allocate_handle(2, service="worker")   # equal dataclass to w
    assert w2 == w and w2 is not w
    a.release(w)                                  # stale double release
    assert a.placements() == {"worker": [[0, 1]]}  # w2 still live
    with pytest.raises(AllocationError):
        a.allocate_handle(3)                      # [0,1] NOT back in pool
