"""SDK layer: @service/@dynamo_endpoint/depends/.link(), the serve
orchestrator and the TPU allocator (VERDICT round-1 missing #4/L6)."""

import asyncio
import sys

import pytest

from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.runtime.store_server import StoreServer
from dynamo_tpu.sdk import depends, dynamo_endpoint, async_on_start, service
from dynamo_tpu.sdk.allocator import AllocationError, TpuAllocator
from dynamo_tpu.sdk.service import collect_graph
from dynamo_tpu.sdk.serve_child import run_service


@service(namespace="t")
class Leaf:
    @dynamo_endpoint()
    async def generate(self, request, ctx):
        yield {"n": request["n"] * 2}


@service(namespace="t")
class Mid:
    leaf = depends(Leaf)
    started = False

    @async_on_start
    async def boot(self):
        type(self).started = True

    @dynamo_endpoint()
    async def generate(self, request, ctx):
        async for item in self.leaf.generate(request):
            yield {"n": item["n"] + 1}


@service(namespace="t")
class Entry:
    mid = depends(Mid)

    @dynamo_endpoint()
    async def generate(self, request, ctx):
        async for item in self.mid.generate(request):
            yield item


Entry.link(Mid).link(Leaf)


def test_spec_and_graph_collection():
    spec = Mid._dynamo_spec
    assert spec.name == "mid" and spec.namespace == "t"
    assert spec.endpoints == {"generate": "generate"}
    assert spec.on_start == ["boot"]
    assert list(spec.dependencies) == ["leaf"]
    # dependency-first order: leaves before their callers
    order = collect_graph(Entry)
    assert order.index(Leaf) < order.index(Mid) < order.index(Entry)


def test_allocator():
    a = TpuAllocator(total_chips=4, platform="tpu")
    assert a.allocate(2)["TPU_VISIBLE_DEVICES"] == "0,1"
    assert a.allocate(2)["TPU_VISIBLE_DEVICES"] == "2,3"
    with pytest.raises(AllocationError):
        a.allocate(1)
    assert a.allocate(0) == {"JAX_PLATFORMS": "cpu"}
    cpu = TpuAllocator(platform="cpu")
    env = cpu.allocate(8)
    assert "host_platform_device_count=8" in env["XLA_FLAGS"]


def test_unwired_dependency_raises():
    with pytest.raises(RuntimeError, match="not wired"):
        Entry().mid


async def test_three_stage_graph_in_process():
    """The full Entry->Mid->Leaf chain, each service brought up exactly the
    way serve_child does, exchanging data over the real data plane."""
    srv = StoreServer()
    port = await srv.start()
    store = f"127.0.0.1:{port}"
    tasks = []
    try:
        for cls in collect_graph(Entry):
            ev = asyncio.Event()
            tasks.append(asyncio.create_task(
                run_service(cls, store, ready_event=ev)))
            await asyncio.wait_for(ev.wait(), 15)
        assert Mid.started

        caller = await DistributedRuntime(store_port=port).connect()
        cl = await caller.namespace("t").component("entry") \
            .endpoint("generate").client().start()
        await cl.wait_for_instances(1)
        items = [x async for x in cl.generate({"n": 20})]
        assert items == [{"n": 41}]   # (20*2)+1 through the chain
        await caller.close()
    finally:
        for t in tasks:
            t.cancel()
        await srv.stop()


@pytest.mark.slow
def test_local_serve_subprocesses(tmp_path):
    """End-to-end orchestration: LocalServe spawns the hello_world graph as
    real processes (plus a dynstore) and the frontend answers."""
    import subprocess

    from dynamo_tpu.sdk.serve import LocalServe

    serve = LocalServe("examples.hello_world:Frontend", platform="cpu")
    try:
        serve.start(timeout=90)
        code = f"""
import asyncio
from dynamo_tpu.runtime.component import DistributedRuntime

async def main():
    drt = await DistributedRuntime(store_port={serve.store.split(':')[1]}).connect()
    cl = await (drt.namespace("hello").component("frontend")
                .endpoint("generate").client().start())
    await cl.wait_for_instances(1)
    out = [x async for x in cl.generate({{"text": "a b"}})]
    print("RESULT", out)
    await drt.close()

asyncio.run(main())
"""
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=60, cwd=".")
        assert "A-BACK" in r.stdout and "B-BACK" in r.stdout, \
            r.stdout + r.stderr
    finally:
        serve.stop()
