#!/usr/bin/env python
"""Fleet soak: ramp a synthetic worker fleet against ONE store and record
where the coordination plane saturates.

Everything in this system converges on one store process — leases,
endpoint registrations, watch fan-out, metrics dumps, the span sink,
router reads, planner scrapes. Before that store can be sharded we need
to SEE it saturate. This rig ramps a synthetic fleet (default 600
workers, in steps) where each synthetic worker is a *real* store session:

- its own TCP connection, lease (with keepalives) and endpoint
  registration — the discovery/liveness load of a worker, without an
  engine;
- a delta-batched :class:`StagePublisher` + ForwardPassMetrics refresh
  per beat — the metrics-plane load;
- a head-sampled :class:`StoreSpanSink` emitting spans per beat (a
  configurable fraction finish as errors, which sampling must never
  drop) — the span-plane load;
- a prefix watch on the fan-out beacon the driver puts every half
  second — one put must fan out to the WHOLE fleet, and each worker
  records the delivery lag.

Riding alongside at every step: the planner's signal collector and the
dyntop/SLO snapshotter (their scrape latency over N workers is part of
the curve), and — unless ``--traffic-rps 0`` — real replayed traffic
through store → kv-router process → HTTP frontend → echo workers, with
client-measured TTFT and forced-deadline requests whose error traces
must stay retrievable via ``GET /v1/traces/{id}`` at any sample rate.

Per step the store's own telemetry (``dyn_store_op_seconds{op,family}``
et al., PR 9) is differenced into the scaling curve: store op p99 by
keyspace family, watch fan-out lag p50/p99, span/metric write+drop
rates, router TTFT. The curve lands in ``bench_points/fleet_soak.json``
together with the detected **saturation knee** (first step whose store
op p99 exceeds ``--knee-mult``× the first step's, above a noise floor)
— the worklist the store-sharding refactor burns down.

    JAX_PLATFORMS=cpu python scripts/fleet_soak.py            # full ramp
    ... --workers 8 --steps 2 --step-duration 2 --traffic-rps 0   # mini
    ... --mode hier --aggregators 4 --shards 2 --workers 1000     # scale

**Modes** (the ``mode`` field on every observer-latency slice keeps
flat/hier artifacts comparable in one plot):

- ``flat`` (default): observers scrape every worker's dumps directly —
  the path PR 9 proved saturates first (merge p50 0.3s → 2.8s).
- ``hier``: ``--aggregators`` regional-aggregator daemons pre-merge the
  fleet into region records (runtime/scale/regions.py) and the
  observers read those; ``--shards`` > 1 additionally splits the store
  by keyspace family (``DYN_STORE_SHARDS`` armed fleet-wide: 2 =
  telemetry shard, 3 = + traces shard). Exit proof for the scale plane:
  observer merge p50 stays flat (<0.5s) past the old knee.

CPU-only, no model weights. The pytest mini run is tier-1; the full ramp
is marked ``chaos`` + ``slow``.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from overload_soak import _percentile as _soak_percentile  # noqa: E402

log = logging.getLogger("fleet_soak")

# NOT "fleet": endpoint keys are "{ns}/components/..." and a namespace
# of "fleet" would put them under the registered "fleet/" beacon prefix,
# classifying the whole discovery plane as family=fleet-soak in the very
# per-family curve this rig exists to record
NAMESPACE = "soak"
FLEET_COMPONENT = "fleet"


def fleet_beacon_key(namespace: str) -> str:
    """The fan-out beacon key (keyspace family ``fleet-soak``)."""
    return f"fleet/{namespace}/beacon"


def fleet_beacon_prefix(namespace: str) -> str:
    return f"fleet/{namespace}/"


def _percentile(values: List[float], q: float) -> Optional[float]:
    """overload_soak's percentile, with ``None`` (JSON null) for an empty
    series — an absent signal must not masquerade as a 0.0 latency."""
    if not values:
        return None
    return _soak_percentile(values, q)


# ---------------------------------------------------------------------------
# synthetic worker: a real store session without an engine
# ---------------------------------------------------------------------------
class SyntheticWorker:
    """One synthetic fleet member; see the module docstring for what it
    loads the store with. All loops are owned tasks, stopped in
    :meth:`stop`."""

    def __init__(self, idx: int, host: str, port: int, namespace: str,
                 lag_sink: List[float], beat_interval: float = 2.0,
                 spans_per_beat: int = 4, error_every: int = 25):
        self.idx = idx
        self.host, self.port = host, port
        self.namespace = namespace
        self.lag_sink = lag_sink
        self.beat_interval = beat_interval
        self.spans_per_beat = spans_per_beat
        self.error_every = error_every
        self.store = None
        self.lease: Optional[int] = None
        self.error_trace_ids: List[str] = []
        self.spans_emitted = 0
        self._tasks: List[asyncio.Task] = []
        self._sink = None
        self._span_n = 0

    async def start(self) -> "SyntheticWorker":
        from dynamo_tpu.llm.metrics_aggregator import (StagePublisher,
                                                       metrics_key)
        from dynamo_tpu.runtime.component import EndpointInfo, endpoint_key
        from dynamo_tpu.runtime.scale.shards import make_store_client
        from dynamo_tpu.utils import tracing
        from dynamo_tpu.utils.prometheus import Registry

        # sharding-aware: with DYN_STORE_SHARDS armed each synthetic
        # worker's planes land on their owning shards, like a real worker
        self.store = await make_store_client(self.host,
                                             self.port).connect()
        self.lease = await self.store.lease_grant(ttl=8.0)
        await self.store.put(
            endpoint_key(self.namespace, FLEET_COMPONENT, "generate",
                         self.lease),
            EndpointInfo("127.0.0.1", 0, "generate", self.lease,
                         self.lease).to_bytes(),
            lease=self.lease)
        # a private registry with real churn so delta batches carry signal
        r = Registry()
        self._beats = r.counter("dyn_fleet_heartbeats_total",
                                "synthetic worker beats", ())
        self._beat_s = r.histogram("dyn_fleet_beat_seconds",
                                   "synthetic beat duration", ())
        self._registry = r
        self._metrics_key = metrics_key(self.namespace, FLEET_COMPONENT,
                                        self.lease)
        self.publisher = StagePublisher(
            self.store, self.namespace, FLEET_COMPONENT, self.lease,
            self.lease, dump_fn=r.state_dump)
        self.tracer = tracing.Tracer(component="fleet", capacity=64)
        self._sink = await tracing.StoreSpanSink(
            self.store, flush_interval=1.0).start(tracer=self.tracer)
        await self.store.watch_prefix(
            fleet_beacon_prefix(self.namespace), self._on_beacon)
        self._tasks.append(asyncio.create_task(self._beat_loop()))
        return self

    async def _on_beacon(self, key: str, value: Optional[bytes],
                         deleted: bool) -> None:
        if deleted or value is None:
            return
        try:
            t_put = json.loads(value.decode())["t"]
        except (ValueError, KeyError):
            return   # foreign key under the prefix: not a beacon
        self.lag_sink.append(time.monotonic() - t_put)

    def _emit_spans(self) -> None:
        now = time.time()
        for _ in range(self.spans_per_beat):
            self._span_n += 1
            # first span of every worker is an error (so even a short
            # mini ramp exercises forced retention), then every Nth
            is_err = self.error_every \
                and self._span_n % self.error_every == 1
            tid = f"synt-{self.idx}-{self._span_n}"
            self.tracer.record("fleet.op", now - 0.002, now, trace_id=tid,
                               status="error" if is_err else "ok")
            self.spans_emitted += 1
            if is_err:
                self.error_trace_ids.append(tid)

    async def _beat_loop(self) -> None:
        from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
        from dynamo_tpu.runtime.store_client import StoreError

        while True:
            t0 = time.monotonic()
            try:
                self._beats.inc()
                fpm = ForwardPassMetrics(
                    request_active_slots=(self.idx + self._span_n) % 4,
                    request_total_slots=4)
                await self.store.put(
                    self._metrics_key,
                    json.dumps(fpm.to_dict()).encode(), lease=self.lease)
                await self.publisher.publish()
                self._emit_spans()
                self._beat_s.observe(value=time.monotonic() - t0)
            except asyncio.CancelledError:
                raise
            except StoreError:
                log.debug("worker %d beat skipped (store unreachable)",
                          self.idx)
            except Exception:
                log.exception("worker %d beat failed", self.idx)
            await asyncio.sleep(self.beat_interval)

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        try:
            if self._sink is not None:
                await asyncio.wait_for(self._sink.stop(), 5.0)
        except (Exception, asyncio.TimeoutError):
            log.debug("worker %d sink drain failed", self.idx)
        try:
            await self.store.close()
        except Exception:
            log.debug("worker %d store close failed", self.idx)


# ---------------------------------------------------------------------------
# store-telemetry differencing (per-step scaling-curve rows)
# ---------------------------------------------------------------------------
async def read_store_dump(store) -> Optional[Dict]:
    from dynamo_tpu.llm.metrics_aggregator import STORE_STAGE_PREFIX
    from dynamo_tpu.utils.prometheus import merge_state_dumps

    dumps = []
    if hasattr(store, "get_prefix_on"):
        # sharded: every shard publishes its own self-dump under the
        # same key in its own KV — the curve must sum all of them
        for i in range(store.num_shards):
            try:
                items = await store.get_prefix_on(i, STORE_STAGE_PREFIX)
            except Exception:
                log.warning("shard %d store dump unreadable", i)
                continue
            for _key, value in items:
                try:
                    dumps.append(json.loads(value.decode())["metrics"])
                except (ValueError, KeyError):
                    log.warning("malformed store self-dump")
    else:
        for _key, value in await store.get_prefix(STORE_STAGE_PREFIX):
            try:
                dumps.append(json.loads(value.decode())["metrics"])
            except (ValueError, KeyError):
                log.warning("malformed store self-dump")
    if not dumps:
        return None
    return dumps[0] if len(dumps) == 1 else merge_state_dumps(dumps)


def _json_p99(p99: Optional[float], buckets) -> Optional[float]:
    """JSON-safe p99: an overflow-bucket quantile clamps to the largest
    finite edge (read as ">= that edge") — ``json.dump`` would otherwise
    emit the non-standard ``Infinity`` literal and break strict parsers
    at exactly the saturated data points the rig targets."""
    if p99 == float("inf"):
        return float(buckets[-1]) if buckets else None
    return p99


def diff_op_families(start: Optional[Dict], end: Optional[Dict]
                     ) -> Tuple[Dict[str, Dict[str, Any]], Dict[str, Any]]:
    """``(families, overall)`` op count + p99 over ONE step, from the
    bucket deltas of two ``dyn_store_op_seconds`` snapshots — the one
    series walk serves both the per-family rows and the step's overall
    p99."""
    from dynamo_tpu.utils.prometheus import hist_quantile

    if not end or "dyn_store_op_seconds" not in end:
        return {}, {"ops": 0, "p99_s": None}
    st_end = end["dyn_store_op_seconds"]
    st_start = (start or {}).get("dyn_store_op_seconds") or {}
    start_series = st_start.get("series") or {}
    buckets = st_end.get("buckets")
    fams: Dict[str, Dict[str, Any]] = {}
    all_counts: Optional[List[float]] = None
    for skey, val in (st_end.get("series") or {}).items():
        parts = skey.split("\x1f")
        fam = parts[1] if len(parts) > 1 else "?"
        base = start_series.get(skey) or {"counts": [0] * len(
            val.get("counts") or []), "total": 0}
        counts = [a - b for a, b in zip(val.get("counts") or [],
                                        base.get("counts") or [])]
        agg = fams.setdefault(fam, {"ops": 0, "counts": None})
        agg["ops"] += val.get("total", 0) - base.get("total", 0)
        if agg["counts"] is None:
            agg["counts"] = counts
        else:
            agg["counts"] = [a + b for a, b in zip(agg["counts"], counts)]
        all_counts = counts if all_counts is None else [
            a + b for a, b in zip(all_counts, counts)]
    total_ops = sum(a["ops"] for a in fams.values())
    overall = {"ops": total_ops,
               "p99_s": _json_p99(
                   hist_quantile(buckets, all_counts or [],
                                 total_ops, 0.99), buckets)}
    return ({fam: {"ops": a["ops"],
                   "p99_s": _json_p99(
                       hist_quantile(buckets, a["counts"],
                                     a["ops"], 0.99), buckets)}
             for fam, a in fams.items() if a["ops"] > 0},
            overall)


def _counter_total(dump: Optional[Dict], name: str) -> float:
    st = (dump or {}).get(name) or {}
    return float(sum((st.get("series") or {}).values()) or 0.0)


def find_knee(steps: List[Dict], knee_mult: float,
              floor_s: float = 0.002) -> Dict[str, Any]:
    """First step whose overall store-op p99 exceeds ``knee_mult`` x the
    first step's (and an absolute noise floor) — the saturation knee."""
    curve = [(s["workers"], (s["store"].get("p99_s") or 0.0))
             for s in steps if s.get("store")]
    if not curve:
        return {"workers": None, "note": "no store telemetry"}
    baseline = curve[0][1]
    for workers, p99 in curve:
        if p99 >= max(knee_mult * baseline, floor_s):
            return {"workers": workers, "p99_s": round(p99, 6),
                    "baseline_p99_s": round(baseline, 6),
                    "mult": knee_mult}
    return {"workers": None, "baseline_p99_s": round(baseline, 6),
            "note": f"no knee <= {curve[-1][0]} workers"}


# ---------------------------------------------------------------------------
# the observer probe (its own process, like the real planner/dyntop)
# ---------------------------------------------------------------------------
async def run_observer_probe(store_addr: str, out_path: str,
                             interval: float = 2.0) -> None:
    """Tick the planner's SignalCollector and the dyntop snapshotter
    against the store forever, appending one JSONL row per round:
    ``{"t", "planner", "snapshot", "source"}`` (seconds per collect;
    the driver slices rows into per-step percentiles). Runs as a
    subprocess so the measurement reflects the observer path, not the
    driver loop that hosts a thousand synthetic workers."""
    from dynamo_tpu.cli.dyntop import ClusterSnapshotter
    from dynamo_tpu.planner.signals import SignalCollector
    from dynamo_tpu.runtime.scale.shards import make_store_client

    host, port = store_addr.split(":")
    store = await make_store_client(host, int(port)).connect()
    collector = SignalCollector(store, NAMESPACE,
                                {"fleet": FLEET_COMPONENT})
    snapper = ClusterSnapshotter(store, NAMESPACE,
                                 ["backend", FLEET_COMPONENT])
    with open(out_path, "a") as f:
        while True:
            row: Dict[str, Any] = {"t": time.time()}
            for name, coro in (("planner", collector.collect),
                               ("snapshot", snapper.collect)):
                t0 = time.monotonic()
                try:
                    await coro()
                    row[name] = time.monotonic() - t0
                except Exception:
                    row[name] = None
                    log.debug("%s probe tick failed", name,
                              exc_info=True)
            row["source"] = collector.last_source
            f.write(json.dumps(row) + "\n")
            f.flush()
            await asyncio.sleep(interval)


# ---------------------------------------------------------------------------
# the ramp
# ---------------------------------------------------------------------------
async def run_soak(a, logdir: str) -> Dict[str, Any]:
    from chaos_soak import Procs, _free_port

    from dynamo_tpu.runtime.scale.shards import make_store_client
    from dynamo_tpu.utils.prometheus import stage_metrics

    os.environ["DYN_TRACE_SAMPLE"] = str(a.trace_sample)
    os.environ["DYN_METRICS_PUSH_INTERVAL"] = "0"
    os.environ["DYN_SLO_TTFT_P90"] = "0.5"
    store_port = _free_port()
    # shard plan: extra dynstore procs + the DYN_STORE_SHARDS map every
    # process (driver, synthetic workers, aggregators, serving procs)
    # resolves through make_store_client
    shard_ports = [_free_port() for _ in range(max(a.shards, 1) - 1)]
    shard_map = ""
    if shard_ports:
        entries = [f"telemetry=127.0.0.1:{shard_ports[0]}"]
        if len(shard_ports) > 1:
            entries.append(f"traces=127.0.0.1:{shard_ports[1]}")
        shard_map = ";".join(entries)
    os.environ["DYN_STORE_SHARDS"] = shard_map
    procs = Procs(logdir, store_port, namespace=NAMESPACE,
                  worker_extra=["--echo-slots", "8", "--register-model"],
                  env_extra={"DYN_TOKEN_ECHO_DELAY_MS": "10",
                             "DYN_TRACE_SAMPLE": str(a.trace_sample),
                             "DYN_STORE_SHARDS": shard_map})
    await asyncio.to_thread(procs.start_store)
    for i, port in enumerate(shard_ports):
        name = f"store-shard{i + 1}"
        procs.workers[name] = procs._spawn(
            name, "dynamo_tpu.runtime.store_server", "--impl", "python",
            "--host", "127.0.0.1", "--port", str(port))
        await asyncio.to_thread(procs._wait_log, procs.workers[name][1],
                                "dynstore listening", 20,
                                procs.workers[name][0])

    svc = None
    session = None
    fleet: List[SyntheticWorker] = []
    lag_sink: List[float] = []
    ttfts: List[float] = []
    error_req_ids: List[str] = []
    traffic_stats = {"submitted": 0, "ok": 0, "failed": 0}
    tasks: List[asyncio.Task] = []
    pending: set = set()
    steps_out: List[Dict[str, Any]] = []

    store = await make_store_client("127.0.0.1", store_port).connect()
    probe_proc = None
    probe_log = None

    try:
        # hier mode: the regional aggregator daemons ARE the observer
        # tree; the collectors below read their region records instead
        # of the flat per-worker scrape
        if a.mode == "hier":
            for i in range(max(a.aggregators, 1)):
                name = f"aggregator{i}"
                procs.workers[name] = procs._spawn(
                    name, "dynamo_tpu.cli.aggregator",
                    "--store", f"127.0.0.1:{store_port}",
                    "--namespace", NAMESPACE,
                    "--interval", str(min(a.beat_interval, 2.0)))
                await asyncio.to_thread(
                    procs._wait_log, procs.workers[name][1],
                    "regional aggregator serving", 30,
                    procs.workers[name][0])
        base = None
        if a.traffic_rps > 0:
            import aiohttp

            from dynamo_tpu.cli.http import run_http

            for _ in range(a.real_workers):
                await asyncio.to_thread(procs.start_worker)
            # the kv-router as its own process: routed traffic crosses it
            procs.workers["router"] = procs._spawn(
                "router", "dynamo_tpu.cli.router",
                "--store", f"127.0.0.1:{store_port}",
                "--namespace", NAMESPACE,
                "--worker-component", "backend")
            await asyncio.to_thread(
                procs._wait_log, procs.workers["router"][1],
                "kv router serving", 30, procs.workers["router"][0])
            http_args = argparse.Namespace(
                store=f"127.0.0.1:{store_port}", host="127.0.0.1", port=0,
                router_component="router", namespace=NAMESPACE)
            svc = await run_http(http_args)
            base = f"http://127.0.0.1:{svc.port}"
            session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=0))
            for _ in range(100):
                async with session.get(f"{base}/v1/models") as r:
                    d = await r.json()
                if any(m["id"] == "echo" for m in d.get("data", [])):
                    break
                await asyncio.sleep(0.2)
            else:
                raise RuntimeError("echo model never appeared")

        # observers: the planner signal collector and the dyntop/SLO
        # snapshotter scrape the whole fleet; their latency is data.
        # They run in their OWN process (like the real planner/dyntop
        # daemons) — the driver's event loop is saturated hosting the
        # synthetic fleet, and an in-loop observer would measure that
        # starvation, not the merge path under test.
        import subprocess

        probe_path = os.path.join(logdir, "observer_probe.jsonl")
        probe_log = open(os.path.join(logdir, "observer_probe.log"), "wb")
        probe_proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--observer-probe", "--probe-out", probe_path,
             "--store", f"127.0.0.1:{store_port}"],
            cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
            stdout=probe_log, stderr=subprocess.STDOUT)

        def probe_rows(t0: float, t1: float) -> List[Dict]:
            rows = []
            try:
                with open(probe_path, "r") as f:
                    for line in f:
                        try:
                            r = json.loads(line)
                        except ValueError:
                            continue
                        if t0 <= r.get("t", 0) <= t1:
                            rows.append(r)
            except OSError:
                pass
            return rows

        beacon_seq = {"n": 0}

        async def beacon_loop():
            while True:
                beacon_seq["n"] += 1
                try:
                    await store.put(
                        fleet_beacon_key(NAMESPACE),
                        json.dumps({"seq": beacon_seq["n"],
                                    "t": time.monotonic()}).encode())
                except Exception:
                    log.debug("beacon put failed", exc_info=True)
                await asyncio.sleep(a.beacon_interval)

        async def one_request(error: bool = False) -> None:
            traffic_stats["submitted"] += 1
            body = {"model": "echo", "prompt": "fleet soak replay",
                    "max_tokens": 64 if error else 8, "stream": True}
            headers = {"x-request-timeout": "0.05"} if error \
                else {"x-request-timeout": "10"}
            t0 = time.monotonic()
            try:
                async def call():
                    async with session.post(f"{base}/v1/completions",
                                            json=body,
                                            headers=headers) as r:
                        rid = r.headers.get("x-request-id", "")
                        async for _chunk in r.content.iter_any():
                            if not error:
                                ttfts.append(time.monotonic() - t0)
                            break
                        async for _chunk in r.content.iter_any():
                            pass
                        return r.status, rid
                status, rid = await asyncio.wait_for(call(), 15.0)
                if error:
                    if rid:
                        error_req_ids.append(rid)
                elif status == 200:
                    traffic_stats["ok"] += 1
                else:
                    traffic_stats["failed"] += 1
            except asyncio.TimeoutError:
                traffic_stats["failed"] += 1
            except Exception:  # noqa: BLE001 - transport error == failed
                traffic_stats["failed"] += 1

        async def traffic_loop():
            i = 0
            while True:
                i += 1
                t = asyncio.create_task(one_request(error=(i % 20 == 0)))
                pending.add(t)
                t.add_done_callback(pending.discard)
                await asyncio.sleep(1.0 / a.traffic_rps)

        tasks.append(asyncio.create_task(beacon_loop()))
        if base is not None:
            tasks.append(asyncio.create_task(traffic_loop()))

        stage = stage_metrics()

        def pipeline_counters() -> Dict[str, float]:
            return {
                "pushes_full": stage.metrics_pushes.get("full"),
                "pushes_delta": stage.metrics_pushes.get("delta"),
                "pushes_skipped": stage.metrics_pushes.get("skipped"),
                "spans_sampled_out": stage.spans_sampled_out.get(),
                "spans_dropped": stage.spans_dropped.get(),
            }

        targets = [max(1, round(a.workers * (i + 1) / a.steps))
                   for i in range(a.steps)]
        flows_prev_bytes = 0
        print(f"fleet soak [{a.mode}]: ramp {targets} synthetic workers, "
              f"{a.step_duration}s/step, trace_sample={a.trace_sample}, "
              f"shards={max(a.shards, 1)}"
              + (f", aggregators={a.aggregators}" if a.mode == "hier"
                 else "")
              + f", logs {logdir}", flush=True)

        for target in targets:
            # spawn up to the target in connect bursts of 50
            while len(fleet) < target:
                burst = [SyntheticWorker(
                    len(fleet) + j, "127.0.0.1", store_port, NAMESPACE,
                    lag_sink, beat_interval=a.beat_interval,
                    spans_per_beat=a.spans_per_beat)
                    for j in range(min(50, target - len(fleet)))]
                started = await asyncio.gather(
                    *(w.start() for w in burst), return_exceptions=True)
                for w, r in zip(burst, started):
                    if isinstance(r, BaseException):
                        log.warning("synthetic worker failed to start: "
                                    "%r", r)
                    else:
                        fleet.append(w)
                await asyncio.sleep(0.05)
            await asyncio.sleep(1.0)   # settle: first beats land

            dump0 = await read_store_dump(store)
            pipe0 = pipeline_counters()
            lag_mark = len(lag_sink)
            ttft_mark = len(ttfts)
            spans_mark = sum(w.spans_emitted for w in fleet)
            t_step = time.monotonic()
            t_wall0 = time.time()
            await asyncio.sleep(a.step_duration)
            dt = time.monotonic() - t_step
            step_obs = probe_rows(t_wall0, time.time())
            dump1 = await read_store_dump(store)
            pipe1 = pipeline_counters()

            # byte-flow ledger slice: the fleet's published link table
            # this step (same fold dyntop/ctl/HTTP read). Bytes are
            # lifetime counters, so the step delta is vs the previous
            # step's total; links/congestion are the live view.
            from dynamo_tpu.llm.metrics_aggregator import \
                fetch_stage_states
            from dynamo_tpu.obs.flows import flows_from_states
            flow_links = flows_from_states(
                await fetch_stage_states(store, NAMESPACE))
            flows_total_bytes = sum(e["bytes"] for e in flow_links)
            hottest = flow_links[0] if flow_links else None
            flows_row = {
                "links": len(flow_links),
                "bytes_step": max(
                    0, flows_total_bytes - flows_prev_bytes),
                "congested_links": sum(
                    1 for e in flow_links if e["congested"]),
                "hottest": (f"{hottest['src']}>{hottest['dst']}"
                            if hottest else None),
                "hottest_bw": (round(hottest["bw"], 1)
                               if hottest else None),
                "max_saturation": round(
                    max((e["saturation"] for e in flow_links),
                        default=0.0), 3),
            }
            flows_prev_bytes = flows_total_bytes

            fams, overall = diff_op_families(dump0, dump1)
            total_ops = overall["ops"]
            overall_p99 = overall["p99_s"]
            lags = lag_sink[lag_mark:]
            step_ttfts = ttfts[ttft_mark:]
            traces_fam = fams.get("traces") or {}
            row = {
                "workers": len(fleet),
                "duration_s": round(dt, 2),
                "store": {
                    "ops": total_ops,
                    "op_rate": round(total_ops / dt, 1),
                    "p99_s": overall_p99,
                    "families": fams,
                    "watches": _counter_total(dump1, "dyn_store_watches"),
                    "leases": _counter_total(dump1, "dyn_store_leases"),
                    "fanout_total": _counter_total(
                        dump1, "dyn_store_watch_fanout_total"),
                    "fanout_drops": _counter_total(
                        dump1, "dyn_store_fanout_drops_total"),
                },
                "beacon_lag": {
                    "events": len(lags),
                    "p50_s": _percentile(lags, 0.50),
                    "p99_s": _percentile(lags, 0.99),
                },
                "spans": {
                    "emitted": sum(w.spans_emitted
                                   for w in fleet) - spans_mark,
                    "sampled_out": pipe1["spans_sampled_out"]
                    - pipe0["spans_sampled_out"],
                    "dropped": pipe1["spans_dropped"]
                    - pipe0["spans_dropped"],
                    "store_writes": traces_fam.get("ops", 0),
                    "write_rate": round(
                        traces_fam.get("ops", 0) / dt, 2),
                },
                "metrics": {
                    k: pipe1[k] - pipe0[k]
                    for k in ("pushes_full", "pushes_delta",
                              "pushes_skipped")},
                # per-step slices (like lags/ttfts/spans): cumulative
                # history would let the fast early-step samples mask an
                # observer that slowed down at fleet size. The mode
                # stamp keeps pre/post scale-plane artifacts comparable
                # in one plot; source records which path actually fed
                # the collector this step (hier degrades to flat when
                # every region record is stale).
                "observer": {
                    "mode": a.mode,
                    "source": (step_obs[-1].get("source", "flat")
                               if step_obs else None),
                    "ticks": len(step_obs),
                    "planner_collect_p50_s": _percentile(
                        [r["planner"] for r in step_obs
                         if r.get("planner") is not None], 0.50),
                    "snapshot_p50_s": _percentile(
                        [r["snapshot"] for r in step_obs
                         if r.get("snapshot") is not None], 0.50),
                },
                "traffic": {
                    "ttft_p50_s": _percentile(step_ttfts, 0.50),
                    "ttft_p99_s": _percentile(step_ttfts, 0.99),
                    "requests": len(step_ttfts),
                },
                "flows": flows_row,
            }
            steps_out.append(row)
            print(f"step {len(fleet):>5} workers: "
                  f"store {row['store']['op_rate']:.0f} op/s "
                  f"p99={row['store']['p99_s']} "
                  f"lag_p99={row['beacon_lag']['p99_s']} "
                  f"span_writes/s={row['spans']['write_rate']}",
                  flush=True)

        # error-trace retrievability at the active sample rate
        retr = {"checked": 0, "found": 0}
        sample_ids = [tid for w in fleet[:200]
                      for tid in w.error_trace_ids[:1]][:50]
        from dynamo_tpu.utils.tracing import TRACE_STORE_PREFIX
        for tid in sample_ids:
            retr["checked"] += 1
            if await store.get_prefix(f"{TRACE_STORE_PREFIX}{tid}/"):
                retr["found"] += 1
        http_retr = {"checked": 0, "found": 0}
        if session is not None and base is not None:
            # let the sinks flush the tail
            await asyncio.sleep(1.5)
            for rid in error_req_ids[-20:]:
                http_retr["checked"] += 1
                async with session.get(f"{base}/v1/traces/{rid}") as r:
                    if r.status == 200:
                        d = await r.json()
                        if d.get("spans"):
                            http_retr["found"] += 1

        # watchdog false-positive lane: every serving process in this rig
        # (echo workers, router, aggregators, the in-driver frontend) runs
        # the flight-recorder watchdog, and any stall it fires publishes
        # an incident beacon — a CLEAN soak must end with zero stall
        # incidents. Beacons are the cheap proxy for stall spans: a stall
        # span cannot exist without its beacon (the watchdog triggers the
        # incident plane on every firing).
        from dynamo_tpu.obs.incidents import list_incidents
        beacons = await list_incidents(store, NAMESPACE)
        stall_beacons = [b for b in beacons
                         if str(b.get("reason", "")).startswith("stall_")]
        watchdog_lane = {
            "incident_beacons": len(beacons),
            "stall_incidents": len(stall_beacons),
            "reasons": sorted({b.get("reason", "?") for b in beacons}),
        }

        knee = find_knee(steps_out, a.knee_mult)
        verdicts = {
            "watchdog_clean": not stall_beacons,
            "completed": len(steps_out) == a.steps,
            "curve_non_empty": all(
                s["store"]["ops"] > 0 and s["beacon_lag"]["events"] > 0
                for s in steps_out),
            "error_traces_retrievable": (
                retr["checked"] == 0 or retr["found"] == retr["checked"]),
            "http_error_traces": (
                http_retr["checked"] == 0
                or http_retr["found"] == http_retr["checked"]),
        }
        if a.mode == "hier":
            # the scale-plane exit bar: region records fed the observers
            # and the merge path stayed flat at the biggest step
            last_obs = steps_out[-1]["observer"] if steps_out else {}
            p50 = last_obs.get("planner_collect_p50_s")
            verdicts["observer_region_fed"] = \
                last_obs.get("source") == "region"
            verdicts["observer_p50_flat"] = (p50 is not None
                                             and p50 < 0.5)
        return {
            "config": {k: getattr(a, k) for k in vars(a)},
            "steps": steps_out,
            "knee": knee,
            "error_traces": retr,
            "http_error_traces": http_retr,
            "traffic": traffic_stats,
            "watchdog": watchdog_lane,
            "verdicts": verdicts,
        }
    finally:
        if probe_proc is not None:
            try:
                probe_proc.terminate()
                probe_proc.wait(timeout=5)
            except Exception:
                log.debug("observer probe teardown failed",
                          exc_info=True)
        if probe_log is not None:
            try:
                probe_log.close()
            except Exception:  # noqa: BLE001 - teardown must not mask
                log.debug("probe log close failed", exc_info=True)
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        if pending:
            # let in-flight replay requests reach a terminal state before
            # the frontend goes away (half-written streams just log noise)
            try:
                await asyncio.wait_for(
                    asyncio.gather(*list(pending), return_exceptions=True),
                    20.0)
            except asyncio.TimeoutError:
                for p in list(pending):
                    p.cancel()
        if fleet:
            await asyncio.gather(*(w.stop() for w in fleet),
                                 return_exceptions=True)
        try:
            if session is not None:
                await session.close()
            if svc is not None:
                await svc.stop()
        except Exception:
            log.debug("frontend teardown failed", exc_info=True)
        try:
            await store.close()
        except Exception:
            log.debug("driver store close failed", exc_info=True)
        procs.stop()


# ---------------------------------------------------------------------------
# wake lane: model-mobility swap wake vs cold boot (fleet/mobility/)
# ---------------------------------------------------------------------------
def run_wake_lane(a) -> Dict[str, Any]:
    """Measure the two model-wake paths on a real (tiny, CPU) engine:

    - **cold**: EngineCore construction + safetensors weight load + the
      first compiled token — what a spawn-from-zero wake costs;
    - **swap**: in-place ``hot_swap`` from a warm host
      :class:`WeightCache` + the first token through the REUSED compiled
      programs — what the mobility plane's wake costs.

    Verdicts: swap p50 must beat cold p50 by >= 3x (the PR's acceptance
    floor; on real fleets the gap is larger — cold adds process boot and
    checkpoint download on top) and the compiled-program caches must stay
    flat across every swap (a recompiling swap is a cold boot in
    disguise). Artifact: ``bench_points/model_wake.json``.
    """
    import tempfile as _tempfile

    import jax

    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.engine.loader import (load_llama_params_host,
                                          save_llama_params)
    from dynamo_tpu.fleet.mobility import WeightCache, hot_swap
    from dynamo_tpu.llm.protocols.common import (BackendInput,
                                                 StopConditions)
    from dynamo_tpu.models import llama

    def cfg(path):
        return JaxEngineConfig(
            model=llama.preset("tiny-byte", tie_embeddings=False),
            tp=1, page_size=8, max_batch=4, max_context=128,
            prefill_chunk=32, params_path=path)

    def first_token(core, seq):
        core.submit(seq, BackendInput(
            token_ids=[5, 6, 7, 8], stop=StopConditions(max_tokens=1)))
        for _ in range(500):
            for so in core.step():
                if so.finish is not None:
                    return so.token
        raise RuntimeError("engine produced no token")

    ckpt_dir = _tempfile.mkdtemp(prefix="wake_lane_")
    mcfg = llama.preset("tiny-byte", tie_embeddings=False)
    paths = []
    for i, seed in enumerate((3, 7)):
        p = os.path.join(ckpt_dir, f"ckpt{i}")
        save_llama_params(p, llama.init_params(mcfg, jax.random.PRNGKey(seed)),
                          mcfg)
        paths.append(p)

    # ---- cold lane: ctor + weight load + first compiled token --------
    cold: List[float] = []
    for i in range(a.wake_reps):
        t0 = time.monotonic()
        core = EngineCore(cfg(paths[i % 2]))
        first_token(core, f"cold{i}")
        cold.append(time.monotonic() - t0)
        del core

    # ---- swap lane: warm cache, in-place swap, first token -----------
    cache = WeightCache(capacity_bytes=1 << 30)
    for p in paths:
        cache.put(p, load_llama_params_host(p, mcfg))
    core = EngineCore(cfg(paths[0]))
    first_token(core, "warm")            # incumbent serving, compiles warm
    programs = (len(core._decode_fns), len(core._prefill_batch_fns),
                len(core._verify_fns))
    swap: List[float] = []
    for i in range(a.wake_reps):
        target = paths[(i + 1) % 2]      # alternate between the siblings
        t0 = time.monotonic()
        hot_swap(core, cache.get(target), cfg(target))
        first_token(core, f"swap{i}")
        swap.append(time.monotonic() - t0)
    programs_after = (len(core._decode_fns), len(core._prefill_batch_fns),
                      len(core._verify_fns))
    cache.close()

    cold_p50 = _percentile(cold, 0.50) or 0.0
    swap_p50 = _percentile(swap, 0.50) or 0.0
    result = {
        "bench": "model_wake",
        "reps": a.wake_reps,
        "cold": {"p50_s": round(cold_p50, 4),
                 "samples_s": [round(s, 4) for s in cold]},
        "swap": {"p50_s": round(swap_p50, 4),
                 "samples_s": [round(s, 4) for s in swap]},
        "speedup": round(cold_p50 / swap_p50, 2) if swap_p50 else None,
        "compiled_programs": {"before": list(programs),
                              "after": list(programs_after)},
        "verdicts": {
            "swap_3x_faster": swap_p50 * 3.0 <= cold_p50,
            "programs_flat": programs_after == programs,
        },
    }
    return result


def main(argv=None) -> int:
    from dynamo_tpu.utils.dynconfig import EnvDefaultsParser

    ap = EnvDefaultsParser(prog="fleet_soak")
    ap.add_argument("--workers", type=int, default=600,
                    help="final synthetic-worker count")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--step-duration", type=float, default=8.0)
    ap.add_argument("--beat-interval", type=float, default=2.0,
                    help="synthetic worker metrics/span beat period")
    ap.add_argument("--beacon-interval", type=float, default=0.5)
    ap.add_argument("--spans-per-beat", type=int, default=4)
    ap.add_argument("--trace-sample", type=float, default=0.01,
                    help="DYN_TRACE_SAMPLE armed fleet-wide")
    ap.add_argument("--traffic-rps", type=float, default=4.0,
                    help="replayed traffic through router+frontend "
                         "(0 = store-only soak)")
    ap.add_argument("--real-workers", type=int, default=2,
                    help="echo workers actually serving the traffic")
    ap.add_argument("--knee-mult", type=float, default=4.0)
    ap.add_argument("--mode", choices=("flat", "hier"), default="flat",
                    help="observer path: flat per-worker scrape, or "
                         "hier regional-aggregator tree")
    ap.add_argument("--aggregators", type=int, default=4,
                    help="regional aggregator daemons in hier mode")
    ap.add_argument("--shards", type=int, default=1,
                    help="dynstore processes (2 = telemetry shard, "
                         "3 = + traces shard; DYN_STORE_SHARDS armed "
                         "fleet-wide)")
    ap.add_argument("--wake-lane", action="store_true",
                    help="run the model-mobility wake bench instead of "
                         "the ramp: in-place swap wake vs cold engine "
                         "boot -> bench_points/model_wake.json")
    ap.add_argument("--wake-reps", type=int, default=3,
                    help="wake-lane repetitions per path")
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_points", "fleet_soak.json"))
    # internal probe-mode flags (the driver spawns itself with these)
    ap.add_argument("--observer-probe", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--probe-out", default="", help=argparse.SUPPRESS)
    ap.add_argument("--store", default="127.0.0.1:4222",
                    help=argparse.SUPPRESS)
    a = ap.parse_args(argv)
    if a.observer_probe:
        try:
            asyncio.run(run_observer_probe(a.store, a.probe_out))
        except KeyboardInterrupt:
            pass
        return 0
    if a.wake_lane:
        if a.out == os.path.join(REPO, "bench_points", "fleet_soak.json"):
            a.out = os.path.join(REPO, "bench_points", "model_wake.json")
        result = run_wake_lane(a)
        os.makedirs(os.path.dirname(a.out), exist_ok=True)
        with open(a.out, "w") as f:
            json.dump(result, f, indent=2, sort_keys=True)
        print(json.dumps({"cold_p50_s": result["cold"]["p50_s"],
                          "swap_p50_s": result["swap"]["p50_s"],
                          "speedup": result["speedup"],
                          "verdicts": result["verdicts"]},
                         indent=2, sort_keys=True), flush=True)
        print(f"artifact: {a.out}", flush=True)
        failed = [k for k, ok in result["verdicts"].items() if not ok]
        if failed:
            print(f"FAIL: {failed}", flush=True)
            return 1
        print("PASS: swap wake beats cold boot, programs flat", flush=True)
        return 0
    if a.mode == "hier" and a.out == os.path.join(
            REPO, "bench_points", "fleet_soak.json"):
        # the two modes keep separate artifacts so the before/after
        # curves survive side by side
        a.out = os.path.join(REPO, "bench_points", "fleet_soak_hier.json")
    logdir = tempfile.mkdtemp(prefix="fleet_soak_")
    result = asyncio.run(run_soak(a, logdir))
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(json.dumps({"knee": result["knee"],
                      "error_traces": result["error_traces"],
                      "http_error_traces": result["http_error_traces"],
                      "verdicts": result["verdicts"]},
                     indent=2, sort_keys=True), flush=True)
    print(f"artifact: {a.out}", flush=True)
    failed = [k for k, ok in result["verdicts"].items() if not ok]
    if failed:
        print(f"FAIL: {failed}", flush=True)
        return 1
    print("PASS: ramp completed, curve recorded, error traces "
          "retrievable", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
