#!/usr/bin/env python
"""Flight-recorder/watchdog lane: overhead A/B + injected-stall capture.

Two acceptance bars for the always-on black box, in one artifact
(``bench_points/flightrec_overhead.json``):

1. **Overhead** — the recorder hooks on the engine's dispatch/fetch path
   plus a live watchdog must cost < 1% decode tok/s. Measured on the
   real :class:`EngineCore` (tiny-byte model, CPU) by interleaving
   recorder-off and recorder-on+watchdog repetitions in ONE process
   (same compiled programs, same machine state — the lanes differ only
   in the thing being measured) and comparing median tok/s.
2. **Detection** — an injected decode stall (EWMA path) and a wedged
   transfer (budget path) must each be detected by the watchdog AND
   captured as a coordinated incident bundle through a real dynstore.

    JAX_PLATFORMS=cpu python scripts/flightrec_overhead.py
    ... --reps 3 --requests 8 --max-tokens 48        # the defaults
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the bench is CPU-only; force it before any jax import via the engine
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_core(a):
    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.models import llama

    cfg = JaxEngineConfig(model=llama.preset("tiny-byte"), tp=1,
                          page_size=8, max_batch=a.batch,
                          max_context=256, prefill_chunk=32)
    return EngineCore(cfg)


def _req(i: int, max_tokens: int):
    from dynamo_tpu.llm.protocols.common import (BackendInput,
                                                 StopConditions)

    prompt = [(7 * i + j) % 250 for j in range(16)]
    return BackendInput(token_ids=prompt,
                        stop=StopConditions(max_tokens=max_tokens))


def _run_round(core, a, tag: str):
    """Submit a wave of requests and step the core to completion;
    returns (generated_tokens, wall_seconds)."""
    want = set()
    for i in range(a.requests):
        rid = f"{tag}-{i}"
        core.submit(rid, _req(i, a.max_tokens))
        want.add(rid)
    done = set()
    tokens = 0
    t0 = time.perf_counter()
    while done < want:
        for so in core.step():
            tokens += 1
            if so.finish is not None:
                done.add(so.seq_id)
    return tokens, time.perf_counter() - t0


async def _measure(a):
    from dynamo_tpu.obs import flightrec
    from dynamo_tpu.obs.watchdog import Watchdog

    core = _build_core(a)
    rec = flightrec.flight_recorder()
    # warmup: compile every program + seed the step-time EWMA; a second
    # round flushes post-compile residue out of the first timed lane
    rec.enabled = True
    _run_round(core, a, "warmup")
    _run_round(core, a, "warmup2")

    lanes = {"off": [], "on": []}
    wd = Watchdog(recorder=rec, interval=0.25, enabled=True)
    for rep in range(a.reps):
        # interleaved A/B: drift hits both lanes equally
        rec.enabled = False
        tok, wall = await asyncio.to_thread(_run_round, core, a,
                                            f"off{rep}")
        lanes["off"].append(tok / wall)
        rec.enabled = True
        await wd.start()
        try:
            tok, wall = await asyncio.to_thread(_run_round, core, a,
                                               f"on{rep}")
        finally:
            await wd.stop()
        lanes["on"].append(tok / wall)
        print(f"rep {rep}: off {lanes['off'][-1]:.1f} tok/s   "
              f"on {lanes['on'][-1]:.1f} tok/s", flush=True)
    assert wd.stalls == 0, "clean bench must not fire the watchdog"
    off = statistics.median(lanes["off"])
    on = statistics.median(lanes["on"])
    return {"tok_s_off": lanes["off"], "tok_s_on": lanes["on"],
            "median_off": round(off, 2), "median_on": round(on, 2),
            "overhead_pct": round((off - on) / off * 100.0, 3)}


async def _injected_stalls():
    """Wedge a decode dispatch (EWMA path) and a KV stream (budget path)
    against a REAL store; both must be detected and captured."""
    from dynamo_tpu.obs import incidents as incidents_mod
    from dynamo_tpu.obs.flightrec import FlightRecorder
    from dynamo_tpu.obs.watchdog import Watchdog
    from dynamo_tpu.runtime.store_client import StoreClient
    from dynamo_tpu.runtime.store_server import StoreServer
    from dynamo_tpu.utils.tracing import Tracer

    ns = "flightrec_bench"
    srv = StoreServer()
    port = await srv.start()
    out = {}
    client = mgr = wd = None
    try:
        client = await StoreClient(port=port).connect()
        rec = FlightRecorder("bench_worker", enabled=True)
        tracer = Tracer(component="bench_worker", enabled=True)
        rec.attach(tracer)
        mgr = incidents_mod.IncidentManager(
            client, namespace=ns, component="bench_worker",
            recorder=rec, proc_label="bench_worker:0", ttl=60.0,
            cooldown=0.0, window=30.0)   # cooldown 0: one beacon per stall
        await mgr.start()
        incidents_mod.install_manager(mgr)
        wd = Watchdog(recorder=rec, tracer=tracer, interval=0.05,
                      mult=8.0, floor=0.1, loop_stall=60.0, enabled=True)
        await wd.start()

        # decode stall: seeded EWMA, then a dispatch that never fetches
        rec.hb_begin("engine.decode", stall="decode")
        rec.hb_done("engine.decode", elapsed=0.01)
        rec.hb_begin("engine.decode")
        # wedged transfer: explicit budget, no layer progress
        rec.hb_begin("kv.recv:bench", stall="transfer", budget=0.2,
                     trace_id="bench-rid")

        deadline = time.monotonic() + 15
        beacons = []
        while time.monotonic() < deadline:
            beacons = await incidents_mod.list_incidents(client, ns)
            if {b["reason"] for b in beacons} >= {"stall_decode",
                                                  "stall_transfer"}:
                break
            await asyncio.sleep(0.1)
        for kind in ("decode", "transfer"):
            hit = [b for b in beacons if b["reason"] == f"stall_{kind}"]
            captured = False
            if hit:
                dumps = await client.get_prefix(
                    incidents_mod.incident_dump_prefix(ns, hit[0]["id"]))
                captured = bool(dumps)
            out[f"stall_{kind}"] = {
                "detected": bool(hit), "captured": captured,
                "incident": hit[0]["id"] if hit else None}
        out["stall_spans"] = sorted(
            {s.name for s in tracer.spans_for("bench-rid")}
            | {s.name for s in list(tracer._spans)
               if s.name.startswith("stall:")})
    finally:
        incidents_mod.install_manager(None)
        if wd is not None:
            await wd.stop()
        if mgr is not None:
            await mgr.stop()
        if client is not None:
            await client.close()
        await srv.stop()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flightrec_overhead")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=48)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_points", "flightrec_overhead.json"))
    a = ap.parse_args(argv)

    measured = asyncio.run(_measure(a))
    injected = asyncio.run(_injected_stalls())
    verdicts = {
        "overhead_lt_1pct": measured["overhead_pct"] < 1.0,
        "decode_stall_captured": injected["stall_decode"]["captured"],
        "transfer_stall_captured": injected["stall_transfer"]["captured"],
    }
    result = {
        "config": {k: getattr(a, k) for k in
                   ("reps", "requests", "max_tokens", "batch")},
        "measured": measured,
        "injected": injected,
        "verdicts": verdicts,
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(json.dumps({"overhead_pct": measured["overhead_pct"],
                      "verdicts": verdicts}, indent=2, sort_keys=True))
    print(f"artifact: {a.out}", flush=True)
    failed = [k for k, ok in verdicts.items() if not ok]
    if failed:
        print(f"FAIL: {failed}", flush=True)
        return 1
    print("PASS: watchdog+recorder overhead within budget, injected "
          "stalls detected and captured", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
