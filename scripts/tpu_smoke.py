"""Real-TPU smoke test for the Pallas kernels (ADVICE round-1 #2): compile
and run flash_attention and paged_attention on the attached chip across
batch sizes, checking numerics against the dense XLA reference. The CPU
test suite only exercises interpret mode; Mosaic tiling violations (e.g.
2-D refs with sub-8 block dims at batch > 1) only surface here.

Usage: python scripts/tpu_smoke.py   (exits non-zero on any failure)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def dense_ref(q, k, v, q_pos, k_pos, k_valid, scale=None, softcap=None,
              window=None):
    import jax.numpy as jnp

    if scale is None:
        scale = 1.0 / np.sqrt(q.shape[-1])
    g = q.shape[2] // k.shape[2]
    k = jnp.repeat(k, g, axis=2)
    v = jnp.repeat(v, g, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if softcap is not None:
        s = jnp.tanh(s / softcap) * softcap
    mask = (k_pos[:, None, None, :] <= q_pos[:, None, :, None]) \
        & k_valid[:, None, None, :]
    if window is not None:
        mask = mask & (k_pos[:, None, None, :]
                       > q_pos[:, None, :, None] - window)
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(mask.any(-1, keepdims=True), p, 0.0)
    return jnp.einsum("bhts,bshd->bthd", p, v.astype(jnp.float32))


def main() -> int:
    global jax
    import jax
    import jax.numpy as jnp

    from dynamo_tpu.ops.attention import flash_attention, paged_attention

    dev = jax.devices()[0]
    if dev.platform != "tpu":
        print(f"SKIP: no TPU (platform={dev.platform})")
        return 0
    print(f"device: {dev.device_kind}")
    failures = 0
    try:
        failures = _run_queue(jax, jnp, flash_attention, paged_attention)
    finally:
        # the tunnel can drop mid-run: whatever completed must still be
        # recorded, and the kernel-variant env must not leak
        os.environ.pop("DYNAMO_TPU_PAGED_KERNEL", None)
        _record(dev.device_kind, failures)
    print("PASS" if failures == 0 else f"{failures} FAILURES")
    return 1 if failures else 0


def _run_queue(jax, jnp, flash_attention, paged_attention) -> int:
    failures = 0

    # GQA shape family the engine serves (Llama 1B/8B: G=4)
    Hq, Hkv, Dh = 8, 2, 64
    for B in (1, 4, 8, 32):
        T, S = 128, 256
        key = jax.random.PRNGKey(B)
        kq, kk, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, Hq, Dh), jnp.bfloat16)
        k = jax.random.normal(kk, (B, S, Hkv, Dh), jnp.bfloat16)
        v = jax.random.normal(kv_, (B, S, Hkv, Dh), jnp.bfloat16)
        q_pos = jnp.broadcast_to(jnp.arange(T), (B, T)) + 16
        k_pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        k_valid = k_pos < (T + 16)
        try:
            out = np.asarray(flash_attention(q, k, v, q_pos, k_pos, k_valid,
                                             interpret=False), np.float32)
            ref = np.asarray(dense_ref(q, k, v, q_pos, k_pos, k_valid),
                             np.float32)
            err = np.abs(out - ref).max()
            ok = bool(err < 0.05)
            print(f"flash  B={B:3d}: max_err={err:.4f} {'OK' if ok else 'FAIL'}")
            RESULTS.append({"case": f"flash B={B}", "ok": ok,
                            "max_err": float(err)})
            failures += 0 if ok else 1
        except Exception as e:  # noqa: BLE001
            print(f"flash  B={B:3d}: COMPILE/RUN FAIL: {type(e).__name__}: "
                  f"{str(e)[:200]}")
            RESULTS.append({"case": f"flash B={B}", "ok": False,
                            "error": f"{type(e).__name__}: {str(e)[:200]}"})
            failures += 1

    # Gemma2/3 kernel variants (round 5): sliding window + score softcap +
    # query_pre_attn_scalar are extra Mosaic lowerings (tanh, window mask,
    # clamped block ranges) that only surface on-chip
    gem = dict(scale=1.0 / np.sqrt(24.0), softcap=50.0, window=96)
    for B in (1, 8):
        T, S = 128, 256
        key = jax.random.PRNGKey(40 + B)
        kq, kk, kv_ = jax.random.split(key, 3)
        q = jax.random.normal(kq, (B, T, Hq, Dh), jnp.bfloat16)
        k = jax.random.normal(kk, (B, S, Hkv, Dh), jnp.bfloat16)
        v = jax.random.normal(kv_, (B, S, Hkv, Dh), jnp.bfloat16)
        q_pos = jnp.broadcast_to(jnp.arange(T), (B, T)) + 16
        k_pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        k_valid = k_pos < (T + 16)
        case = f"flash[gemma] B={B}"
        try:
            out = np.asarray(flash_attention(q, k, v, q_pos, k_pos, k_valid,
                                             interpret=False, **gem),
                             np.float32)
            ref = np.asarray(dense_ref(q, k, v, q_pos, k_pos, k_valid,
                                       **gem), np.float32)
            err = np.abs(out - ref).max()
            ok = bool(err < 0.05)
            print(f"{case}: max_err={err:.4f} {'OK' if ok else 'FAIL'}")
            RESULTS.append({"case": case, "ok": ok, "max_err": float(err)})
            failures += 0 if ok else 1
        except Exception as e:  # noqa: BLE001
            print(f"{case}: COMPILE/RUN FAIL: {type(e).__name__}: "
                  f"{str(e)[:200]}")
            RESULTS.append({"case": case, "ok": False,
                            "error": f"{type(e).__name__}: {str(e)[:200]}"})
            failures += 1

    page, P = 64, 8
    for variant in ("dma", "simple"):
        os.environ["DYNAMO_TPU_PAGED_KERNEL"] = variant
        for B in (1, 8, 32):
            case = f"paged[{variant}] B={B:3d}"
            try:
                n_pages = B * P + 1
                key = jax.random.PRNGKey(100 + B)
                kq, kk, kv_ = jax.random.split(key, 3)
                q = jax.random.normal(kq, (B, Hq, Dh), jnp.bfloat16)
                k_pages = jax.random.normal(kk, (Hkv, n_pages, page, Dh),
                                            jnp.bfloat16)
                v_pages = jax.random.normal(kv_, (Hkv, n_pages, page, Dh),
                                            jnp.bfloat16)
                pt = (np.arange(P)[None]
                      + np.arange(B)[:, None] * P + 1).astype(np.int32)
                page_tables = jnp.asarray(pt)
                lengths = jnp.asarray(
                    np.random.RandomState(B).randint(1, P * page, B),
                    jnp.int32)
                out = np.asarray(
                    paged_attention(q, k_pages, v_pages, page_tables,
                                    lengths, interpret=False), np.float32)
                # gather the pages into dense context, reuse the flash ref
                kg = np.asarray(k_pages, np.float32)[:, pt] \
                    .transpose(1, 2, 3, 0, 4).reshape(B, P * page, Hkv, Dh)
                vg = np.asarray(v_pages, np.float32)[:, pt] \
                    .transpose(1, 2, 3, 0, 4).reshape(B, P * page, Hkv, Dh)
                kp = jnp.broadcast_to(jnp.arange(P * page), (B, P * page))
                valid = kp < np.asarray(lengths)[:, None]
                ref = np.asarray(dense_ref(
                    jnp.asarray(q)[:, None],
                    jnp.asarray(kg, jnp.bfloat16),
                    jnp.asarray(vg, jnp.bfloat16),
                    (lengths - 1)[:, None], kp, valid), np.float32)[:, 0]
                err = np.abs(out - ref.reshape(out.shape)).max()
                ok = bool(err < 0.05)
                print(f"{case}: max_err={err:.4f} {'OK' if ok else 'FAIL'}")
                RESULTS.append({"case": case, "ok": ok,
                                "max_err": float(err)})
                failures += 0 if ok else 1
            except Exception as e:  # noqa: BLE001
                print(f"{case}: COMPILE/RUN FAIL: {type(e).__name__}: "
                      f"{str(e)[:200]}")
                RESULTS.append({"case": case, "ok": False,
                                "error": f"{type(e).__name__}: {str(e)[:200]}"})
                failures += 1

    # paged decode with the Gemma variant set: the window clamps the DMA
    # kernel's active block range at BOTH ends (lanes start mid-table) —
    # a prefetch-chain shape the causal cases never exercise
    for variant in ("dma", "simple"):
        os.environ["DYNAMO_TPU_PAGED_KERNEL"] = variant
        for B in (1, 8):
            case = f"paged[{variant}][gemma] B={B}"
            try:
                n_pages = B * P + 1
                key = jax.random.PRNGKey(200 + B)
                kq, kk, kv_ = jax.random.split(key, 3)
                q = jax.random.normal(kq, (B, Hq, Dh), jnp.bfloat16)
                k_pages = jax.random.normal(kk, (Hkv, n_pages, page, Dh),
                                            jnp.bfloat16)
                v_pages = jax.random.normal(kv_, (Hkv, n_pages, page, Dh),
                                            jnp.bfloat16)
                pt = (np.arange(P)[None]
                      + np.arange(B)[:, None] * P + 1).astype(np.int32)
                page_tables = jnp.asarray(pt)
                # lengths straddle the window: some lanes shorter than 96,
                # some spanning several out-of-window pages
                lengths = jnp.asarray(
                    np.random.RandomState(B).randint(1, P * page, B),
                    jnp.int32)
                out = np.asarray(
                    paged_attention(q, k_pages, v_pages, page_tables,
                                    lengths, interpret=False, **gem),
                    np.float32)
                kg = np.asarray(k_pages, np.float32)[:, pt] \
                    .transpose(1, 2, 3, 0, 4).reshape(B, P * page, Hkv, Dh)
                vg = np.asarray(v_pages, np.float32)[:, pt] \
                    .transpose(1, 2, 3, 0, 4).reshape(B, P * page, Hkv, Dh)
                kp = jnp.broadcast_to(jnp.arange(P * page), (B, P * page))
                valid = kp < np.asarray(lengths)[:, None]
                ref = np.asarray(dense_ref(
                    jnp.asarray(q)[:, None],
                    jnp.asarray(kg, jnp.bfloat16),
                    jnp.asarray(vg, jnp.bfloat16),
                    (lengths - 1)[:, None], kp, valid, **gem),
                    np.float32)[:, 0]
                err = np.abs(out - ref.reshape(out.shape)).max()
                ok = bool(err < 0.05)
                print(f"{case}: max_err={err:.4f} {'OK' if ok else 'FAIL'}")
                RESULTS.append({"case": case, "ok": ok,
                                "max_err": float(err)})
                failures += 0 if ok else 1
            except Exception as e:  # noqa: BLE001
                print(f"{case}: COMPILE/RUN FAIL: {type(e).__name__}: "
                      f"{str(e)[:200]}")
                RESULTS.append({"case": case, "ok": False,
                                "error": f"{type(e).__name__}: {str(e)[:200]}"})
                failures += 1
    return failures


RESULTS = []


def _record(device_kind: str, failures: int) -> None:
    """Write the per-round smoke record the judge/driver can read.
    ``failures`` counts completed-and-failed cases; an aborted run is
    visible as pass=False with fewer results than cases."""
    import json
    import time

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "TPU_SMOKE.json")
    with open(path, "w") as f:
        # 4 flash + 2 flash[gemma] + 2x3 paged + 2x2 paged[gemma] cases
        complete = len(RESULTS) >= 16
        json.dump({"device": device_kind, "failures": failures,
                   "pass": failures == 0 and complete,
                   "complete": complete, "when": time.time(),
                   "results": RESULTS}, f, indent=2)
    print(f"recorded -> {path}")


if __name__ == "__main__":
    sys.exit(main())
