#!/usr/bin/env python
"""dynalint — run the repo's static analysis suite.

    python scripts/dynalint.py                     # all rules, full tree
    python scripts/dynalint.py dynamo_tpu/llm/     # per-file rules, subset
    python scripts/dynalint.py --rule lock-discipline --json
    python scripts/dynalint.py --list-rules
    python scripts/dynalint.py --write-baseline    # grandfather current

Exit 1 when any unsuppressed, non-baselined finding (or stale baseline
entry) remains. Suppress inline with ``# dynalint: ok(<rule>) <reason>``;
grandfather pre-existing findings in ``scripts/dynalint_baseline.json``
(every entry needs a one-line justification). See docs/static_analysis.md.

Whole-repo rules (knob-drift, metrics-catalog) reason about two-way sync,
so they always analyze the full default tree; when explicit paths narrow
the scan they are skipped by default (name them with ``--rule`` to run
them anyway — still against the full tree).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from dynamo_tpu.analysis import all_rules, run_lint          # noqa: E402
from dynamo_tpu.analysis import baseline as baseline_mod     # noqa: E402
from dynamo_tpu.analysis.core import Rule                    # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO, "scripts", "dynalint_baseline.json")


def _is_repo_rule(cls) -> bool:
    return cls.check_repo is not Rule.check_repo


def main(argv: List[str]) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: dynamo_tpu/ "
                        "+ scripts/)")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME", help="run only these rules")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered findings as failures too")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(preserves existing reasons)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--verbose", action="store_true",
                   help="also list suppressed/baselined findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for name in sorted(rules):
            kind = "repo" if _is_repo_rule(rules[name]) else "file"
            print(f"{name:22s} [{kind}] {rules[name].description}")
        return 0

    names = args.rule
    if names:
        unknown = [n for n in names if n not in rules]
        if unknown:
            p.error(f"unknown rule(s): {', '.join(unknown)} "
                    f"(--list-rules shows the registry)")
    elif args.paths:
        # narrowed scan: whole-repo rules would misreport two-way sync
        names = sorted(n for n, c in rules.items() if not _is_repo_rule(c))
    else:
        names = sorted(rules)

    # a typo'd path silently green-lighting every violation is the worst
    # possible CI outcome — reject missing paths and empty scans loudly
    for path in args.paths:
        if not os.path.exists(path):
            p.error(f"path does not exist: {path}")
        if os.path.isfile(path) and not path.endswith(".py"):
            p.error(f"not a Python file: {path}")
    if args.write_baseline and args.paths:
        # a subset rewrite would silently delete every entry (and its
        # hand-written reason) for files outside the subset
        p.error("--write-baseline requires a full-tree scan "
                "(drop the explicit paths)")

    baseline_path = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    result = run_lint(paths=[os.path.abspath(x) for x in args.paths] or None,
                      rule_names=names, baseline_path=baseline_path)
    if result.files == 0:
        print(f"error: no Python files found under: "
              f"{', '.join(args.paths)}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # meta findings (reason-less suppressions) are never grandfathered
        real = [f for f in result.findings if f.rule != "suppression"]
        baseline_mod.save(args.baseline, real)
        print(f"wrote {os.path.relpath(args.baseline, REPO)} "
              f"({len(real)} entries) — now justify every reason field")
        return 0

    print(result.to_json() if args.json else
          result.to_text(verbose=args.verbose))
    return 1 if result.failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
