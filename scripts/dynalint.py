#!/usr/bin/env python
"""dynalint — run the repo's static analysis suite.

    python scripts/dynalint.py                     # all rules, full tree
    python scripts/dynalint.py dynamo_tpu/llm/     # per-file rules, subset
    python scripts/dynalint.py --changed           # pre-commit: git diff
    python scripts/dynalint.py --report host-sync  # transfer inventory
    python scripts/dynalint.py --rule lock-discipline --json
    python scripts/dynalint.py --list-rules
    python scripts/dynalint.py --write-baseline    # grandfather current

Exit 1 when any unsuppressed, non-baselined finding (or stale baseline
entry) remains. Suppress inline with ``# dynalint: ok(<rule>) <reason>``;
grandfather pre-existing findings in ``scripts/dynalint_baseline.json``
(every entry needs a one-line justification). See docs/static_analysis.md.

Whole-repo rules (knob-drift, metrics-catalog, store-key-drift,
wire-field-drift) reason about two-way sync, so they always analyze the
full default tree; when explicit paths narrow the scan they are skipped
by default (name them with ``--rule`` to run them anyway — still against
the full tree). ``--changed`` keeps them: per-file rules see only the
files ``git diff`` names (merge-base vs HEAD + worktree), whole-repo
rules keep full-tree semantics — sub-second pre-commit runs with the
drift gates intact.

``--report <rule>`` inventories EVERY site the rule knows — open findings
first, then suppressed (with their reasons) and baselined ones — and
exits 0: for ``host-sync`` this is the documented device->host transfer
budget of the dispatch paths.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from dynamo_tpu.analysis import all_rules, run_lint          # noqa: E402
from dynamo_tpu.analysis import baseline as baseline_mod     # noqa: E402
from dynamo_tpu.analysis.core import Rule                    # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO, "scripts", "dynalint_baseline.json")


def _is_repo_rule(cls) -> bool:
    return cls.check_repo is not Rule.check_repo


def _git(args: List[str]) -> List[str]:
    try:
        out = subprocess.run(["git"] + args, cwd=REPO, check=True,
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return []
    return [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]


def changed_files() -> Optional[List[str]]:
    """Changed ``.py`` files under the default roots: merge-base vs HEAD
    plus worktree/index plus untracked. None when git is unavailable."""
    if not _git(["rev-parse", "--is-inside-work-tree"]):
        return None
    base = "HEAD"
    for upstream in ("@{upstream}", "origin/main", "origin/master"):
        mb = _git(["merge-base", "HEAD", upstream])
        if mb:
            base = mb[0]
            break
    names = set(_git(["diff", "--name-only", base, "HEAD"]))
    names |= set(_git(["diff", "--name-only", "HEAD"]))
    names |= set(_git(["ls-files", "--others", "--exclude-standard"]))
    from dynamo_tpu.analysis.runner import DEFAULT_ROOTS
    roots = tuple(r.rstrip("/") + "/" for r in DEFAULT_ROOTS)
    out = []
    for rel in sorted(names):
        if not rel.endswith(".py") or not rel.startswith(roots):
            continue
        path = os.path.join(REPO, rel)
        if os.path.exists(path):       # deleted files can't be parsed
            out.append(path)
    return out


def _report(rule_name: str, result) -> int:
    """Inventory mode: every site the rule knows, ranked — open findings
    first, then suppressed/baselined dispatch-path sites before the rest."""
    def disp_rank(key: str) -> int:
        low = key.lower()
        for rank, tokens in enumerate((("decode",), ("verify", "spec"),
                                       ("prefill",))):
            if any(t in low for t in tokens):
                return rank
        return 3

    rows = []   # (status_rank, disp_rank, path, line, text)
    for f in result.findings:
        rows.append((0, disp_rank(f.key), f.path, f.line,
                     f"OPEN       {f.location()}: {f.message}"))
    for f, reason in result.suppressed:
        rows.append((1, disp_rank(f.key), f.path, f.line,
                     f"suppressed {f.location()} [{f.key}] — {reason}"))
    for f in result.grandfathered:
        rows.append((2, disp_rank(f.key), f.path, f.line,
                     f"baselined  {f.location()} [{f.key}]"))
    try:
        print(f"{rule_name} inventory — {len(rows)} site(s) "
              f"({len(result.findings)} open, {len(result.suppressed)} "
              f"suppressed, {len(result.grandfathered)} baselined)")
        for _s, _d, _p, _l, text in sorted(rows):
            print(text)
    except BrokenPipeError:
        # `--report x | head` closing the pipe early is a fine way to read
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


def main(argv: List[str]) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: dynamo_tpu/ "
                        "+ scripts/)")
    p.add_argument("--rule", action="append", default=None,
                   metavar="NAME", help="run only these rules")
    p.add_argument("--changed", action="store_true",
                   help="per-file rules over `git diff` files only "
                        "(merge-base vs HEAD + worktree); whole-repo "
                        "rules keep full-tree semantics")
    p.add_argument("--report", metavar="RULE", default=None,
                   help="inventory mode: print every site RULE knows "
                        "(open + suppressed + baselined), exit 0")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--no-baseline", action="store_true",
                   help="report grandfathered findings as failures too")
    p.add_argument("--write-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "(preserves existing reasons)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--verbose", action="store_true",
                   help="also list suppressed/baselined findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for name in sorted(rules):
            kind = "repo" if _is_repo_rule(rules[name]) else "file"
            print(f"{name:22s} [{kind}] {rules[name].description}")
        return 0

    if args.report is not None:
        if args.report not in rules:
            p.error(f"unknown rule {args.report!r} "
                    f"(--list-rules shows the registry)")
        result = run_lint(rule_names=[args.report],
                          baseline_path=args.baseline)
        return _report(args.report, result)

    names = args.rule
    if names:
        unknown = [n for n in names if n not in rules]
        if unknown:
            p.error(f"unknown rule(s): {', '.join(unknown)} "
                    f"(--list-rules shows the registry)")
    elif args.paths:
        # narrowed scan: whole-repo rules would misreport two-way sync
        names = sorted(n for n, c in rules.items() if not _is_repo_rule(c))
    else:
        names = sorted(rules)

    if args.changed:
        if args.paths:
            p.error("--changed and explicit paths are mutually exclusive")
        changed = changed_files()
        if changed is None:
            p.error("--changed requires a git checkout")
        if not changed:
            print("ok: no changed Python files under dynamo_tpu/ + "
                  "scripts/")
            return 0
        # unlike an explicit path subset, --changed KEEPS the whole-repo
        # rules: the runner feeds them the full default tree anyway, so
        # the drift gates stay sound while per-file rules run sub-second
        args.paths = changed

    # a typo'd path silently green-lighting every violation is the worst
    # possible CI outcome — reject missing paths and empty scans loudly
    for path in args.paths:
        if not os.path.exists(path):
            p.error(f"path does not exist: {path}")
        if os.path.isfile(path) and not path.endswith(".py"):
            p.error(f"not a Python file: {path}")
    if args.write_baseline and args.paths:
        # a subset rewrite would silently delete every entry (and its
        # hand-written reason) for files outside the subset
        p.error("--write-baseline requires a full-tree scan "
                "(drop the explicit paths)")

    baseline_path = None if (args.no_baseline or args.write_baseline) \
        else args.baseline
    result = run_lint(paths=[os.path.abspath(x) for x in args.paths] or None,
                      rule_names=names, baseline_path=baseline_path)
    if result.files == 0:
        print(f"error: no Python files found under: "
              f"{', '.join(args.paths)}", file=sys.stderr)
        return 2

    if args.write_baseline:
        # meta findings (reason-less suppressions) are never grandfathered
        real = [f for f in result.findings if f.rule != "suppression"]
        baseline_mod.save(args.baseline, real)
        print(f"wrote {os.path.relpath(args.baseline, REPO)} "
              f"({len(real)} entries) — now justify every reason field")
        return 0

    print(result.to_json() if args.json else
          result.to_text(verbose=args.verbose))
    return 1 if result.failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
