#!/usr/bin/env python
"""Static check: network awaits in standing async code must be bounded.

Standalone CLI for the ``unbounded-await`` dynalint rule (the logic lives
in ``dynamo_tpu/analysis/rules/unbounded_await.py`` since the gates were
generalized into a framework — see docs/static_analysis.md). Kept as a
thin wrapper so existing muscle memory, CI wiring, and
``tests/test_churn.py::test_no_unbounded_network_awaits`` keep working
unchanged.

    python scripts/check_unbounded_awaits.py [paths...]

Exit 1 on findings. ``# unbounded-ok`` annotations are honored as before
(as is the framework's ``# dynalint: ok(unbounded-await) <reason>``).
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from dynamo_tpu.analysis.core import Module                    # noqa: E402
from dynamo_tpu.analysis.core import iter_python_files         # noqa: E402
from dynamo_tpu.analysis.rules.unbounded_await import (        # noqa: E402
    GUARD_CALLS, LEGACY_SCOPE, NETWORK_CALLS, unbounded_awaits)

__all__ = ["DEFAULT_PATHS", "NETWORK_CALLS", "GUARD_CALLS", "ANNOTATION",
           "check_file", "run", "main"]

DEFAULT_PATHS = [os.path.join(REPO, *rel.split("/")) for rel in LEGACY_SCOPE]
ANNOTATION = "unbounded-ok"


def check_file(path: str) -> List[Tuple[int, str]]:
    """Legacy per-file API: [(lineno, primitive name), ...]."""
    mod = Module(path, repo=REPO)
    # the framework's generic suppression also mutes here, matching what
    # `scripts/dynalint.py` would report
    return [(lineno, name)
            for lineno, name, _fn in unbounded_awaits(mod)
            if not any(r == "unbounded-await"
                       for r, _reason, _l in mod.suppressions_at(lineno))]


def run(paths: List[str]) -> List[str]:
    out: List[str] = []
    for root in paths:
        for path in iter_python_files([root]):
            for lineno, name in check_file(path):
                rel = os.path.relpath(path, REPO)
                out.append(
                    f"{rel}:{lineno}: unbounded network await "
                    f"({name}) — wrap in wait_for()/deadline.wait_for() "
                    f"or annotate '# unbounded-ok: <why bounded>'")
    return out


def main(argv: List[str]) -> int:
    findings = run(argv[1:] or DEFAULT_PATHS)
    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} unbounded network await(s)")
        return 1
    print("ok: no unbounded network awaits")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
