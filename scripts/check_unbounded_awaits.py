#!/usr/bin/env python
"""Static check: network awaits in ``dynamo_tpu/runtime/`` must be bounded.

Every ``await`` of a network primitive (``asyncio.open_connection``, frame/
stream ``read``/``readexactly``, writer ``drain``, queue ``q_pull``) is a
potential hang: if the peer stalls without closing the socket, the coroutine
parks forever and the request above it never reaches a terminal state. This
check walks the runtime layer's ASTs and flags any such await that is

- not wrapped in a ``wait_for`` (``asyncio.wait_for`` or the deadline
  layer's ``deadline.wait_for``), and
- not annotated ``# unbounded-ok`` on the await's line or a contiguous
  comment block directly above it (the annotation asserts the await's
  lifetime is bounded by something else — e.g. an rx loop that lives
  exactly as long as its connection and has a loss path).

Runnable standalone (exit 1 on findings) and as a tier-1 test
(tests/test_churn.py::test_no_unbounded_network_awaits).

    python scripts/check_unbounded_awaits.py [paths...]
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# the planner is a standing control loop over the same store primitives —
# an unbounded await there parks the whole autoscaler, so it is gated too.
# engine/spec.py is gated because it runs ON the engine thread: any await
# (or blocking network read) sneaking into a proposer would stall every
# request in the batch, so the file must stay visibly clean under this gate
DEFAULT_PATHS = [os.path.join(REPO, "dynamo_tpu", "runtime"),
                 os.path.join(REPO, "dynamo_tpu", "planner"),
                 os.path.join(REPO, "dynamo_tpu", "engine", "spec.py"),
                 # goodput plane: roofline runs on the engine thread, the
                 # SLO monitor inside standing daemons (planner, dyntop),
                 # and dyntop itself is a standing store-polling loop —
                 # an unbounded await in any of them parks its owner
                 os.path.join(REPO, "dynamo_tpu", "utils", "roofline.py"),
                 os.path.join(REPO, "dynamo_tpu", "utils", "slo.py"),
                 os.path.join(REPO, "dynamo_tpu", "cli", "dyntop.py"),
                 # overload plane: the admission gate runs inside every
                 # request, the brownout controller inside standing
                 # daemons, and the soak is the harness that must itself
                 # never hang while proving nothing else does
                 os.path.join(REPO, "dynamo_tpu", "utils", "overload.py"),
                 os.path.join(REPO, "scripts", "overload_soak.py")]

# method/function names whose await parks on the network
NETWORK_CALLS = {"open_connection", "readexactly", "read", "drain",
                 "q_pull"}
# enclosing call names that bound the await
GUARD_CALLS = {"wait_for"}
ANNOTATION = "unbounded-ok"


def _call_name(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return ""


def _annotated(lines: List[str], lineno: int) -> bool:
    """True when the await's own line, or the contiguous comment block
    directly above it, carries the ``# unbounded-ok`` annotation."""
    if ANNOTATION in lines[lineno - 1]:
        return True
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        if ANNOTATION in lines[i]:
            return True
        i -= 1
    return False


def check_file(path: str) -> List[Tuple[int, str]]:
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    lines = src.splitlines()
    tree = ast.parse(src, filename=path)
    # parent links, to detect an enclosing wait_for(...) call
    parents = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    findings: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Await):
            continue
        name = _call_name(node.value)
        if name not in NETWORK_CALLS:
            continue
        # guarded: some ancestor expression is a wait_for(...) call
        cur, guarded = node, False
        while cur in parents:
            cur = parents[cur]
            if _call_name(cur) in GUARD_CALLS:
                guarded = True
                break
            if isinstance(cur, (ast.AsyncFunctionDef, ast.FunctionDef)):
                break
        if guarded or _annotated(lines, node.lineno):
            continue
        findings.append((node.lineno, name))
    return findings


def run(paths: List[str]) -> List[str]:
    out: List[str] = []
    for root in paths:
        files = [root] if root.endswith(".py") else [
            os.path.join(dp, fn) for dp, _, fns in os.walk(root)
            for fn in sorted(fns) if fn.endswith(".py")]
        for path in sorted(files):
            for lineno, name in check_file(path):
                rel = os.path.relpath(path, REPO)
                out.append(
                    f"{rel}:{lineno}: unbounded network await "
                    f"({name}) — wrap in wait_for()/deadline.wait_for() "
                    f"or annotate '# unbounded-ok: <why bounded>'")
    return out


def main(argv: List[str]) -> int:
    findings = run(argv[1:] or DEFAULT_PATHS)
    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} unbounded network await(s)")
        return 1
    print("ok: no unbounded network awaits")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
