"""Ablation timing probe for the engine's compiled programs on the attached
accelerator. Times each suspect in isolation to localize the decode/prefill
gap seen in bench.py (VERDICT round 2 item 2).

Under the axon TPU tunnel, block_until_ready can return before execution and
any host fetch costs a full tunnel round trip (~27ms). So every measurement
here (a) forces completion by fetching one scalar of the result, (b) runs the
op N times inside a lax.scan so the per-op cost is (wall - RTT) / N.

Run: python scripts/perf_probe.py [--model llama-3.2-1b] [--batch 32]
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.models import llama

RTT_MS = 0.0


def fetch(out):
    leaf = jax.tree.leaves(out)[0]
    return np.asarray(jax.tree.leaves(out)[0].ravel()[0])


def timeit(fn, *args, reps=3, warmup=1, **kw):
    """Wall ms per call, forcing real completion via a scalar fetch."""
    for _ in range(warmup):
        fetch(fn(*args, **kw))
    t0 = time.perf_counter()
    for _ in range(reps):
        fetch(fn(*args, **kw))
    return (time.perf_counter() - t0) / reps * 1e3


def report(name, ms_call, n_inner):
    per = (ms_call - RTT_MS) / n_inner
    print(f"{name:44s} {ms_call:9.2f} ms/call {per:8.3f} ms/op")
    return per


def main():
    global RTT_MS
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="llama-3.2-1b")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--ctx", type=int, default=512)
    ap.add_argument("--page", type=int, default=64)
    ap.add_argument("--inner", type=int, default=64)
    args = ap.parse_args()

    m = llama.preset(args.model, max_position=2048)
    B, S, page, N = args.batch, args.ctx, args.page, args.inner
    P = S // page
    n_pages = B * P + 1
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})  B={B} S={S} N={N}")

    # tunnel round-trip: trivial dispatch + scalar fetch
    trivial = jax.jit(lambda x: x + 1)
    x0 = jnp.zeros(())
    fetch(trivial(x0))
    t0 = time.perf_counter()
    for _ in range(10):
        fetch(trivial(x0))
    RTT_MS = (time.perf_counter() - t0) / 10 * 1e3
    print(f"tunnel RTT (dispatch+scalar fetch): {RTT_MS:.1f} ms")

    params = jax.device_put(llama.init_params(m, jax.random.PRNGKey(0)))
    nbytes = sum(a.size * a.dtype.itemsize for a in jax.tree.leaves(params))
    k_pool = jnp.zeros((m.num_layers, m.num_kv_heads, n_pages, page,
                        m.head_dim), m.dtype)
    v_pool = jnp.zeros_like(k_pool)
    print(f"params {nbytes/1e9:.2f} GB; kv pools {2*k_pool.size*2/1e9:.2f} GB;"
          f" weights floor ~{nbytes/819e9*1e3:.2f} ms/step")

    tokens = jnp.ones((B,), jnp.int32)
    lengths = jnp.full((B,), S - N - 1, jnp.int32)
    page_tables = jnp.arange(B * P, dtype=jnp.int32).reshape(B, P)

    # --- matmul-only decode step (floor) ---------------------------------
    @jax.jit
    def matmul_only(params, tokens):
        lp = params["layers"]
        def body(x, _):
            h = x
            for l in range(m.num_layers):
                hn = llama.rms_norm(h, lp["ln1"][l], m.rms_eps)
                q = jnp.einsum("btd,dhk->bthk", hn, lp["wq"][l])
                k = jnp.einsum("btd,dhk->bthk", hn, lp["wk"][l])
                v = jnp.einsum("btd,dhk->bthk", hn, lp["wv"][l])
                h = h + jnp.einsum("bthk,hkd->btd", q + k.mean() + v.mean(),
                                   lp["wo"][l])
                h2 = llama.rms_norm(h, lp["ln2"][l], m.rms_eps)
                g = jnp.einsum("btd,df->btf", h2, lp["wg"][l])
                u = jnp.einsum("btd,df->btf", h2, lp["wu"][l])
                h = h + jnp.einsum("btf,fd->btd", jax.nn.silu(g) * u,
                                   lp["wd"][l])
            hf = llama.rms_norm(h, params["final_norm"], m.rms_eps)
            head = (params["embed"].T if m.tie_embeddings
                    else params["lm_head"])
            logits = jnp.einsum("btd,dv->btv", hf, head.astype(hf.dtype))
            return h + logits.mean().astype(h.dtype), ()
        x = params["embed"][tokens][:, None]
        x, _ = jax.lax.scan(body, x, None, length=N)
        return x
    report("matmul-only step (scan)", timeit(matmul_only, params, tokens), N)

    # --- full forward_decode ---------------------------------------------
    for impl in ("pallas", "xla"):
        @jax.jit
        def run_n(params, tokens, k_pool, v_pool, page_tables, lengths):
            def body(carry, _):
                kp, vp, ln = carry
                logits, kp, vp = llama.forward_decode(
                    params, m, tokens, kp, vp, page_tables, ln,
                    attn_impl=impl)
                return (kp, vp, ln + 1), logits[:, 0, 0]
            (kp, vp, ln), outs = jax.lax.scan(
                body, (k_pool, v_pool, lengths), None, length=N)
            return outs
        report(f"forward_decode step [{impl}]",
               timeit(run_n, params, tokens, k_pool, v_pool, page_tables,
                      lengths), N)

    # --- pieces ----------------------------------------------------------
    @jax.jit
    def scatter_only(k_pool, v_pool):
        pos = lengths - 1
        w_page = jnp.take_along_axis(page_tables, (pos // page)[:, None],
                                     axis=1)[:, 0]
        w_off = pos % page
        kk = jnp.ones((B, m.num_kv_heads, m.head_dim), m.dtype)
        def body(carry, _):
            kp, vp = carry
            for l in range(m.num_layers):
                kp = kp.at[l, :, w_page, w_off].set(kk)
                vp = vp.at[l, :, w_page, w_off].set(kk)
            return (kp, vp), ()
        (kp, vp), _ = jax.lax.scan(body, (k_pool, v_pool), None, length=N)
        return kp
    report("pool scatter, all layers", timeit(scatter_only, k_pool, v_pool), N)

    from dynamo_tpu.ops.attention import paged_attention
    q = jnp.ones((B, m.num_heads, m.head_dim), m.dtype)

    # decode attention must stream the whole ATTENDED KV once per step.
    # The kernels read whole pages, so bytes/op counts the pages actually
    # touched: ceil(attended/page) * page tokens. Effective GB/s against
    # that floor localizes the HBM-bandwidth deficit (round-2 probe: ~9%
    # of the chip's 819 GB/s) per kernel VARIANT.
    attended = int(lengths[0])
    touched_tokens = -(-attended // page) * page
    kv_bytes = (B * touched_tokens * m.num_kv_heads * m.head_dim * 2
                * k_pool.dtype.itemsize * m.num_layers)

    def attn_report(ms_per_op):
        if ms_per_op > 0:
            gbs = kv_bytes / (ms_per_op * 1e-3) / 1e9
            print(f"{'':44s}  -> effective {gbs:7.1f} GB/s "
                  f"({kv_bytes/1e6:.1f} MB KV per step, "
                  f"{attended} of {args.ctx} tokens attended)")

    def paged_probe(label):
        @jax.jit
        def paged_only(q, k_pool, v_pool):
            def body(acc, _):
                for l in range(m.num_layers):
                    acc = acc + paged_attention(q, k_pool[l], v_pool[l],
                                                page_tables, lengths)
                return acc, ()
            acc, _ = jax.lax.scan(body, jnp.zeros_like(q), None, length=N)
            return acc
        per = report(f"paged_attention[{label}], all layers",
                     timeit(paged_only, q, k_pool, v_pool), N)
        attn_report(per)

    saved = os.environ.get("DYNAMO_TPU_PAGED_KERNEL")
    saved_ppb = os.environ.get("DYNAMO_TPU_PAGED_PPB")
    # the baseline runs must use the DEFAULT depth, not an inherited knob
    os.environ.pop("DYNAMO_TPU_PAGED_PPB", None)
    try:
        for variant in ("dma", "simple"):
            os.environ["DYNAMO_TPU_PAGED_KERNEL"] = variant
            paged_probe(variant)
        if dev.platform == "tpu":
            # DMA-depth sweep: pages-per-block trades issue-latency
            # amortization against partial-block waste
            os.environ["DYNAMO_TPU_PAGED_KERNEL"] = "dma"
            for ppb in (2, 4, 16):
                if ppb <= P:
                    os.environ["DYNAMO_TPU_PAGED_PPB"] = str(ppb)
                    paged_probe(f"dma ppb={ppb}")
    finally:
        for var, val in (("DYNAMO_TPU_PAGED_KERNEL", saved),
                         ("DYNAMO_TPU_PAGED_PPB", saved_ppb)):
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val

    @jax.jit
    def gather_attend_only(q, k_pool, v_pool):
        t = jnp.arange(S, dtype=jnp.int32)
        rp = jnp.take_along_axis(
            page_tables, jnp.broadcast_to((t // page)[None], (B, S)), axis=1)
        ro = jnp.broadcast_to((t % page)[None], (B, S))
        mask = (t[None] < lengths[:, None])[:, None, :]
        def body(acc, _):
            for l in range(m.num_layers):
                k_ctx = k_pool[l, :, rp, ro]
                v_ctx = v_pool[l, :, rp, ro]
                acc = acc + llama.attend(q[:, None], k_ctx, v_ctx, mask)[:, 0]
            return acc, ()
        acc, _ = jax.lax.scan(body, jnp.zeros_like(q), None, length=N)
        return acc
    report("gather+dense attend, all layers",
           timeit(gather_attend_only, q, k_pool, v_pool), N)

    from dynamo_tpu.engine.sampling import SamplingState, sample
    s = SamplingState.host_init(B)
    logits = jnp.ones((B, m.vocab_size), jnp.float32)

    @jax.jit
    def sample_n(logits, temp, top_p, top_k, key):
        def body(key, _):
            tok, logp, key2 = sample(logits, temp, top_p, top_k, key)
            return key2, tok
        key, toks = jax.lax.scan(body, key, None, length=N)
        return toks
    report("sample", timeit(sample_n, logits, jnp.asarray(s.temperature),
                            jnp.asarray(s.top_p), jnp.asarray(s.top_k),
                            s.key), N)

    # --- prefill chunks --------------------------------------------------
    C = 128
    Sp = 256
    NP = 8
    positions = jnp.arange(C, dtype=jnp.int32)[None]
    read_pos = jnp.arange(Sp, dtype=jnp.int32)[None]
    read_valid = (jnp.arange(Sp) < C)[None]

    for Bp in (1, 4, 8):
        for impl in ("flash", "xla"):
            tk = jnp.ones((Bp, C), jnp.int32)
            pos = jnp.broadcast_to(positions, (Bp, C))
            wi = (jnp.arange(Bp)[:, None] * Sp
                  + jnp.arange(C)[None]).astype(jnp.int32)
            ri = (jnp.arange(Bp)[:, None] * Sp
                  + jnp.arange(Sp)[None]).astype(jnp.int32)
            rp_ = jnp.broadcast_to(read_pos, (Bp, Sp))
            rv = jnp.broadcast_to(read_valid, (Bp, Sp))

            @jax.jit
            def prefill_n(params, tk, k_pool, v_pool):
                def body(carry, _):
                    kp, vp = carry
                    logits, kp, vp = llama.forward(
                        params, m, tk, pos, kp, vp, wi, ri, rp_, rv,
                        attn_impl=impl)
                    return (kp, vp), logits[:, -1, 0]
                (kp, vp), outs = jax.lax.scan(body, (k_pool, v_pool), None,
                                              length=NP)
                return outs
            per = report(f"prefill C={C} B={Bp} [{impl}]",
                         timeit(prefill_n, params, tk, k_pool, v_pool), NP)
            print(f"{'':44s} -> {Bp*C/per*1e3:10.0f} tok/s")


if __name__ == "__main__":
    main()
