"""Trace smoke: loopback disagg stack, one traced request, full timeline.

Launches the dynstore, a disagg decode worker (remote-prefill forced), a
prefill worker, and the discovery HTTP frontend as FOUR separate processes
on 127.0.0.1, sends one streamed chat completion, then asserts:

- ``GET /v1/traces/{x-request-id}`` returns one stitched trace with >= 6
  spans from >= 2 distinct OS processes covering every hop (http:chat ->
  preprocess -> rpc:generate -> prefill.remote_wait -> prefill.queue_wait
  -> prefill.compute -> kv.push -> decode.stream -> sse.egress);
- cross-process parenting holds (prefill.compute under remote_wait);
- ``?format=chrome`` yields well-formed Chrome trace-event JSON;
- the frontend ``/metrics`` merge exposes non-empty ``llm_ttft_seconds``
  and ``llm_kv_transfer_seconds`` histograms for the request.

    python scripts/trace_smoke.py [--timeout 240]

Exit 0 = complete timeline + metrics; on failure, dumps the tail of every
process log. CPU-only (synthetic model, JAX_PLATFORMS=cpu): runnable in CI.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL = "trace-smoke"
ENGINE_ARGS = json.dumps({"max_batch": 2, "max_context": 256,
                          "prefill_chunk": 32, "decode_steps": 4, "seed": 3})
# every hop of the disagg path must appear in the stitched trace
WANT_SPANS = {"http:chat", "preprocess", "rpc:generate",
              "prefill.remote_wait", "prefill.queue_wait",
              "prefill.compute", "kv.push", "decode.stream", "sse.egress"}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url: str, timeout: float = 5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        body = r.read()
    return json.loads(body) if body[:1] in (b"{", b"[") else body.decode()


class Stack:
    """The four loopback processes, logs tee'd to files for failure dumps."""

    def __init__(self, logdir: str):
        self.logdir = logdir
        self.procs = []         # (name, Popen, log path)
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "DYNAMO_TPU_DATAPLANE": "python"}

    def spawn(self, name: str, *argv: str) -> None:
        path = os.path.join(self.logdir, f"{name}.log")
        with open(path, "wb") as log:
            proc = subprocess.Popen(
                [sys.executable, "-m", *argv], cwd=REPO, env=self.env,
                stdout=log, stderr=subprocess.STDOUT)
        self.procs.append((name, proc, path))

    def check_alive(self) -> None:
        for name, proc, _ in self.procs:
            if proc.poll() is not None:
                raise RuntimeError(f"{name} exited rc={proc.returncode}")

    def wait_log(self, name: str, needle: str, deadline: float) -> None:
        path = next(p for n, _, p in self.procs if n == name)
        while time.monotonic() < deadline:
            self.check_alive()
            with open(path, "rb") as f:
                if needle.encode() in f.read():
                    return
            time.sleep(0.25)
        raise RuntimeError(f"{name}: {needle!r} not seen before timeout")

    def dump(self, tail: int = 3000) -> None:
        for name, _, path in self.procs:
            with open(path, "rb") as f:
                body = f.read()[-tail:].decode(errors="replace")
            print(f"\n--- {name} (last {tail}B) ---\n{body}", flush=True)

    def stop(self) -> None:
        for _, proc, _ in reversed(self.procs):
            if proc.poll() is None:
                proc.terminate()
        for _, proc, _ in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def run(stack: Stack, timeout: float) -> int:
    deadline = time.monotonic() + timeout
    store_port, http_port = _free_port(), _free_port()
    store = f"127.0.0.1:{store_port}"
    base = f"http://127.0.0.1:{http_port}"

    stack.spawn("store", "dynamo_tpu.runtime.store_server",
                "--host", "127.0.0.1", "--port", str(store_port))
    stack.wait_log("store", "dynstore listening", deadline)

    # decode worker: max_local_prefill_length=0 forces EVERY prompt through
    # the remote-prefill queue, so one request exercises the whole path
    stack.spawn("decode", "dynamo_tpu.cli.worker", "--engine", "jax",
                "--store", store, "--advertise-host", "127.0.0.1",
                "--model-name", MODEL, "--register-model",
                "--enable-disagg", "--max-local-prefill-length", "0",
                "--max-prefill-queue-size", "4", "--kv-block-size", "8",
                "--metrics-interval", "0.2",
                "--extra-engine-args", ENGINE_ARGS)
    stack.wait_log("decode", "serving", deadline)

    stack.spawn("prefill", "dynamo_tpu.cli.prefill_worker",
                "--store", store, "--advertise-host", "127.0.0.1",
                "--model-name", MODEL, "--kv-block-size", "8",
                "--extra-engine-args", ENGINE_ARGS)
    stack.wait_log("prefill", "prefill worker pulling", deadline)

    stack.spawn("http", "dynamo_tpu.cli.http", "--store", store,
                "--host", "127.0.0.1", "--port", str(http_port))
    stack.wait_log("http", "http frontend", deadline)

    # model discovery
    while True:
        stack.check_alive()
        if time.monotonic() > deadline:
            raise RuntimeError("model never discovered")
        try:
            if any(m["id"] == MODEL
                   for m in _get(base + "/v1/models")["data"]):
                break
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.5)

    # one traced streamed request
    body = json.dumps({
        "model": MODEL, "stream": True, "max_tokens": 6,
        "messages": [{"role": "user", "content":
                      "trace smoke: " + "tell me about latency " * 4}],
        "ext": {"use_raw_prompt": True}}).encode()
    req = urllib.request.Request(
        base + "/v1/chat/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        rid = r.headers["x-request-id"]
        r.read()                     # drain the SSE stream
    print(f"request {rid} served", flush=True)

    # spans flush to the store asynchronously: poll for the full timeline
    spans, names = [], set()
    while time.monotonic() < deadline:
        stack.check_alive()
        data = _get(f"{base}/v1/traces/{rid}")
        spans = data["spans"]
        names = {s["name"] for s in spans}
        if WANT_SPANS <= names:
            break
        time.sleep(0.3)
    missing = WANT_SPANS - names
    assert not missing, f"incomplete timeline, missing {missing}: {names}"
    assert len(spans) >= 6, f"only {len(spans)} spans"
    assert all(s["trace_id"] == rid for s in spans), "foreign trace ids"
    pids = {(s["component"], s["pid"]) for s in spans}
    assert len({p for _, p in pids}) >= 2, f"single-process trace: {pids}"
    by_name = {s["name"]: s for s in spans}
    assert by_name["prefill.compute"]["parent_id"] == \
        by_name["prefill.remote_wait"]["span_id"], "broken x-proc parenting"

    chrome = _get(f"{base}/v1/traces/{rid}?format=chrome")
    events = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert len(events) >= 6, "chrome export lost spans"
    json.dumps(chrome)               # must round-trip as JSON

    # merged stage metrics: TTFT (frontend) + KV transfer (both workers)
    text = ""
    while time.monotonic() < deadline:
        text = _get(base + "/metrics")
        if ("llm_ttft_seconds_count" in text
                and "llm_kv_transfer_seconds_count" in text):
            break
        time.sleep(0.3)
    assert "llm_ttft_seconds_count" in text, "no TTFT histogram"
    assert 'llm_kv_transfer_seconds_count{component="prefill",' \
        'direction="send"}' in text, "no KV-transfer histogram"

    print(f"PASS: {len(spans)} spans across "
          f"{len({p for _, p in pids})} processes "
          f"({', '.join(sorted(c for c, _ in pids))}); "
          f"TTFT + KV-transfer histograms exposed", flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args()
    stack = Stack(tempfile.mkdtemp(prefix="trace_smoke_"))
    print(f"logs: {stack.logdir}", flush=True)
    try:
        return run(stack, args.timeout)
    except Exception as e:
        print(f"FAIL: {e}", flush=True)
        stack.dump()
        return 1
    finally:
        stack.stop()


if __name__ == "__main__":
    sys.exit(main())
