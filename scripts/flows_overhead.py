#!/usr/bin/env python
"""Byte-flow ledger lane: overhead A/B for the always-on accounting.

Acceptance bar for ISSUE-20, written to
``bench_points/flows_overhead.json``: the ledger chokepoint on the
engine's spill/prefetch path must cost < 1% decode tok/s. Measured on
the real :class:`EngineCore` (tiny-byte model, CPU) by interleaving
ledger-off and ledger-on repetitions in ONE process (same compiled
programs, same machine state — the lanes differ only in whether
``record_flow`` accounts) and comparing median tok/s.

The artifact also carries a microbench of the chokepoint itself
(µs per ``record_flow`` with a measured-seconds sample, i.e. the full
path: window bookkeeping + stage metrics + pair EWMA) so a regression
in the accounting hot path is visible even when the engine A/B noise
floor hides it.

    JAX_PLATFORMS=cpu python scripts/flows_overhead.py
    ... --reps 3 --requests 8 --max-tokens 48        # the defaults
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import statistics
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# the bench is CPU-only; force it before any jax import via the engine
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_core(a):
    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.models import llama

    cfg = JaxEngineConfig(model=llama.preset("tiny-byte"), tp=1,
                          page_size=8, max_batch=a.batch,
                          max_context=256, prefill_chunk=32)
    return EngineCore(cfg)


def _req(i: int, max_tokens: int):
    from dynamo_tpu.llm.protocols.common import (BackendInput,
                                                 StopConditions)

    prompt = [(7 * i + j) % 250 for j in range(16)]
    return BackendInput(token_ids=prompt,
                        stop=StopConditions(max_tokens=max_tokens))


def _run_round(core, a, tag: str):
    """Submit a wave of requests and step the core to completion;
    returns (generated_tokens, wall_seconds)."""
    want = set()
    for i in range(a.requests):
        rid = f"{tag}-{i}"
        core.submit(rid, _req(i, a.max_tokens))
        want.add(rid)
    done = set()
    tokens = 0
    t0 = time.perf_counter()
    while done < want:
        for so in core.step():
            tokens += 1
            if so.finish is not None:
                done.add(so.seq_id)
    return tokens, time.perf_counter() - t0


async def _measure(a):
    from dynamo_tpu.obs.flows import flow_ledger

    core = _build_core(a)
    led = flow_ledger()
    # warmup: compile every program; a second round flushes post-compile
    # residue out of the first timed lane
    led.enabled = True
    _run_round(core, a, "warmup")
    _run_round(core, a, "warmup2")

    lanes = {"off": [], "on": []}
    for rep in range(a.reps):
        # interleaved A/B: drift hits both lanes equally
        led.enabled = False
        tok, wall = await asyncio.to_thread(_run_round, core, a,
                                            f"off{rep}")
        lanes["off"].append(tok / wall)
        led.enabled = True
        tok, wall = await asyncio.to_thread(_run_round, core, a,
                                            f"on{rep}")
        lanes["on"].append(tok / wall)
        print(f"rep {rep}: off {lanes['off'][-1]:.1f} tok/s   "
              f"on {lanes['on'][-1]:.1f} tok/s", flush=True)
    off = statistics.median(lanes["off"])
    on = statistics.median(lanes["on"])
    return {"tok_s_off": lanes["off"], "tok_s_on": lanes["on"],
            "median_off": round(off, 2), "median_on": round(on, 2),
            "overhead_pct": round((off - on) / off * 100.0, 3)}


def _record_microbench(n: int = 20000):
    """µs per record_flow on the full accounted path (window + stage
    metrics + pair EWMA feed) vs the disabled early-return."""
    from dynamo_tpu.obs.flows import FlowLedger

    led = FlowLedger(local="bench")
    t0 = time.perf_counter()
    for _ in range(n):
        led.record("disagg_push", 4096, 1e-4, src="bench", dst="peer")
    on_us = (time.perf_counter() - t0) / n * 1e6
    led.enabled = False
    t0 = time.perf_counter()
    for _ in range(n):
        led.record("disagg_push", 4096, 1e-4, src="bench", dst="peer")
    off_us = (time.perf_counter() - t0) / n * 1e6
    return {"n": n, "record_us": round(on_us, 3),
            "disabled_us": round(off_us, 4)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flows_overhead")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--max-tokens", type=int, default=48)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_points", "flows_overhead.json"))
    a = ap.parse_args(argv)

    measured = asyncio.run(_measure(a))
    micro = _record_microbench()
    verdicts = {
        "overhead_lt_1pct": measured["overhead_pct"] < 1.0,
    }
    result = {
        "config": {k: getattr(a, k) for k in
                   ("reps", "requests", "max_tokens", "batch")},
        "measured": measured,
        "record_microbench": micro,
        "verdicts": verdicts,
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(json.dumps({"overhead_pct": measured["overhead_pct"],
                      "record_us": micro["record_us"],
                      "verdicts": verdicts}, indent=2, sort_keys=True))
    print(f"artifact: {a.out}", flush=True)
    failed = [k for k, ok in verdicts.items() if not ok]
    if failed:
        print(f"FAIL: {failed}", flush=True)
        return 1
    print("PASS: byte-flow ledger overhead within budget", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
