#!/bin/bash
# TPU tunnel watcher: probe until the chip answers, then run the full
# validation queue (kernel smoke -> bench -> perf probe) and record
# artifacts. Designed to run detached:
#   setsid bash scripts/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
# The axon tunnel drops for hours at a time; this catches any window.
set -u
cd "$(dirname "$0")/.."

probe() {
    timeout 90 python -c "import jax; d=jax.devices()[0]; \
print(d.platform, d.device_kind)" 2>/dev/null | tail -1
}

echo "$(date -u +%H:%M:%S) tpu_watch: starting"
while true; do
    out=$(probe)
    if echo "$out" | grep -qi tpu; then
        echo "$(date -u +%H:%M:%S) TUNNEL UP: $out"
        failed=0

        echo "$(date -u +%H:%M:%S) running tpu_smoke..."
        timeout 1200 python scripts/tpu_smoke.py 2>&1 | tail -20
        rc=${PIPESTATUS[0]}
        [ "$rc" -ne 0 ] && { echo "tpu_smoke FAILED (rc=$rc)"; failed=1; }

        # probe BEFORE bench: if the window closes early, the per-kernel
        # bandwidth diagnostic is the most actionable artifact (the driver
        # re-runs bench.py itself at round end anyway)
        echo "$(date -u +%H:%M:%S) running perf_probe..."
        # 1800: the ppb sweep adds two jit-compile+measure cycles; a slow
        # probe must not read as a "real failure" that ends the watch
        timeout 1800 python scripts/perf_probe.py 2>&1 | tee /tmp/perf_probe.log | tail -40
        rc=${PIPESTATUS[0]}
        if [ "$rc" -ne 0 ]; then
            echo "perf_probe FAILED (rc=$rc)"; failed=1
        else
            cp /tmp/perf_probe.log TPU_PERF.log
        fi

        echo "$(date -u +%H:%M:%S) running bench.py..."
        # bench budgets 1500s measurement + up to 300s of backend probes,
        # plus compile time — 2700 leaves room for its final JSON line
        touch /tmp/bench_start_marker
        timeout 2700 python bench.py > /tmp/bench_tpu_out.json \
            2>/tmp/bench_tpu_err.log
        rc=$?
        if [ "$rc" -ne 0 ] || [ ! -s /tmp/bench_tpu_out.json ]; then
            echo "bench FAILED (rc=$rc); stderr tail:"
            tail -c 1000 /tmp/bench_tpu_err.log
            # bench flushes BENCH_PARTIAL.json after every (model,batch)
            # point: a wedge mid-sweep still leaves the measured points as
            # the round's on-chip artifact (round-4 lesson)
            # only a partial written by THIS bench invocation (newer than
            # the start marker) may be salvaged — never a stale leftover
            if [ -s BENCH_PARTIAL.json ] && \
               [ BENCH_PARTIAL.json -nt /tmp/bench_start_marker ] && \
               grep -q '"platform": "tpu"' BENCH_PARTIAL.json; then
                cp BENCH_PARTIAL.json TPU_BENCH.json
                echo "salvaged partial on-chip bench -> TPU_BENCH.json"
            fi
            failed=1
        else
            # deposit in the repo so the window's result survives as a
            # round artifact even if nobody is watching the log
            cp /tmp/bench_tpu_out.json TPU_BENCH.json
            tail -c 2000 /tmp/bench_tpu_out.json
            echo
        fi

        if [ "$failed" -ne 0 ]; then
            # disambiguate: if the tunnel is GONE the failure was the drop
            # — keep watching and retry the queue on the next window. If
            # the chip still answers, the failure is real (e.g. Mosaic
            # rejects a kernel): exit nonzero, don't burn TPU windows
            # re-running an 80-minute queue forever.
            if echo "$(probe)" | grep -qi tpu; then
                echo "$(date -u +%H:%M:%S) queue FAILED with tunnel up -> real failure"
                exit 1
            fi
            echo "$(date -u +%H:%M:%S) queue FAILED (tunnel dropped); resuming watch"
            sleep 300
            continue
        fi
        echo "$(date -u +%H:%M:%S) queue complete: all stages passed"
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) tunnel down ($out)"
    sleep 300
done
