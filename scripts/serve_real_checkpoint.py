"""Serve a REAL pretrained checkpoint end-to-end and verify a completion.

BASELINE config 1/2's correctness half (VERDICT r4 item #5): sharded
safetensors (or GGUF) -> sharded device pytrees -> the in-tree engine ->
OpenAI HTTP -> a pinned greedy completion. This box ships no real
checkpoints (zero egress), so the script is the recorded, runnable recipe
for any host that has one (the TPU VM's HF cache, a mounted model dir):

    python scripts/serve_real_checkpoint.py /path/to/Llama-3.2-1B \
        [--prompt "The capital of France is"] [--expect " Paris"] \
        [--tp 1] [--attn auto] [--max-tokens 16]

Path may be an HF-layout directory (config.json + *.safetensors +
tokenizer.json) or a .gguf file. Exit 0 = loaded, served over HTTP,
completion streamed, and (with --expect) the pinned text matched.
Ref: lib/llm/src/model_card/create.rs:41-143 (from_local_path).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("model_path")
    ap.add_argument("--prompt", default="The capital of France is")
    ap.add_argument("--expect", default=None,
                    help="substring the completion must contain")
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--attn", default="auto")
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-context", type=int, default=2048)
    ap.add_argument("--timeout", type=float, default=900.0,
                    help="server-up timeout (weight load + first compile)")
    args = ap.parse_args()
    # the server subprocess runs with cwd=REPO: a relative model path must
    # resolve against the CALLER's cwd, not the repo
    args.model_path = os.path.abspath(args.model_path)

    port = _free_port()
    ea = {"tp": args.tp, "max_batch": args.max_batch,
          "max_context": args.max_context, "attn_impl": args.attn,
          "decode_steps": 8}
    # loopback only: this is a verification drive, not a deployment — the
    # model must not be reachable from the network for the run's duration
    cmd = [sys.executable, "-m", "dynamo_tpu.cli.run", "in=http", "out=jax",
           "--http-host", "127.0.0.1", "--http-port", str(port),
           "--model-path", args.model_path,
           "--extra-engine-args", json.dumps(ea)]
    print("+", " ".join(cmd), flush=True)
    t0 = time.monotonic()
    proc = subprocess.Popen(cmd, cwd=REPO)
    base = f"http://127.0.0.1:{port}"
    try:
        while True:
            if proc.poll() is not None:
                print(f"FAIL: server exited rc={proc.returncode}")
                return 1
            if time.monotonic() - t0 > args.timeout:
                print("FAIL: server not up within timeout")
                return 1
            try:
                with urllib.request.urlopen(base + "/v1/models",
                                            timeout=2) as r:
                    models = json.load(r)["data"]
                    break
            # dynalint: ok(swallowed-exception) connection refused IS the
            # polled-for condition while the server boots; the enclosing
            # loop times out loudly
            except Exception:
                time.sleep(2)
        model_id = models[0]["id"]
        load_s = time.monotonic() - t0
        print(f"up in {load_s:.1f}s; model={model_id}")

        body = json.dumps({"model": model_id, "prompt": args.prompt,
                           "max_tokens": args.max_tokens,
                           "temperature": 0}).encode()
        t1 = time.monotonic()
        req = urllib.request.Request(
            base + "/v1/completions", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=600) as r:
            out = json.load(r)
        dt = time.monotonic() - t1
        text = out["choices"][0]["text"]
        usage = out.get("usage", {})
        print(json.dumps({
            "model": model_id, "prompt": args.prompt, "completion": text,
            "usage": usage, "load_s": round(load_s, 1),
            "gen_s": round(dt, 2),
            "tok_s": (round(usage.get("completion_tokens", 0) / dt, 1)
                      if dt > 0 else None)}, ensure_ascii=False))
        if args.expect is not None and args.expect not in text:
            print(f"FAIL: expected {args.expect!r} in completion")
            return 1
        print("PASS")
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
