#!/usr/bin/env python
"""Overload soak: open-loop ramp to ~3x capacity; goodput must plateau.

The congestion-collapse experiment the overload-control layer exists to
win. Real store + echo-worker processes (reusing the chaos harness's
process manager) behind an in-process discovery HTTP frontend; an
open-loop driver (arrivals do NOT wait for completions — the only honest
way to model overload) pushes a 50/50 interactive/batch mix through three
phases:

    baseline   (~0.5x capacity)  → measure the pre-overload goodput peak
    overload   (~3x capacity)    → the plane must shed, brown out, plateau
    recovery   (back to 0.5x)    → brownout must step back down

Worker slot gates (``DYN_WORKER_SLOTS``), frontend admission
(``DYN_ADMIT_*``) and the SLO-burn brownout controller are all armed; the
brownout level round-trips the store (controller publishes, the
frontend's watcher applies). PASS iff:

- goodput (requests completed within ``--slo`` seconds per second) over
  the overload steady state stays >= 70% of the pre-overload peak — a
  plateau, not a collapse;
- zero hung requests (every request reaches a terminal state within its
  deadline + slack);
- p99 time-to-rejection of shed (429) requests < 100 ms — shed work must
  not consume deadline budget;
- interactive success rate >= --min-interactive (0.95) while batch
  absorbs the shedding (more batch than interactive rejects);
- the brownout level provably steps up and back down (hysteresis).

Writes the measured phases + verdicts as a bench artifact
(``bench_points/overload_soak.json``).

    JAX_PLATFORMS=cpu python scripts/overload_soak.py

Exit 0 = pass. CPU-only, no model weights; the pytest wrapper is marked
``chaos`` + ``slow`` and excluded from tier-1.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

NAMESPACE = "overload"


def _percentile(values, q: float) -> float:
    if not values:
        return 0.0
    vals = sorted(values)
    idx = min(len(vals) - 1, int(q * len(vals)))
    return vals[idx]


class Recorder:
    """Per-request terminal outcomes, bucketed per second for goodput."""

    def __init__(self, slo_s: float):
        self.slo_s = slo_s
        self.rows = []          # (t, phase, priority, status, latency)
        self.hung = 0

    def add(self, t, phase, priority, status, latency) -> None:
        self.rows.append((t, phase, priority, status, latency))

    def goodput_buckets(self, phase: str):
        """{second_bucket: goodput} over completion times of one phase."""
        buckets = {}
        for t, ph, _pri, status, lat in self.rows:
            if ph != phase:
                continue
            b = int(t + lat)
            buckets.setdefault(b, 0)
            if status == 200 and lat <= self.slo_s:
                buckets[b] += 1
        return buckets

    def phase_stats(self, phase: str):
        rows = [r for r in self.rows if r[1] == phase]
        ok = [r for r in rows if r[3] == 200]
        shed = [r for r in rows if r[3] == 429]
        good = [r for r in ok if r[4] <= self.slo_s]
        out = {
            "submitted": len(rows),
            "ok": len(ok),
            "good": len(good),
            "shed": len(shed),
            "deadline_504": sum(1 for r in rows if r[3] == 504),
            "other": sum(1 for r in rows
                         if r[3] not in (200, 429, 504)),
            "shed_ttr_p99": round(_percentile([r[4] for r in shed], 0.99),
                                  4),
            "latency_p50": round(_percentile([r[4] for r in ok], 0.50), 4),
            "latency_p99": round(_percentile([r[4] for r in ok], 0.99), 4),
        }
        for pri in ("interactive", "batch"):
            rows_p = [r for r in rows if r[2] == pri]
            out[pri] = {
                "submitted": len(rows_p),
                "ok": sum(1 for r in rows_p if r[3] == 200),
                "shed": sum(1 for r in rows_p if r[3] == 429),
            }
        return out


async def run_soak(a, logdir: str):
    from chaos_soak import Procs, _free_port

    import aiohttp

    from dynamo_tpu.cli.http import run_http
    from dynamo_tpu.utils import overload
    from dynamo_tpu.utils.prometheus import stage_metrics

    # capacity of the echo fleet: workers x slots concurrent requests,
    # each costing tokens x per-token delay seconds
    service_s = a.tokens * a.token_delay_ms / 1000.0
    capacity = a.workers * a.slots / service_s
    base_rate = a.base_frac * capacity
    peak_rate = a.overload_mult * capacity
    print(f"overload soak: capacity ~{capacity:.0f} req/s "
          f"(service {service_s * 1000:.0f}ms), baseline {base_rate:.0f}, "
          f"overload {peak_rate:.0f} req/s, logs {logdir}", flush=True)

    # --- knobs, set before any controller/frontend is constructed -------
    worker_env = {
        "DYN_TOKEN_ECHO_DELAY_MS": str(a.token_delay_ms),
        "DYN_WORKER_SLOTS": str(a.slots),
        # deep-ish interactive queue (still << deadline/service), batch
        # refused at a quarter of it: interactive rides out the brownout
        # adaptation window instead of being shed next to batch
        "DYN_WORKER_QUEUE_DEPTH": str(9 * a.slots // 2),
        "DYN_WORKER_BATCH_QUEUE_DEPTH": str(max(a.slots // 2, 1)),
    }
    os.environ["DYN_ADMIT_CONCURRENCY"] = str(a.workers * a.slots * 8)
    os.environ["DYN_ADMIT_QUEUE"] = str(a.workers * a.slots * 4)
    os.environ["DYN_SLO_TTFT_P90"] = str(a.slo_ttft)
    os.environ["DYN_SLO_WINDOWS"] = "5,15"
    os.environ["DYN_BROWNOUT_MAX_TOKENS"] = str(max(a.tokens // 4, 1))
    # ladder capped below shed_all: L1 (shed batch) + L2 (cap tokens)
    # already bring this scenario back inside capacity — survival mode is
    # reserved for the availability-collapse case shedding can't fix, and
    # reaching it here would just mean the dwell gave L2's relief no time
    # to show up in the burn window
    ctrl = overload.BrownoutController(
        up_burn=2.0, down_burn=0.5, dwell_up=a.dwell_up,
        dwell_down=a.dwell_down, max_level=overload.LEVEL_NO_SPEC)

    store_port = _free_port()
    procs = Procs(logdir, store_port, namespace=NAMESPACE,
                  worker_extra=["--echo-slots", str(a.slots),
                                "--register-model"],
                  env_extra=worker_env)
    procs.start_store()
    for _ in range(a.workers):
        procs.start_worker()

    svc = None
    level_track = {"max": 0, "timeline": []}
    rec = Recorder(a.slo)
    pending = set()
    verdicts = {}
    try:
        http_args = argparse.Namespace(
            store=f"127.0.0.1:{store_port}", host="127.0.0.1", port=0,
            router_component=None, namespace=NAMESPACE)
        svc = await run_http(http_args)
        base = f"http://127.0.0.1:{svc.port}"

        # brownout controller: the frontend runs in-process, so the
        # monitor reads its stage registry directly (no publish latency);
        # the LEVEL still round-trips the store — controller publishes,
        # the frontend's watcher applies it
        monitor = overload.BrownoutMonitor(
            svc.store, NAMESPACE, controller=ctrl)

        async def brownout_loop():
            while True:
                states = [("http", stage_metrics().registry.state_dump())]
                lvl = await monitor.tick(states)
                tl = level_track["timeline"]
                if not tl or tl[-1][1] != lvl:
                    tl.append((round(time.monotonic() - t0, 1), lvl))
                    print(f"brownout -> L{lvl} "
                          f"({overload.LEVEL_NAMES[lvl]})", flush=True)
                level_track["max"] = max(level_track["max"], lvl)
                await asyncio.sleep(a.brownout_tick)

        # wait until discovery has the echo model. Unlimited client-side
        # connections: the default 100-connection pool would queue excess
        # requests CLIENT-side and time-to-rejection would measure our own
        # driver's pool, not the server's shed latency
        session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0))
        for _ in range(100):
            async with session.get(f"{base}/v1/models") as r:
                d = await r.json()
                if any(m["id"] == "echo" for m in d.get("data", [])):
                    break
            await asyncio.sleep(0.2)
        else:
            raise RuntimeError("echo model never appeared via discovery")

        # driver + frontend + client share ONE interpreter here (production
        # separates them): a gen-2 GC pause lands in every in-flight
        # request's latency and pollutes the time-to-rejection tail this
        # soak exists to measure. Freeze the warm state and disable the
        # cyclic collector for the measured window (refcounting still
        # frees the per-request garbage; the run is ~a minute).
        import gc

        gc.collect()
        gc.freeze()
        gc.disable()

        t0 = time.monotonic()
        bt = asyncio.create_task(brownout_loop())

        body = {"model": "echo", "prompt": "x" * a.tokens,
                "max_tokens": a.tokens}

        async def one(phase: str, priority: str) -> None:
            sub = time.monotonic()
            status, latency = 0, 0.0
            try:
                async def call():
                    async with session.post(
                            f"{base}/v1/completions", json=body,
                            headers={"x-priority": priority,
                                     "x-request-timeout":
                                         str(a.request_deadline)}) as r:
                        await r.json()
                        return r.status
                status = await asyncio.wait_for(
                    call(), a.request_deadline + 10.0)
            except asyncio.TimeoutError:
                rec.hung += 1
                status = -1
            except Exception:  # noqa: BLE001 - typed transport failure
                status = -2
            latency = time.monotonic() - sub
            rec.add(sub - t0, phase, priority, status, latency)

        async def drive(phase: str, rate: float, duration: float,
                        rate_from: float = None) -> None:
            """Open-loop arrivals at ``rate`` req/s; with ``rate_from``
            the rate ramps linearly over the first ``--ramp-s`` seconds
            (an instantaneous 3x step is a connect storm, not a ramp)."""
            print(f"phase {phase}: {rate:.0f} req/s for {duration:.0f}s",
                  flush=True)
            loop = asyncio.get_event_loop()
            start = loop.time()
            end = start + duration
            next_t = start
            i = 0
            while loop.time() < end:
                r = rate
                if rate_from is not None and a.ramp_s > 0:
                    frac = min((loop.time() - start) / a.ramp_s, 1.0)
                    r = rate_from + (rate - rate_from) * frac
                pri = "interactive" if i % 2 == 0 else "batch"
                i += 1
                t = asyncio.create_task(one(phase, pri))
                pending.add(t)
                t.add_done_callback(pending.discard)
                next_t += 1.0 / r
                delay = next_t - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)

        await drive("baseline", base_rate, a.baseline_s)
        await drive("overload", peak_rate, a.overload_s,
                    rate_from=base_rate)
        await drive("recovery", base_rate, a.recovery_s)

        # every submitted request must reach a terminal state
        if pending:
            await asyncio.wait_for(
                asyncio.gather(*list(pending), return_exceptions=True),
                a.request_deadline + 15.0)
        # let the brownout step the rest of the way down
        settle_end = time.monotonic() + a.settle_s
        while time.monotonic() < settle_end and ctrl.level > 0:
            await asyncio.sleep(0.5)
        bt.cancel()
        await session.close()
        gc.enable()

        # ------------------------------------------------------------------
        base_stats = rec.phase_stats("baseline")
        over_stats = rec.phase_stats("overload")
        rec_stats = rec.phase_stats("recovery")
        base_buckets = rec.goodput_buckets("baseline")
        peak = max(base_buckets.values(), default=0)
        over_buckets = rec.goodput_buckets("overload")
        # steady state: drop the first adaptation seconds of overload
        over_start = min(over_buckets, default=0)
        steady = [v for b, v in sorted(over_buckets.items())
                  if b >= over_start + a.adapt_s]
        steady_goodput = sum(steady) / len(steady) if steady else 0.0

        inter = over_stats["interactive"]
        inter_total = (base_stats["interactive"]["submitted"]
                       + inter["submitted"]
                       + rec_stats["interactive"]["submitted"])
        inter_ok = (base_stats["interactive"]["ok"] + inter["ok"]
                    + rec_stats["interactive"]["ok"])
        inter_rate = inter_ok / inter_total if inter_total else 0.0
        shed_ttrs = [r[4] for r in rec.rows if r[3] == 429]
        ttr_p99 = _percentile(shed_ttrs, 0.99)
        slow_sheds = sorted(
            ((round(r[0], 2), r[2], round(r[4], 3))
             for r in rec.rows if r[3] == 429),
            key=lambda x: -x[2])[:15]
        final_level = ctrl.level

        verdicts = {
            "goodput_plateau": steady_goodput >= 0.7 * peak,
            "zero_hung": rec.hung == 0,
            "shed_ttr_p99_ok": (not shed_ttrs) or ttr_p99 < 0.1,
            "interactive_protected": inter_rate >= a.min_interactive,
            "batch_absorbs": (over_stats["batch"]["shed"]
                              >= over_stats["interactive"]["shed"]),
            "brownout_stepped_up": level_track["max"] >= 1,
            "brownout_stepped_down": final_level < level_track["max"],
        }
        result = {
            "config": {k: getattr(a, k) for k in vars(a)},
            "capacity_req_s": round(capacity, 1),
            "rates": {"baseline": round(base_rate, 1),
                      "overload": round(peak_rate, 1)},
            "baseline": base_stats,
            "overload": over_stats,
            "recovery": rec_stats,
            "goodput": {"baseline_peak": peak,
                        "overload_steady": round(steady_goodput, 2),
                        "ratio": round(steady_goodput / peak, 3)
                        if peak else None},
            "shed_ttr_p99_s": round(ttr_p99, 4),
            # the slowest rejections (t_rel, priority, seconds): a fat
            # tail here means sheds are queueing behind admitted work
            "slow_sheds": slow_sheds,
            "hung": rec.hung,
            "interactive_success": round(inter_rate, 4),
            "brownout": {"max_level": level_track["max"],
                         "final_level": final_level,
                         "timeline": level_track["timeline"][-120:]},
            "verdicts": verdicts,
        }
        return result
    finally:
        try:
            if svc is not None:
                await svc.stop()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdicts dict is already built; procs.stop() below reaps anyway
        except Exception:
            pass
        if not verdicts or not all(verdicts.values()):
            procs.dump()
        procs.stop()


# ---------------------------------------------------------------------------
# mixed-model, multi-tenant lane: two echo models, skewed tenant traffic
# ---------------------------------------------------------------------------
async def run_mixed_model(a, logdir: str):
    """Per-tenant quota isolation under 3x overload, across two models.

    Two echo models (own components, fleet-registered), two tenants:
    ``good`` stays inside its quota, ``hog`` offers 3x its quota. Phases:

        solo    good tenant alone        -> its interactive baseline
        mixed   good + hog at 3x quota   -> isolation must hold

    PASS iff the good tenant's interactive success in the mixed phase is
    not below its solo baseline (beyond epsilon), the hog's overage is
    shed with typed per-tenant 429s, and BOTH models keep serving
    through the storm. Artifact: bench_points/mixed_model_soak.json.
    """
    from chaos_soak import Procs, _free_port

    import aiohttp

    from dynamo_tpu.cli.http import run_http

    service_s = a.tokens * a.token_delay_ms / 1000.0
    per_worker = a.slots / service_s
    good_rate = 0.3 * per_worker            # well inside one worker
    hog_quota = 0.3 * per_worker
    hog_rate = 3.0 * hog_quota              # 3x its own quota
    os.environ["DYN_TENANT_QUOTAS"] = json.dumps({
        "good": {"rps": good_rate * 1.5, "burst": good_rate * 3},
        "hog": {"rps": hog_quota, "burst": hog_quota},
    })
    print(f"mixed-model soak: per-worker capacity ~{per_worker:.0f} req/s, "
          f"good {good_rate:.0f} req/s, hog {hog_rate:.0f} req/s "
          f"(quota {hog_quota:.0f}), logs {logdir}", flush=True)

    store_port = _free_port()
    procs = Procs(logdir, store_port, namespace=NAMESPACE,
                  env_extra={"DYN_TOKEN_ECHO_DELAY_MS":
                             str(a.token_delay_ms),
                             "DYN_WORKER_SLOTS": str(a.slots)})
    procs.start_store()
    models = ("mixa", "mixb")
    for model in models:
        for _ in range(a.workers):
            procs.start_worker(extra=["--component", f"backend-{model}",
                                      "--model-name", model,
                                      "--register-model",
                                      "--echo-slots", str(a.slots)])

    svc = None
    rows = []          # (phase, tenant, model, status, latency)
    pending = set()
    verdicts = {}
    try:
        http_args = argparse.Namespace(
            store=f"127.0.0.1:{store_port}", host="127.0.0.1", port=0,
            router_component=None, namespace=NAMESPACE)
        svc = await run_http(http_args)
        base = f"http://127.0.0.1:{svc.port}"
        session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(limit=0))
        for model in models:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                async with session.get(f"{base}/v1/models") as r:
                    if model in {m["id"]
                                 for m in (await r.json())["data"]}:
                        break
                await asyncio.sleep(0.2)
            else:
                raise RuntimeError(f"{model} never appeared via discovery")

        t0 = time.monotonic()

        async def one(phase, tenant, model):
            sub = time.monotonic()
            status = -2
            try:
                async with session.post(
                        f"{base}/v1/completions",
                        json={"model": model, "prompt": "x" * a.tokens,
                              "max_tokens": a.tokens},
                        headers={"x-tenant": tenant,
                                 "x-priority": "interactive",
                                 "x-request-timeout": "5"}) as r:
                    await r.json()
                    status = r.status
            except Exception:  # noqa: BLE001 - counted as failure
                pass
            rows.append((phase, tenant, model,
                         status, time.monotonic() - sub))

        async def drive(phase, tenant, rate, duration):
            loop = asyncio.get_event_loop()
            end = loop.time() + duration
            next_t = loop.time()
            i = 0
            while loop.time() < end:
                model = models[i % 2]     # tenants spread over models
                i += 1
                t = asyncio.create_task(one(phase, tenant, model))
                pending.add(t)
                t.add_done_callback(pending.discard)
                next_t += 1.0 / rate
                delay = next_t - loop.time()
                if delay > 0:
                    await asyncio.sleep(delay)

        print(f"phase solo: good at {good_rate:.0f} req/s "
              f"for {a.solo_s:.0f}s", flush=True)
        await drive("solo", "good", good_rate, a.solo_s)
        print(f"phase mixed: good {good_rate:.0f} + hog {hog_rate:.0f} "
              f"req/s for {a.mixed_s:.0f}s", flush=True)
        await asyncio.gather(
            drive("mixed", "good", good_rate, a.mixed_s),
            drive("mixed", "hog", hog_rate, a.mixed_s))
        if pending:
            await asyncio.wait_for(
                asyncio.gather(*list(pending), return_exceptions=True),
                20.0)
        await session.close()

        def stats(phase, tenant):
            sel = [r for r in rows if r[0] == phase and r[1] == tenant]
            ok = sum(1 for r in sel if r[3] == 200)
            return {
                "submitted": len(sel), "ok": ok,
                "shed_429": sum(1 for r in sel if r[3] == 429),
                "success": round(ok / len(sel), 4) if sel else None,
                "per_model": {
                    m: {"submitted": sum(1 for r in sel if r[2] == m),
                        "ok": sum(1 for r in sel
                                  if r[2] == m and r[3] == 200)}
                    for m in models},
            }

        solo = stats("solo", "good")
        mixed_good = stats("mixed", "good")
        mixed_hog = stats("mixed", "hog")
        both_served = all(
            mixed_good["per_model"][m]["ok"] > 0 for m in models)
        verdicts = {
            # the acceptance bar: a tenant at 3x its quota cannot push
            # another tenant's interactive success below its solo
            # baseline (epsilon for sampling noise)
            "tenant_isolated": (mixed_good["success"] is not None
                                and solo["success"] is not None
                                and mixed_good["success"]
                                >= solo["success"] - a.isolation_eps),
            "hog_shed_by_quota": mixed_hog["shed_429"] > 0,
            "hog_not_starved": mixed_hog["ok"] > 0,   # quota, not a ban
            "both_models_served": both_served,
        }
        result = {
            "config": {k: getattr(a, k) for k in vars(a)},
            "rates": {"good": round(good_rate, 1),
                      "hog": round(hog_rate, 1),
                      "hog_quota": round(hog_quota, 1)},
            "solo_good": solo,
            "mixed_good": mixed_good,
            "mixed_hog": mixed_hog,
            "verdicts": verdicts,
        }
        return result
    finally:
        try:
            if svc is not None:
                await svc.stop()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdicts dict is already built; procs.stop() below reaps anyway
        except Exception:
            pass
        if not verdicts or not all(verdicts.values()):
            procs.dump()
        procs.stop()
        os.environ.pop("DYN_TENANT_QUOTAS", None)


def main() -> int:
    ap = argparse.ArgumentParser(prog="overload_soak")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--slots", type=int, default=4,
                    help="engine slots per worker (the real capacity)")
    ap.add_argument("--tokens", type=int, default=8)
    ap.add_argument("--token-delay-ms", type=int, default=60)
    ap.add_argument("--base-frac", type=float, default=0.5,
                    help="baseline rate as a fraction of capacity")
    ap.add_argument("--overload-mult", type=float, default=3.0)
    ap.add_argument("--baseline-s", type=float, default=8.0)
    ap.add_argument("--overload-s", type=float, default=18.0)
    ap.add_argument("--recovery-s", type=float, default=12.0)
    ap.add_argument("--settle-s", type=float, default=15.0,
                    help="post-traffic wait for brownout to step down")
    ap.add_argument("--adapt-s", type=float, default=4.0,
                    help="overload seconds excluded from the steady-state "
                         "goodput (the brownout adaptation transient)")
    ap.add_argument("--request-deadline", type=float, default=3.0)
    ap.add_argument("--slo", type=float, default=1.0,
                    help="goodput = completions within this many seconds")
    ap.add_argument("--slo-ttft", type=float, default=0.25,
                    help="DYN_SLO_TTFT_P90 objective driving the brownout")
    ap.add_argument("--ramp-s", type=float, default=2.0,
                    help="seconds over which the overload rate ramps in")
    ap.add_argument("--dwell-up", type=float, default=2.0,
                    help="seconds between brownout up-steps (long enough "
                         "for each level's relief to start landing in "
                         "the burn window before escalating)")
    ap.add_argument("--dwell-down", type=float, default=3.0)
    ap.add_argument("--brownout-tick", type=float, default=0.25)
    ap.add_argument("--min-interactive", type=float, default=0.95)
    ap.add_argument("--mixed-model", action="store_true",
                    help="run the mixed-model multi-tenant isolation "
                         "lane instead of the overload ramp (two echo "
                         "models, one tenant at 3x its quota)")
    ap.add_argument("--solo-s", type=float, default=6.0,
                    help="mixed-model lane: good-tenant-only baseline "
                         "seconds")
    ap.add_argument("--mixed-s", type=float, default=10.0,
                    help="mixed-model lane: good+hog seconds")
    ap.add_argument("--isolation-eps", type=float, default=0.02,
                    help="mixed-model lane: allowed success-rate slack "
                         "vs the solo baseline")
    ap.add_argument("--out", default=None)
    a = ap.parse_args()
    if a.out is None:
        a.out = os.path.join(
            REPO, "bench_points",
            "mixed_model_soak.json" if a.mixed_model
            else "overload_soak.json")
    logdir = tempfile.mkdtemp(prefix="overload_soak_")
    if a.mixed_model:
        result = asyncio.run(run_mixed_model(a, logdir))
    else:
        result = asyncio.run(run_soak(a, logdir))
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "config" and k != "brownout"},
                     indent=2, sort_keys=True), flush=True)
    if not a.mixed_model:
        print(f"brownout: max L{result['brownout']['max_level']}, "
              f"final L{result['brownout']['final_level']}", flush=True)
    print(f"artifact: {a.out}", flush=True)
    failed = [k for k, ok in result["verdicts"].items() if not ok]
    if failed:
        print(f"FAIL: {failed}", flush=True)
        return 1
    print("PASS: " + ("tenant isolation held across models under 3x "
                      "hog overload"
                      if a.mixed_model else
                      "goodput plateaued, sheds fast, interactive "
                      "protected, brownout cycled"), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
