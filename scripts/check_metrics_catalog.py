#!/usr/bin/env python
"""Static check: the metrics catalog in docs/observability.md cannot rot.

Walks every Python file under ``dynamo_tpu/`` and collects metric names
registered through the in-tree registry (``.counter("name", ...)``,
``.gauge(...)``, ``.histogram(...)`` calls with a literal first argument),
then cross-checks them against ``docs/observability.md``:

- every REGISTERED metric name must appear in the doc (inside backticks or
  a table cell — anywhere, literally);
- every metric-shaped token in the doc (``dyn_*`` / ``llm_*`` lowercase
  identifiers, ignoring ``*`` wildcards and the ``_bucket``/``_sum``/
  ``_count`` exposition suffixes of a registered histogram) must be a
  registered metric — documented metrics that no code exports are exactly
  how operators end up alerting on series that never appear.

Runnable standalone (exit 1 on findings) and as a tier-1 test
(tests/test_goodput.py::test_metrics_catalog_in_sync).

    python scripts/check_metrics_catalog.py
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CODE_ROOT = os.path.join(REPO, "dynamo_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")

REGISTER_METHODS = {"counter", "gauge", "histogram"}
# doc tokens that look like metrics: lowercase dyn_/llm_ identifiers
DOC_TOKEN = re.compile(r"\b(?:dyn|llm)_[a-z0-9_]+\b")
# names that appear in docs as env/config rather than metrics never match
# DOC_TOKEN (env knobs are uppercase), so no allowlist is needed today.


def registered_metrics(root: str = CODE_ROOT) -> Dict[str, List[str]]:
    """{metric_name: [file:line, ...]} for every literal registration."""
    out: Dict[str, List[str]] = {}
    for dirpath, _dirs, files in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=path)
            except SyntaxError:
                continue
            # local aliases of a register method (`g = registry.gauge`)
            # register through a bare Name call — resolve them too
            aliases: Set[str] = set()
            for node in ast.walk(tree):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr in REGISTER_METHODS):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                name = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else "")
                if (name not in REGISTER_METHODS and name not in aliases) \
                        or not node.args:
                    continue
                arg0 = node.args[0]
                if isinstance(arg0, ast.Constant) and isinstance(
                        arg0.value, str) and DOC_TOKEN.fullmatch(arg0.value):
                    rel = os.path.relpath(path, REPO)
                    out.setdefault(arg0.value, []).append(
                        f"{rel}:{node.lineno}")
    return out


def documented_tokens(doc: str = DOC) -> Set[str]:
    with open(doc, "r", encoding="utf-8") as f:
        text = f.read()
    # drop wildcard families like `llm_kv_blocks_*`: they are prose
    # shorthand, not catalog entries (the expanded names must still appear)
    text = re.sub(r"\b(?:dyn|llm)_[a-z0-9_]+\*", " ", text)
    return set(DOC_TOKEN.findall(text))


def run() -> List[str]:
    registered = registered_metrics()
    documented = documented_tokens()
    findings: List[str] = []
    for name in sorted(registered):
        if name not in documented:
            where = registered[name][0]
            findings.append(
                f"undocumented metric {name!r} (registered at {where}) — "
                f"add it to docs/observability.md")
    # exposition-format suffixes of registered histograms/counters are
    # legitimate doc tokens (e.g. `llm_ttft_seconds_bucket`)
    expanded = set(registered)
    for name in registered:
        for sfx in ("_bucket", "_sum", "_count", "_total"):
            expanded.add(name + sfx)
    for token in sorted(documented):
        if token not in expanded:
            findings.append(
                f"documented metric {token!r} is not registered anywhere "
                f"under dynamo_tpu/ — stale catalog entry (or a typo)")
    return findings


def main(_argv: List[str]) -> int:
    findings = run()
    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} metrics-catalog finding(s)")
        return 1
    n = len(registered_metrics())
    print(f"ok: {n} registered metrics all documented, catalog clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
