#!/usr/bin/env python
"""Static check: the metrics catalog in docs/observability.md cannot rot.

Standalone CLI for the ``metrics-catalog`` dynalint rule (the logic lives
in ``dynamo_tpu/analysis/rules/metrics_catalog.py`` since the gates were
generalized into a framework — see docs/static_analysis.md). Kept as a
thin wrapper so existing CI wiring and ``tests/test_goodput.py::
test_metrics_catalog_in_sync`` keep working unchanged.

    python scripts/check_metrics_catalog.py

Exit 1 on findings: registered-but-undocumented metrics, and documented-
but-unregistered catalog entries (operators alerting on series that never
appear).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Set

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from dynamo_tpu.analysis.core import Module, iter_python_files  # noqa: E402
from dynamo_tpu.analysis.rules import metrics_catalog as _rule  # noqa: E402

__all__ = ["CODE_ROOT", "DOC", "registered_metrics", "registered_types",
           "documented_tokens", "documented_types", "run", "main"]

CODE_ROOT = os.path.join(REPO, "dynamo_tpu")
DOC = os.path.join(REPO, "docs", "observability.md")


def registered_metrics(root: str = CODE_ROOT) -> Dict[str, List[str]]:
    """{metric_name: [file:line, ...]} for every literal registration."""
    out: Dict[str, List[str]] = {}
    for path in iter_python_files([root]):
        try:
            mod = Module(path, repo=REPO)
        except SyntaxError:
            continue
        for name, sites in _rule.registered_in_module(mod).items():
            out.setdefault(name, []).extend(sites)
    return out


def registered_types(root: str = CODE_ROOT) -> Dict[str, Set[str]]:
    """{metric_name: {register methods}} — the type side of the catalog
    check (``counter``/``gauge``/``histogram``)."""
    out: Dict[str, Set[str]] = {}
    for path in iter_python_files([root]):
        try:
            mod = Module(path, repo=REPO)
        except SyntaxError:
            continue
        for name, kinds in _rule.registered_types_in_module(mod).items():
            out.setdefault(name, set()).update(kinds)
    return out


def documented_tokens(doc: str = DOC) -> Set[str]:
    return _rule.documented_tokens(doc)


def documented_types(doc: str = DOC) -> Dict[str, str]:
    return _rule.documented_types(doc)


def run() -> List[str]:
    findings = _rule.catalog_findings(registered_metrics(),
                                      documented_tokens(),
                                      registered_kinds=registered_types(),
                                      claimed_types=documented_types())
    out: List[str] = []
    for f in findings:
        name = f.key.split(":", 1)[1]
        if f.key.startswith("undocumented:"):
            out.append(
                f"undocumented metric {name!r} (registered at "
                f"{f.path}:{f.line}) — add it to docs/observability.md")
        elif f.key.startswith("type-mismatch:"):
            out.append(f.message)
        else:
            out.append(
                f"documented metric {name!r} is not registered anywhere "
                f"under dynamo_tpu/ — stale catalog entry (or a typo)")
    return out


def main(_argv: List[str]) -> int:
    findings = run()
    for line in findings:
        print(line)
    if findings:
        print(f"\n{len(findings)} metrics-catalog finding(s)")
        return 1
    n = len(registered_metrics())
    print(f"ok: {n} registered metrics all documented, catalog clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
