#!/usr/bin/env python
"""Chaos soak: sustained traffic while the store and workers are kill -9'd.

Launches the dynstore and N echo workers as real OS processes, drives
concurrent request streams through the runtime data plane from this process,
and meanwhile:

- kill -9's random workers and respawns them (membership churn),
- kill -9's the store itself and restarts it on the same port
  (control-plane outage: every client must reconnect and replay its
  session — leases re-granted, endpoints re-registered, watches diffed).

Every request carries an end-to-end deadline and a hang-detection harness
above it. The soak PASSES iff:

- zero hung requests: every submitted request reaches a terminal state
  (stream complete, or a typed error) within its deadline + slack;
- the success rate stays >= --min-success (default 0.9) — requests caught
  mid-stream on a killed worker may fail (typed), everything else must
  route around the churn.

    JAX_PLATFORMS=cpu python scripts/chaos_soak.py [--duration 30]

Exit 0 = pass. CPU-only, no model weights; runnable in CI (the pytest
wrapper is marked ``chaos`` + ``slow`` and excluded from tier-1).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import random
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NAMESPACE = "chaos"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class Procs:
    """Store + worker subprocesses, logs tee'd for failure dumps.
    ``worker_extra`` / ``env_extra`` let other harnesses (the overload
    soak) reuse this with different worker knobs."""

    def __init__(self, logdir: str, store_port: int,
                 namespace: str = NAMESPACE, worker_extra=(),
                 env_extra=None):
        self.logdir = logdir
        self.store_port = store_port
        self.namespace = namespace
        self.worker_extra = list(worker_extra)
        self.env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "DYNAMO_TPU_DATAPLANE": "python",
                    "DYN_TOKEN_ECHO_DELAY_MS": "5",
                    "DYN_STORE_RECONNECT_BASE": "0.05",
                    "DYN_STORE_RECONNECT_ATTEMPTS": "12",
                    **(env_extra or {})}
        self.store = None            # (proc, log path)
        self.workers = {}            # idx -> (proc, log path)
        self._n = 0

    def _spawn(self, name: str, *argv: str):
        path = os.path.join(self.logdir, f"{name}.log")
        log = open(path, "wb")
        proc = subprocess.Popen([sys.executable, "-m", *argv], cwd=REPO,
                                env=self.env, stdout=log,
                                stderr=subprocess.STDOUT)
        return proc, path

    def start_store(self) -> None:
        self.store = self._spawn(
            f"store-{int(time.time() * 1000)}",
            "dynamo_tpu.runtime.store_server", "--impl", "python",
            "--host", "127.0.0.1", "--port", str(self.store_port))
        self._wait_log(self.store[1], "dynstore listening", 20)

    def kill_store(self) -> None:
        self.store[0].send_signal(signal.SIGKILL)
        self.store[0].wait()

    def start_worker(self, extra=()) -> int:
        """``extra`` appends per-worker argv (the mixed-model lanes use
        it to place workers in per-model components)."""
        idx = self._n
        self._n += 1
        self.workers[idx] = self._spawn(
            f"worker{idx}", "dynamo_tpu.cli.worker", "--engine", "echo",
            "--store", f"127.0.0.1:{self.store_port}",
            "--advertise-host", "127.0.0.1",
            "--namespace", self.namespace,
            "--metrics-interval", "0.5", "--echo-slots", "4",
            *self.worker_extra, *extra)
        try:
            self._wait_log(self.workers[idx][1], "serving", 30,
                           proc=self.workers[idx][0])
        except RuntimeError:
            self.workers.pop(idx, None)
            raise
        return idx

    def kill_worker(self, idx: int) -> None:
        proc, _ = self.workers.pop(idx)
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    def _wait_log(self, path: str, needle: str, timeout: float,
                  proc=None) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with open(path, "rb") as f:
                if needle.encode() in f.read():
                    return
            if proc is not None and proc.poll() is not None:
                raise RuntimeError(f"{path}: process exited "
                                   f"rc={proc.returncode} before ready")
            time.sleep(0.2)
        raise RuntimeError(f"{path}: {needle!r} not seen in {timeout}s")

    def dump(self, tail: int = 2500) -> None:
        paths = [self.store[1]] + [p for _, p in self.workers.values()]
        for path in paths:
            try:
                with open(path, "rb") as f:
                    body = f.read()[-tail:].decode(errors="replace")
                print(f"\n--- {os.path.basename(path)} ---\n{body}",
                      flush=True)
            except OSError:
                pass

    def stop(self) -> None:
        procs = [self.store[0]] if self.store else []
        procs += [p for p, _ in self.workers.values()]
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


class Stats:
    def __init__(self):
        self.submitted = 0
        self.ok = 0
        self.typed_failures = 0
        self.hung = 0
        self.failure_kinds = {}
        self.planner_scale_ups = None   # set by the --planner scenario

    def fail(self, kind: str) -> None:
        self.typed_failures += 1
        self.failure_kinds[kind] = self.failure_kinds.get(kind, 0) + 1

    def summary(self) -> str:
        total = self.submitted
        rate = (self.ok / total) if total else 0.0
        return (f"submitted={total} ok={self.ok} typed_failures="
                f"{self.typed_failures} hung={self.hung} "
                f"success={rate:.1%} kinds={self.failure_kinds}")


async def soak(duration: float, n_workers: int, concurrency: int,
               request_deadline: float, min_success: float,
               store_kills: int, logdir: str,
               planner: bool = False) -> Stats:
    from dynamo_tpu.llm.protocols.common import BackendInput
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context, EngineError

    rng = random.Random(11)
    store_port = _free_port()
    procs = Procs(logdir, store_port)
    stats = Stats()
    procs.start_store()
    for _ in range(n_workers):
        procs.start_worker()

    drt = await DistributedRuntime(store_port=store_port,
                                   advertise_host="127.0.0.1").connect()
    client = await (drt.namespace(NAMESPACE).component("backend")
                    .endpoint("generate").client().start())
    await client.wait_for_instances(n_workers, timeout=30)

    plan = None
    if planner:
        # planner-enabled scenario: the autoscaler rides the SAME churn —
        # local connector spawning real echo workers, a mid-run load surge
        # that must scale the pool up, graceful drain back down after
        from dynamo_tpu.planner.connectors import LocalConnector, PoolSpec
        from dynamo_tpu.planner.loop import Planner, PlannerConfig
        from dynamo_tpu.planner.policy import LoadPolicy

        connector = LocalConnector(
            f"127.0.0.1:{store_port}", NAMESPACE,
            {"decode": PoolSpec(component="backend", engine="echo",
                                extra_args=["--echo-slots", "4"],
                                env=dict(procs.env))},
            platform="cpu", logdir=logdir)
        plan = await Planner(
            drt, NAMESPACE, {"decode": "backend"}, LoadPolicy(),
            connector,
            PlannerConfig(interval=1.0, min_replicas=1,
                          max_replicas=n_workers + 3, cooldown_up=3.0,
                          cooldown_down=8.0, down_consensus=2)).start()
        print("chaos: planner enabled (local connector)", flush=True)

    stop_at = time.monotonic() + duration
    payload = BackendInput(token_ids=list(range(1, 9))).to_dict()
    # the surge payload holds a slot ~8x longer, saturating occupancy
    surge_payload = BackendInput(token_ids=list(range(1, 65))).to_dict()
    surge_window = (duration / 3.0, 2.0 * duration / 3.0) if planner \
        else None
    t_start = time.monotonic()

    async def one_request(req=None) -> None:
        stats.submitted += 1
        ctx = Context(deadline=time.time() + request_deadline)

        async def run():
            items = []
            async for item in client.generate(req or payload, ctx):
                items.append(item)
            return items

        try:
            # hang harness: the deadline layer must fire FIRST; tripping
            # this outer wait_for means a request failed to reach a
            # terminal state — the one unforgivable outcome
            await asyncio.wait_for(run(), request_deadline + 10.0)
            stats.ok += 1
        except asyncio.TimeoutError:
            stats.hung += 1
        except EngineError as e:
            stats.fail(f"engine:{e.code}")
        except Exception as e:  # noqa: BLE001 - typed == not hung
            stats.fail(type(e).__name__)

    async def traffic() -> None:
        while time.monotonic() < stop_at:
            n, req = concurrency, None
            if surge_window is not None:
                t = time.monotonic() - t_start
                if surge_window[0] <= t < surge_window[1]:
                    n, req = concurrency * 4, surge_payload
            burst = [asyncio.create_task(one_request(req))
                     for _ in range(n)]
            await asyncio.gather(*burst)
            await asyncio.sleep(0.05)

    async def respawn_worker() -> None:
        # worker startup is seconds; run it off-thread and retry — a spawn
        # landing inside a store outage dies at initial connect
        for _ in range(4):
            try:
                idx = await asyncio.to_thread(procs.start_worker)
                print(f"chaos: spawned worker{idx}", flush=True)
                return
            except RuntimeError as e:
                print(f"chaos: worker spawn failed ({e}); retrying",
                      flush=True)
                await asyncio.sleep(1.0)

    async def churn() -> None:
        # deterministic schedule: 6 evenly spaced chaos events; store
        # kill -9s at fixed slots, worker kill(+background respawn) at the
        # rest. Never kills the LAST worker — total extinction measures
        # respawn latency, not churn-proofness.
        t0 = time.monotonic()
        n_events = 6
        store_slots = {1, 4} if store_kills >= 2 else (
            {2} if store_kills == 1 else set())
        respawns = []
        for i in range(n_events):
            at = duration * (i + 1) / (n_events + 1)
            await asyncio.sleep(max(0.0, t0 + at - time.monotonic()))
            if time.monotonic() >= stop_at:
                break
            if i in store_slots:
                print("chaos: kill -9 store", flush=True)
                procs.kill_store()
                await asyncio.sleep(0.4)
                await asyncio.to_thread(procs.start_store)
                print("chaos: store restarted", flush=True)
            elif len(procs.workers) >= 2:
                victim = rng.choice(list(procs.workers))
                print(f"chaos: kill -9 worker{victim}", flush=True)
                procs.kill_worker(victim)
                respawns.append(asyncio.create_task(respawn_worker()))
        for t in respawns:
            await t

    try:
        await asyncio.gather(traffic(), churn())
        # settle: the live set must converge to the surviving workers
        await asyncio.sleep(1.0)
        live = client.instance_ids()
        print(f"live instances at end: {len(live)} "
              f"(worker procs: {len(procs.workers)})", flush=True)
        if plan is not None:
            ups = sum(1 for d in plan.decisions_log
                      if d.action == "scale_up")
            downs = sum(1 for d in plan.decisions_log
                        if d.action == "scale_down")
            stats.planner_scale_ups = ups
            print(f"planner: {len(plan.decisions_log)} decisions, "
                  f"{ups} scale_up, {downs} scale_down", flush=True)
    finally:
        if plan is not None:
            try:
                await plan.stop()   # drains planner-spawned workers
            # dynalint: ok(swallowed-exception) harness teardown after the
            # verdict is already computed; procs.stop() below reaps anyway
            except Exception:
                pass
        try:
            await drt.close()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdict is already computed; procs.stop() below reaps anyway
        except Exception:
            pass
        ok = (stats.hung == 0 and stats.submitted > 0
              and stats.ok / max(stats.submitted, 1) >= min_success)
        if not ok:
            procs.dump()
        procs.stop()
    return stats


async def model_kill_soak(duration: float, n_workers: int,
                          concurrency: int, request_deadline: float,
                          min_success: float, logdir: str) -> dict:
    """Mixed-model blast-radius scenario: kill an ENTIRE model pool
    mid-traffic; the surviving model's success rate and latency must
    stay flat (model pools share a namespace and a store, nothing else).

    PASS iff model A (survivor): zero hung requests, success >=
    ``min_success`` through the whole run, and post-kill p90 latency
    within 2x its pre-kill p90 (+50ms slack) — the client-side proxy for
    "its SLO burn stays flat". Model B's post-kill failures are the
    point, not a defect (they must be typed, never hangs).
    """
    from dynamo_tpu.llm.protocols.common import BackendInput
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context

    store_port = _free_port()
    procs = Procs(logdir, store_port)
    procs.start_store()
    pools = {"a": [], "b": []}
    for model in pools:
        for _ in range(n_workers):
            pools[model].append(procs.start_worker(
                extra=["--component", f"backend-{model}",
                       "--model-name", f"model{model}",
                       "--register-model"]))

    drt = await DistributedRuntime(store_port=store_port,
                                   advertise_host="127.0.0.1").connect()
    clients = {}
    for model in pools:
        clients[model] = await (
            drt.namespace(NAMESPACE).component(f"backend-{model}")
            .endpoint("generate").client().start())
        await clients[model].wait_for_instances(n_workers, timeout=30)

    rows = {m: [] for m in pools}     # (t_rel, ok, hung, latency)
    payload = BackendInput(token_ids=list(range(1, 9))).to_dict()
    t0 = time.monotonic()
    kill_at = duration / 3.0
    stop_at = t0 + duration

    async def one(model):
        sub = time.monotonic()
        ok, hung = False, False
        ctx = Context(deadline=time.time() + request_deadline)

        async def run():
            async for _ in clients[model].generate(payload, ctx):
                pass

        try:
            await asyncio.wait_for(run(), request_deadline + 10.0)
            ok = True
        except asyncio.TimeoutError:
            hung = True
        except Exception:  # noqa: BLE001 - typed failure == not hung
            pass
        rows[model].append((sub - t0, ok, hung,
                            time.monotonic() - sub))

    async def traffic(model, conc):
        while time.monotonic() < stop_at:
            await asyncio.gather(*[one(model) for _ in range(conc)])
            await asyncio.sleep(0.05)

    async def killer():
        await asyncio.sleep(max(0.0, t0 + kill_at - time.monotonic()))
        print(f"chaos: kill -9 ENTIRE model b pool "
              f"({len(pools['b'])} workers)", flush=True)
        for idx in pools["b"]:
            procs.kill_worker(idx)

    def p90(vals):
        if not vals:
            return 0.0
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(0.9 * len(vals)))]

    verdicts = {}
    try:
        await asyncio.gather(traffic("a", concurrency),
                             traffic("b", max(concurrency // 2, 1)),
                             killer())
        a_rows = rows["a"]
        a_ok = sum(1 for r in a_rows if r[1])
        a_hung = sum(1 for r in a_rows if r[2])
        pre = [r[3] for r in a_rows if r[0] < kill_at and r[1]]
        post = [r[3] for r in a_rows if r[0] >= kill_at and r[1]]
        b_post = [r for r in rows["b"] if r[0] >= kill_at + 1.0]
        verdicts = {
            "survivor_zero_hung": a_hung == 0,
            "survivor_success": (a_ok / max(len(a_rows), 1)
                                 >= min_success),
            "survivor_latency_flat":
                p90(post) <= 2.0 * p90(pre) + 0.05,
            "victim_failures_typed":
                all(not r[2] for r in rows["b"]),
        }
        result = {
            "duration_s": duration,
            "survivor": {"submitted": len(a_rows), "ok": a_ok,
                         "hung": a_hung,
                         "p90_pre_kill_s": round(p90(pre), 4),
                         "p90_post_kill_s": round(p90(post), 4)},
            "victim": {"submitted": len(rows["b"]),
                       "ok": sum(1 for r in rows["b"] if r[1]),
                       "post_kill_ok": sum(1 for r in b_post if r[1]),
                       "hung": sum(1 for r in rows["b"] if r[2])},
            "verdicts": verdicts,
        }
        return result
    finally:
        try:
            await drt.close()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdict is already computed; procs.stop() below reaps anyway
        except Exception:
            pass
        if not verdicts or not all(verdicts.values()):
            procs.dump()
        procs.stop()


async def _midkill_echo_arm(duration: float, n_workers: int,
                            concurrency: int, request_deadline: float,
                            logdir: str, rng) -> dict:
    """Echo arm of the mid-stream-kill soak: waves of concurrent streams
    with a kill -9 landing at a random token index inside each wave. The
    frontend-side resume layer (llm/resume.py) must absorb every break:
    zero client-visible failures, and every stream's token sequence
    byte-identical to the unkilled reference (for echo, the prompt
    itself) — duplicated or dropped tokens across the splice are the
    failure mode under test."""
    from dynamo_tpu.llm.protocols.common import BackendInput
    from dynamo_tpu.llm.remote import RemoteCoreEngine
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.utils.prometheus import stage_metrics

    store_port = _free_port()
    procs = Procs(logdir, store_port)
    procs.start_store()
    for _ in range(n_workers):
        procs.start_worker()

    drt = await DistributedRuntime(store_port=store_port,
                                   advertise_host="127.0.0.1").connect()
    client = await (drt.namespace(NAMESPACE).component("backend")
                    .endpoint("generate").client().start())
    await client.wait_for_instances(n_workers, timeout=30)
    engine = RemoteCoreEngine(client)
    stage = stage_metrics()
    base = {k: stage.stream_resumes.get(k)
            for k in ("resumed", "exhausted", "expired")}

    # 120 tokens at DYN_TOKEN_ECHO_DELAY_MS=5 ~= 0.6s per stream: the
    # kill delay below lands inside the stream, at a random token index
    prompt = list(range(1, 121))
    counts = {"submitted": 0, "ok": 0, "mismatch": 0, "failed": 0,
              "hung": 0}
    kills = 0

    async def one_stream() -> None:
        counts["submitted"] += 1
        req = BackendInput(token_ids=list(prompt))
        ctx = Context(deadline=time.time() + request_deadline)
        got = []

        async def run():
            async for item in engine.generate(req, ctx):
                got.extend(item.token_ids)

        try:
            await asyncio.wait_for(run(), request_deadline + 10.0)
            counts["ok" if got == prompt else "mismatch"] += 1
        except asyncio.TimeoutError:
            counts["hung"] += 1
        except Exception as e:  # noqa: BLE001 - any error is a verdict
            counts["failed"] += 1
            print(f"midkill[echo]: client-visible failure: "
                  f"{type(e).__name__}: {e}", flush=True)

    stop_at = time.monotonic() + duration
    max_waves = max(6, int(duration / 1.2))
    verdicts = {}
    try:
        for _wave in range(max_waves):
            streams = [asyncio.create_task(one_stream())
                       for _ in range(concurrency)]
            # mid-stream, by construction: the streams above are a few
            # to a few-dozen frames in when the SIGKILL lands
            await asyncio.sleep(rng.uniform(0.1, 0.4))
            if len(procs.workers) >= 2:
                victim = rng.choice(list(procs.workers))
                print(f"midkill[echo]: kill -9 worker{victim}", flush=True)
                procs.kill_worker(victim)
                kills += 1
            await asyncio.gather(*streams)
            await asyncio.to_thread(procs.start_worker)
            resumed = stage.stream_resumes.get("resumed") - base["resumed"]
            if time.monotonic() >= stop_at and kills and resumed:
                break
        resumes = {k: stage.stream_resumes.get(k) - base[k]
                   for k in base}
        verdicts = {
            "zero_client_visible_failures":
                counts["failed"] == 0 and counts["hung"] == 0,
            "zero_dup_or_dropped_tokens": counts["mismatch"] == 0,
            "all_streams_completed":
                counts["submitted"] > 0
                and counts["ok"] == counts["submitted"],
            "killed_mid_stream": kills >= 1,
            "streams_resumed": resumes["resumed"] >= 1,
        }
        return {"workers": n_workers, "concurrency": concurrency,
                "stream_tokens": len(prompt), "kills": kills,
                "resume_outcomes": resumes, **counts,
                "verdicts": verdicts}
    finally:
        try:
            await drt.close()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdict is already computed; procs.stop() below reaps anyway
        except Exception:
            pass
        if not verdicts or not all(verdicts.values()):
            procs.dump()
        procs.stop()


async def _midkill_jax_arm(request_deadline: float, logdir: str,
                           rng) -> dict:
    """Donor-alive arm: three real jax (tiny-byte) workers with cluster
    KV sharing on. Worker A runs the unkilled greedy reference (sealing
    prompt+output into its host tier and publishing its cluster registry
    record); the measured stream is pinned to victim B and kill -9'd at
    a random token index; the resume attempt lands on cold worker C with
    A stamped as donor — exactly what the router's post-death donor
    election produces. PASS iff the spliced stream is token-identical to
    A's reference AND the first post-resume frame proves the KV
    re-attach (kv_prefix_hit_tokens >= one page: C held nothing of this
    prompt, so any hit is the cluster fetch, not recompute)."""
    import json as _json

    from dynamo_tpu.llm import resume
    from dynamo_tpu.llm.protocols.common import (BackendInput, EngineOutput,
                                                 StopConditions)
    from dynamo_tpu.runtime.component import DistributedRuntime
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.utils.prometheus import stage_metrics

    ea = {"preset": "tiny-byte", "max_batch": 2, "max_context": 256,
          "prefill_chunk": 32, "page_size": 8, "host_cache_blocks": 64}
    store_port = _free_port()
    procs = Procs(
        logdir, store_port,
        worker_extra=["--engine", "jax",
                      "--extra-engine-args", _json.dumps(ea)],
        env_extra={"DYN_KV_CLUSTER": "1",
                   "DYN_KV_CLUSTER_PUBLISH_INTERVAL": "0.3"})
    procs.start_store()

    drt = await DistributedRuntime(store_port=store_port,
                                   advertise_host="127.0.0.1").connect()
    client = await (drt.namespace(NAMESPACE).component("backend")
                    .endpoint("generate").client().start())
    ids, widx = [], []
    for i in range(3):
        widx.append(await asyncio.to_thread(procs.start_worker))
        await client.wait_for_instances(i + 1, timeout=60)
        ids.append((set(client.instance_ids()) - set(ids)).pop())
    a_id, b_id, c_id = ids

    page = ea["page_size"]
    max_toks = 32
    prompt = [(17 * i + 3) % 251 + 1 for i in range(48)]
    warm_prompt = [(23 * i + 7) % 251 + 1 for i in range(48)]

    def payload(toks):
        return BackendInput(token_ids=list(toks),
                            stop=StopConditions(max_tokens=max_toks,
                                                ignore_eos=True))

    async def direct(req, iid):
        got = []
        ctx = Context(deadline=time.time() + request_deadline)
        async for item in client.generate(req.to_dict(), ctx,
                                          mode="direct", instance_id=iid):
            got.extend(EngineOutput.from_dict(item).token_ids)
        return got

    stage = stage_metrics()
    resumed0 = stage.stream_resumes.get("resumed")
    state = {"killed_at": None, "resume_at": None, "reattach_hit": None,
             "attempts": 0}
    got = []
    verdicts = {}
    try:
        # A's run IS the unkilled greedy reference (params are seed-
        # deterministic across workers) and doubles as the donor warm:
        # prompt+output seal, write-through mirrors them to A's host
        # tier, the registry record publishes under A's lease
        ref_tokens = await direct(payload(prompt), a_id)
        # compile warm B and C with same-bucket content so the measured
        # stream never pauses on a first-request XLA compile
        await direct(payload(warm_prompt), b_id)
        await direct(payload(warm_prompt), c_id)
        await asyncio.sleep(1.5)   # registry publish + metrics beats

        kill_at = rng.randint(6, 16)

        async def dispatch(req, ctx, exclude, attempt, on_instance):
            state["attempts"] = attempt + 1
            if attempt == 0:
                target = b_id
            else:
                target = c_id
                state["resume_at"] = len(got)
                # what the router's post-death donor election stamps in
                # production (route() excludes the dead instance): A is
                # the surviving owner of the sealed prefix
                req.kv_donor = a_id
                req.kv_donor_blocks = len(prompt) // page
            async for item in client.generate(
                    req.to_dict(), ctx, mode="direct", instance_id=target,
                    exclude=exclude, resume=attempt,
                    on_instance=on_instance):
                yield EngineOutput.from_dict(item)

        async def killer():
            while True:
                if len(got) >= kill_at:
                    procs.kill_worker(widx[1])
                    state["killed_at"] = len(got)
                    print(f"midkill[jax]: kill -9 victim at token "
                          f"{len(got)}", flush=True)
                    return
                await asyncio.sleep(0.003)

        ctx = Context(deadline=time.time() + request_deadline)
        ktask = asyncio.create_task(killer())
        try:
            async for item in resume.run(dispatch, payload(prompt), ctx):
                if (state["resume_at"] is not None
                        and state["reattach_hit"] is None):
                    state["reattach_hit"] = item.kv_prefix_hit_tokens or 0
                if item.token_ids:
                    got.extend(item.token_ids)
        finally:
            ktask.cancel()

        resumed = stage.stream_resumes.get("resumed") - resumed0
        hit = state["reattach_hit"] or 0
        verdicts = {
            "reference_complete": len(ref_tokens) == max_toks,
            "killed_mid_stream":
                state["killed_at"] is not None
                and 0 < state["killed_at"] < max_toks,
            "stream_resumed": resumed >= 1 and state["attempts"] >= 2,
            "tokens_identical_to_unkilled_reference": got == ref_tokens,
            # C was cold on this prompt: a >= one-page hit on the resume
            # attempt's admission can only be the cluster re-attach
            "kv_reattach_taken": hit >= page,
        }
        return {"engine": ea, "prompt_tokens": len(prompt),
                "max_tokens": max_toks,
                "killed_at_token": state["killed_at"],
                "resumed_at_token": state["resume_at"],
                "dispatch_attempts": state["attempts"],
                "post_resume_prefix_hit_tokens": hit,
                "reference_tokens": ref_tokens, "stream_tokens": got,
                "verdicts": verdicts}
    finally:
        try:
            await drt.close()
        # dynalint: ok(swallowed-exception) harness teardown after the
        # verdict is already computed; procs.stop() below reaps anyway
        except Exception:
            pass
        if not verdicts or not all(verdicts.values()):
            procs.dump()
        procs.stop()


async def midstream_kill_soak(duration: float, n_workers: int,
                              concurrency: int, request_deadline: float,
                              logdir: str) -> dict:
    """Mid-stream failover soak (docs/robustness.md#resumable-streams):
    kill -9 decode workers at random token indices under live streams.
    The echo arm proves the splice contract at volume; the jax arm
    proves the KV re-attach path on a real engine with a surviving
    donor. Artifact: bench_points/midstream_kill_soak.json."""
    rng = random.Random(23)
    result = {
        "echo_arm": await _midkill_echo_arm(
            duration, n_workers, concurrency, request_deadline,
            logdir, rng),
        # first-touch XLA compiles on CPU dominate the jax arm's warm
        # runs; the measured stream itself finishes in seconds
        "jax_donor_arm": await _midkill_jax_arm(
            max(request_deadline, 120.0), logdir, rng),
    }
    result["verdicts"] = {
        **{f"echo_{k}": v
           for k, v in result["echo_arm"]["verdicts"].items()},
        **{f"jax_{k}": v
           for k, v in result["jax_donor_arm"]["verdicts"].items()},
    }
    return result


def main() -> int:
    ap = argparse.ArgumentParser(prog="chaos_soak")
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--concurrency", type=int, default=4)
    ap.add_argument("--request-deadline", type=float, default=10.0)
    ap.add_argument("--min-success", type=float, default=0.9)
    ap.add_argument("--store-kills", type=int, default=2)
    ap.add_argument("--planner", action="store_true",
                    help="run the SLA planner (local connector) under a "
                         "mid-run load surge; the pool must scale up")
    ap.add_argument("--overload", action="store_true",
                    help="run the overload-control ramp scenario instead "
                         "(scripts/overload_soak.py: open-loop 3x ramp, "
                         "goodput must plateau)")
    ap.add_argument("--model-kill", action="store_true",
                    help="mixed-model blast-radius scenario: kill an "
                         "entire model pool mid-traffic; the surviving "
                         "model's success + latency must stay flat")
    ap.add_argument("--mid-stream-kill", action="store_true",
                    help="mid-stream failover scenario: kill -9 decode "
                         "workers at random token indices; streams must "
                         "resume with zero client-visible failures, "
                         "byte-identical tokens, and (jax arm) a cluster "
                         "KV re-attach instead of full recompute")
    a = ap.parse_args()
    if a.mid_stream_kill:
        import json as _json

        logdir = tempfile.mkdtemp(prefix="midstream_kill_soak_")
        print(f"mid-stream-kill soak: {a.duration}s echo arm, "
              f"{a.workers} workers, logs {logdir}", flush=True)
        result = asyncio.run(midstream_kill_soak(
            a.duration, a.workers, a.concurrency, a.request_deadline,
            logdir))
        out = os.path.join(REPO, "bench_points",
                           "midstream_kill_soak.json")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            _json.dump(result, f, indent=2, sort_keys=True)
        print(_json.dumps(result["verdicts"], indent=2, sort_keys=True),
              flush=True)
        print(f"artifact: {out}", flush=True)
        failed = [k for k, ok in result["verdicts"].items() if not ok]
        if failed:
            print(f"FAIL: {failed}", flush=True)
            return 1
        print("PASS: every killed stream resumed, token-identical, "
              "KV re-attached", flush=True)
        return 0
    if a.model_kill:
        import json as _json

        logdir = tempfile.mkdtemp(prefix="model_kill_soak_")
        print(f"model-kill soak: {a.duration}s, {a.workers} workers per "
              f"model pool, logs {logdir}", flush=True)
        result = asyncio.run(model_kill_soak(
            a.duration, a.workers, a.concurrency, a.request_deadline,
            a.min_success, logdir))
        out = os.path.join(REPO, "bench_points", "model_kill_soak.json")
        os.makedirs(os.path.dirname(out), exist_ok=True)
        with open(out, "w") as f:
            _json.dump(result, f, indent=2, sort_keys=True)
        print(_json.dumps(result, indent=2, sort_keys=True), flush=True)
        print(f"artifact: {out}", flush=True)
        failed = [k for k, ok in result["verdicts"].items() if not ok]
        if failed:
            print(f"FAIL: {failed}", flush=True)
            return 1
        print("PASS: surviving model undisturbed by the pool kill",
              flush=True)
        return 0
    if a.overload:
        # the overload soak IS a chaos scenario: same process harness,
        # different failure mode (congestion instead of kill -9)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from overload_soak import main as overload_main

        sys.argv = [sys.argv[0]]
        return overload_main()
    logdir = tempfile.mkdtemp(prefix="chaos_soak_")
    print(f"chaos soak: {a.duration}s, {a.workers} workers, logs {logdir}"
          + (" [planner]" if a.planner else ""), flush=True)
    stats = asyncio.run(soak(a.duration, a.workers, a.concurrency,
                             a.request_deadline, a.min_success,
                             a.store_kills, logdir, planner=a.planner))
    print(stats.summary(), flush=True)
    if stats.hung:
        print(f"FAIL: {stats.hung} hung request(s)", flush=True)
        return 1
    if not stats.submitted or stats.ok / stats.submitted < a.min_success:
        print(f"FAIL: success rate below {a.min_success:.0%}", flush=True)
        return 1
    if a.planner and not stats.planner_scale_ups:
        print("FAIL: planner never scaled the pool up under the surge",
              flush=True)
        return 1
    print("PASS: zero hung requests, success rate within bounds",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
