"""Benchmark: decode throughput + TTFT of the in-tree JAX engine on the
attached accelerator (TPU under the driver; CPU as fallback).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Primary metric: steady-state decode tokens/sec/chip on Llama-3.2-1B shapes
(bf16, random-init weights — throughput is weight-value independent),
continuous batch of 8, 128-token prompts. The reference publishes no absolute
numbers (BASELINE.md); ``vs_baseline`` is measured against a nominal H100
Dynamo+vLLM figure for a 1B-class model, stated in TARGET_TOK_S below.
"""

from __future__ import annotations

import json
import os
import time

TARGET_TOK_S = 4000.0  # nominal Dynamo+vLLM H100 decode tok/s/GPU, 1B-class model


def main() -> None:
    import jax

    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.llm.protocols.common import BackendInput, StopConditions
    from dynamo_tpu.models import llama

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    if on_tpu:
        model = llama.preset("llama-3.2-1b", max_position=2048)
        max_batch, prompt_len, gen_tokens = 8, 128, 128
        max_context = 1024
    else:  # smoke path for dev machines
        model = llama.preset("tiny-byte")
        max_batch, prompt_len, gen_tokens = 4, 32, 32
        max_context = 256

    cfg = JaxEngineConfig(model=model, tp=1, page_size=64,
                          max_batch=max_batch, max_context=max_context,
                          prefill_chunk=min(512, max_context),
                          decode_steps=32 if on_tpu else 8)
    core = EngineCore(cfg)

    def run_round(tag: str):
        t0 = time.monotonic()
        prompt = list(range(1, prompt_len + 1))
        for i in range(max_batch):
            core.submit(f"{tag}{i}", BackendInput(
                token_ids=[p + i for p in prompt],
                stop=StopConditions(max_tokens=gen_tokens, ignore_eos=True)))
        done = 0
        first_token_at = None
        tokens = 0
        while done < max_batch:
            outs = core.step()
            for so in outs:
                tokens += 1
                if first_token_at is None:
                    first_token_at = time.monotonic() - t0
                if so.finish is not None:
                    done += 1
        return tokens, time.monotonic() - t0, first_token_at

    # warmup: compile all bucket programs
    run_round("warm")
    # timed: measure decode-dominated steady state
    tokens, wall, ttft = run_round("bench")

    tok_s = tokens / wall
    result = {
        "metric": "decode_tok_s_per_chip",
        "value": round(tok_s, 1),
        "unit": "tok/s",
        "vs_baseline": round(tok_s / TARGET_TOK_S, 3),
        "platform": platform,
        "model": "llama-3.2-1b" if on_tpu else "tiny-byte",
        "batch": max_batch,
        "prompt_len": prompt_len,
        "gen_tokens": gen_tokens,
        "ttft_s": round(ttft, 4) if ttft else None,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
