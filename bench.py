"""Benchmark: decode throughput, TTFT, prefill throughput and MFU of the
in-tree JAX engine on the attached accelerator (TPU under the driver; CPU as
fallback).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "sweep": [...]}

Resilience contract (VERDICT round 1, item 1): the TPU plugin (axon) can fail
or hang at backend init. The bench therefore
  1. probes backend init in a SUBPROCESS with a timeout (a hang cannot take
     down the bench process), retrying once;
  2. on probe failure forces ``JAX_PLATFORMS=cpu`` before importing jax in
     this process and still emits a JSON line (``tpu: "unavailable"``);
  3. wraps everything so any error yields a JSON error line, never a bare
     traceback with rc=1.

Primary metric: best steady-state decode tokens/sec/chip on Llama-3.2-1B
shapes (bf16, random-init weights — throughput is weight-value independent)
across batch sizes 1/8/32, 128-token prompts, 128 generated tokens. The
reference publishes no absolute numbers (BASELINE.md); ``vs_baseline`` is
measured against a nominal Dynamo+vLLM H100 figure for a 1B-class model
(TARGET_TOK_S). An 8B-shaped sweep runs when the chip's HBM fits bf16 8B
weights (v5e 16G does not; it is recorded as skipped there).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# North-star decode target (BASELINE.md publishes no absolute tok/s table,
# so this is derived, not copied): vLLM-class serving sustains roughly
# 1-1.5% MFU-equivalent per-token bandwidth at 1B-class bf16 decode; on
# H100 (~3.35 TB/s HBM) an 8B model decodes ~2.5k tok/s/GPU and a 1B-class
# model is memory-bound at ~4k with realistic batching — the same arithmetic
# lands near 4k on v5e (819 GB/s HBM, 2.5 GB of 1B-bf16 weights ->
# ~330 tok/s/batch-line * b=16 effective). vs_baseline is this nominal
# constant; `mfu` in the payload is the hardware-normalized truth.
TARGET_TOK_S = 4000.0
PROBE_TIMEOUT_S = float(os.environ.get("DYNAMO_BENCH_PROBE_TIMEOUT", "150"))
BUDGET_S = float(os.environ.get("DYNAMO_BENCH_BUDGET", "1500"))
# Every (model, batch) measurement is flushed here the moment it lands: a
# tunnel wedge mid-sweep must leave the points already measured as a real
# artifact (round-4 lost its only on-chip window to end-of-run-only writing)
PARTIAL_PATH = os.environ.get(
    "DYNAMO_BENCH_PARTIAL", os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_PARTIAL.json"))
# Besides the rolling partial, every (model, batch) point gets its OWN
# artifact file the moment it lands — a later wedge (or a corrupted rolling
# write) can never take already-measured points with it.
POINTS_DIR = os.environ.get(
    "DYNAMO_BENCH_POINTS_DIR", os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "bench_points"))

def _probe_backend(timeout_s: float):
    """Initialize the jax backend in a subprocess. Returns (platform,
    device_kind) or None. A hung PJRT plugin kills the child, not us."""
    code = ("import jax\n"
            "d = jax.devices()[0]\n"
            "print('PROBE|' + d.platform + '|' + d.device_kind)\n")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except Exception:
        return None
    if r.returncode != 0:
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PROBE|"):
            _, plat, kind = line.strip().split("|", 2)
            return plat, kind
    return None


def _flush_partial(payload: dict) -> None:
    """Atomically write the in-progress result. Never allowed to fail the
    bench: a read-only FS just loses the hedge, not the run."""
    try:
        tmp = PARTIAL_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, PARTIAL_PATH)
    except Exception:
        pass


def _flush_point(model: str, entry: dict, meta: dict) -> None:
    """One self-contained JSON artifact per (model, batch) point, carrying
    the platform tag so even a single surviving point is attributable."""
    try:
        os.makedirs(POINTS_DIR, exist_ok=True)
        batch = entry.get("batch", "x")
        path = os.path.join(POINTS_DIR, f"{model}_b{batch}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({**meta, "model": model, **entry}, f)
        os.replace(tmp, path)
    except Exception:
        pass


def _run_model(model_cfg, batches, prompt_len, gen_tokens, max_context,
               on_tpu, deadline, flush=None):
    """For each batch size, build an EngineCore sized max_batch=b (decode
    dispatches always run at full engine width, so measuring batch b inside a
    max-sized engine would measure padding, not batch-b performance), run a
    warmup (compile) round then a timed round. Returns (n_params, sweep)."""
    import jax

    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.llm.protocols.common import BackendInput, StopConditions

    def make_core(b: int) -> EngineCore:
        # decode_steps amortizes the per-dispatch host round-trip (68 ms
        # through the driver's TPU tunnel): 32 steps/dispatch cuts that
        # overhead to ~2 ms/step at the cost of up to 31 wasted steps on
        # the final dispatch of a finished sequence
        return EngineCore(JaxEngineConfig(
            model=model_cfg, tp=1, page_size=64, max_batch=b,
            max_context=max_context, prefill_chunk=min(512, max_context),
            decode_steps=32 if on_tpu else 8))

    core = None
    n_params = None
    # prompt ids must stay inside the model vocab: out-of-range ids clamp in
    # the embedding gather and degenerate every prompt to the same token
    mod = min(997, model_cfg.vocab_size - 1)

    def round_(tag: str, b: int, salt: int):
        # unique prompts per round: the warm round must compile the same
        # (no-prefix-hit) program the timed round runs, and timed TTFT must
        # measure a true prefill, not a prefix-cache hit
        prompt = list(range(1, prompt_len + 1))
        t0 = time.monotonic()
        for i in range(b):
            core.submit(f"{tag}{i}", BackendInput(
                token_ids=[(p * 31 + i * 7 + salt) % mod + 1 for p in prompt],
                stop=StopConditions(max_tokens=gen_tokens, ignore_eos=True)))
        done = 0
        tokens = 0
        post_tokens = 0          # tokens emitted by dispatches after t_first
        first: dict = {}
        t_first = None           # wall time when the last first-token landed
        while done < b:
            outs = core.step()
            now = time.monotonic()
            counted = t_first is not None  # this whole dispatch is post-first
            for so in outs:
                tokens += 1
                if so.seq_id not in first:
                    first[so.seq_id] = now - t0
                if so.finish is not None:
                    done += 1
            if counted:
                post_tokens += len(outs)
            elif len(first) == b:
                t_first = now - t0
        return (tokens, time.monotonic() - t0, sorted(first.values()),
                t_first, post_tokens)

    sweep = []

    def _record(entry):
        sweep.append(entry)
        if flush is not None:
            flush(n_params, sweep, entry)

    for b in batches:
        if time.monotonic() > deadline:
            _record({"batch": b, "skipped": "time budget"})
            continue
        try:
            core = None  # drop the previous core BEFORE building the next
            # one: params + KV pools of two cores resident at once would OOM
            # the 8B sweep on exactly the chips its HBM gate admits
            core = make_core(b)
            if n_params is None:
                n_params = sum(int(a.size)
                               for a in jax.tree.leaves(core.params))
            round_(f"warm{b}_", b, salt=2 * b)       # compile + warm caches
            g0 = core.goodput.lifetime()             # timed-round baseline
            tokens, wall, ttfts, t_first, post_tokens = round_(
                f"bench{b}_", b, salt=2 * b + 1)
            g1 = core.goodput.lifetime()
        except Exception as e:
            # one batch failing (e.g. OOM at the largest size) must not
            # discard the batches already measured for this model
            _record({"batch": b, "error": f"{type(e).__name__}: {e}"})
            continue
        # steady-state decode rate: tokens from dispatches strictly after the
        # one that produced the last first-token, over the time after it —
        # both the prefill and that mixed first dispatch are excluded
        decode_wall = (wall - t_first) if t_first else 0.0
        tok_s = (post_tokens / decode_wall
                 if post_tokens > 0 and decode_wall > 0 else tokens / wall)
        entry = {
            "batch": b,
            "decode_tok_s": round(tok_s, 1),
            "p50_ttft_s": round(ttfts[len(ttfts) // 2], 4),
            "prefill_tok_s": (round(b * prompt_len / ttfts[-1], 1)
                              if ttfts else None),
            "total_tok_s": round(tokens / wall, 1),
        }
        # goodput accounting (utils/roofline.py): analytic FLOPs/bytes of
        # the timed round's dispatches over their measured wall time,
        # against the platform peak (TPU table / calibrated CPU). Non-null
        # on EVERY platform — `mfu: null` is dead.
        busy = g1["busy_s"] - g0["busy_s"]
        if busy > 0:
            d_flops = g1["flops_total"] - g0["flops_total"]
            d_bytes = g1["bytes_total"] - g0["bytes_total"]
            entry["mfu"] = round(d_flops / busy / g1["peak_flops"], 4)
            entry["mbu"] = round(
                d_bytes / busy / (g1["peak_hbm_gbps"] * 1e9), 4)
            entry["hbm_gbps"] = round(d_bytes / busy / 1e9, 2)
            entry["peak_source"] = g1["peak_source"]
        try:
            # prefix-reuse TTFT: the same prompts again — admission matches
            # the cached blocks, so only the last token truly prefills
            # (the KV-aware-routing / prefix-cache serving claim, measured)
            if time.monotonic() < deadline:
                _, _, warm_ttfts, _, _ = round_(
                    f"reuse{b}_", b, salt=2 * b + 1)
                entry["p50_ttft_warm_s"] = round(
                    warm_ttfts[len(warm_ttfts) // 2], 4)
        except Exception:  # noqa: BLE001 - warm pass is optional
            pass
        _record(entry)
    return n_params, sweep


def _spec_ab(on_tpu, deadline, flush_point):
    """Speculative-decoding A/B: the same engine with DYN_SPEC off vs
    ``spec='ngram'`` on a repetitive/structured workload, so the n-gram
    proposer has real hit rate. Random-init weights are scaled toward zero,
    which makes greedy generation collapse into the repetition attractor a
    TRAINED model exhibits on structured prompts (code, JSON, extraction) —
    the token map becomes (near) position-independent, so the stream cycles
    and prompt-lookup drafts verify. Throughput numbers stay honest: weight
    VALUES don't change the math executed per token, and the measured
    ``spec_accept_rate`` is recorded alongside so the win is attributable.

    Emits spec_decode_tok_s / spec_off_decode_tok_s / spec_accept_rate as a
    self-contained bench_points artifact, so the next TPU window measures
    the win unattended."""
    import jax

    from dynamo_tpu.engine.engine import EngineCore, JaxEngineConfig
    from dynamo_tpu.llm.protocols.common import BackendInput, StopConditions
    from dynamo_tpu.models import llama

    if on_tpu:
        mcfg = llama.preset("llama-3.2-1b", max_position=2048)
        batch, gen, k, steps, ctx = 8, 128, 32, 32, 1024
    else:
        # big enough that bf16 weights (~59 MB) exceed the LLC: CPU decode
        # is then memory-bandwidth-bound over the weight stream, the same
        # regime the TPU win comes from (a cache-resident tiny model would
        # A/B the dispatch overhead instead)
        mcfg = llama.LlamaConfig(
            vocab_size=4096, hidden_size=512, num_layers=8, num_heads=8,
            num_kv_heads=4, head_dim=64, intermediate_size=1536,
            rope_theta=10000.0, max_position=1024)
        batch, gen, k, steps, ctx = 4, 64, 16, 8, 512

    def build(spec):
        core = EngineCore(JaxEngineConfig(
            model=mcfg, tp=1, page_size=64, max_batch=batch,
            max_context=ctx, prefill_chunk=min(128, ctx),
            decode_steps=steps, spec=spec, spec_k=k))
        core.params = jax.jit(
            lambda p: jax.tree.map(lambda a: a * 0.05, p))(core.params)
        return core

    def measure(core, n, tag):
        prompt = [5, 6, 7, 8, 9, 10, 11, 12] * 8
        t0 = time.monotonic()
        for i in range(batch):
            core.submit(f"{tag}{i}", BackendInput(
                token_ids=[p + i for p in prompt],
                stop=StopConditions(max_tokens=n, ignore_eos=True)))
        toks = done = post = 0
        t_first = None
        seen = set()
        while done < batch:
            outs = core.step()
            now = time.monotonic()
            counted = t_first is not None
            for so in outs:
                toks += 1
                seen.add(so.seq_id)
                if so.finish is not None:
                    done += 1
            if counted:
                post += len(outs)
            elif len(seen) == batch:
                t_first = now - t0
        wall = time.monotonic() - t0
        return (post / (wall - t_first)
                if t_first and post and wall > t_first else toks / wall)

    entry = {"batch": batch, "spec_k": k, "gen_tokens": gen,
             "params_m": None}
    prev_adapt = os.environ.get("DYN_SPEC_ADAPT")
    os.environ["DYN_SPEC_ADAPT"] = "0"   # fixed k: one verify bucket to
    try:                                 # compile, stable timed round
        for spec, key in (("off", "spec_off_decode_tok_s"),
                          ("ngram", "spec_decode_tok_s")):
            if time.monotonic() > deadline:
                entry["skipped"] = "time budget"
                break
            core = build(spec)
            if entry["params_m"] is None:
                entry["params_m"] = round(sum(
                    int(a.size) for a in jax.tree.leaves(core.params)) / 1e6,
                    1)
            measure(core, gen // 2, "warm")       # compile + warm caches
            entry[key] = round(measure(core, gen, "bench"), 1)
            if spec == "ngram":
                entry["spec_accept_rate"] = round(
                    core.spec_accepted_total
                    / max(1, core.spec_proposed_total), 3)
                entry["spec_proposed"] = core.spec_proposed_total
            del core
    finally:
        if prev_adapt is None:
            os.environ.pop("DYN_SPEC_ADAPT", None)
        else:
            os.environ["DYN_SPEC_ADAPT"] = prev_adapt
    flush_point(entry)
    return entry


def main() -> None:
    t_start = time.monotonic()
    deadline = t_start + BUDGET_S
    try:  # a stale partial from a previous run must never be mistaken for
        os.remove(PARTIAL_PATH)  # this run's artifact by the salvage path
    except OSError:
        pass

    probe = _probe_backend(PROBE_TIMEOUT_S)
    if probe is None:
        probe = _probe_backend(PROBE_TIMEOUT_S)  # one retry
    tpu_status = "ok"
    if probe is None or probe[0] == "cpu":
        # accelerator init failed/hung twice (or only CPU exists): force the
        # CPU path before this process ever touches a backend
        from dynamo_tpu.utils.hostmesh import force_cpu

        force_cpu(1)
        if probe is None:
            tpu_status = "unavailable"

    import jax

    dev = jax.devices()[0]
    platform = dev.platform
    on_tpu = platform not in ("cpu",)
    # peak normalization lives in utils/roofline.py now (one table for the
    # engine's goodput plane and this bench); entries carry peak_source

    from dynamo_tpu.models import llama

    notes = []
    if on_tpu:
        runs = [("llama-3.2-1b",
                 llama.preset("llama-3.2-1b", max_position=2048),
                 [1, 8, 32], 128, 128, 1024)]
        try:
            hbm = int((dev.memory_stats() or {}).get("bytes_limit", 0))
        except Exception:
            hbm = 0
        if not hbm:
            # PJRT plugins may expose no memory_stats; fall back to the
            # chip family's known HBM capacity
            kind = dev.device_kind.lower()
            hbm = int(95e9 if "v5p" in kind else 32e9 if "v6" in kind
                      else 32e9 if "v4" in kind else 16e9)
            notes.append(f"hbm from device_kind table: {hbm/1e9:.0f}G")
        if hbm >= 22e9:  # 8B bf16 weights are 16G; need headroom for KV+work
            runs.append(("llama-3-8b",
                         llama.preset("llama-3-8b", max_position=2048),
                         [1, 8], 128, 128, 1024))
        else:
            notes.append(f"8B sweep skipped: HBM {hbm/1e9:.1f}G < 22G "
                         "(bf16 8B weights alone are 16G)")
    else:
        runs = [("tiny-byte", llama.preset("tiny-byte"), [1, 4], 32, 32, 256)]

    sweeps = []

    def assemble(partial: bool):
        best = None
        for sw in sweeps:
            if sw.get("model") == runs[0][0]:
                done = [e for e in sw.get("results", []) if "decode_tok_s" in e]
                if done:
                    best = max(done, key=lambda e: e["decode_tok_s"])
        return {
            "metric": "decode_tok_s_per_chip",
            "value": best["decode_tok_s"] if best else 0.0,
            "unit": "tok/s",
            "vs_baseline": (round(best["decode_tok_s"] / TARGET_TOK_S, 3)
                            if best else 0.0),
            "platform": platform,
            "device_kind": dev.device_kind,
            "tpu": tpu_status,
            "model": runs[0][0],
            "best_batch": best.get("batch") if best else None,
            "p50_ttft_s": best.get("p50_ttft_s") if best else None,
            "mfu": best.get("mfu") if best else None,
            "mbu": best.get("mbu") if best else None,
            "hbm_gbps": best.get("hbm_gbps") if best else None,
            "peak_source": best.get("peak_source") if best else None,
            "paged_kernel": (os.environ.get("DYNAMO_TPU_PAGED_KERNEL", "dma")
                             if platform == "tpu" else "simple[interpret]"),
            "sweep": sweeps,
            "notes": notes,
            "partial": partial,
            "wall_s": round(time.monotonic() - t_start, 1),
        }

    point_meta = {"platform": platform, "device_kind": dev.device_kind,
                  "tpu": tpu_status}
    # an artifact must exist BEFORE the first point: a wedge inside the
    # first warmup/compile round still leaves a platform-tagged record
    _flush_partial(assemble(partial=True))

    for name, mcfg, batches, plen, gen, ctx in runs:
        if time.monotonic() > deadline:
            sweeps.append({"model": name, "skipped": "time budget"})
            continue
        live = {"model": name, "prompt_len": plen, "gen_tokens": gen,
                "results": []}
        sweeps.append(live)

        def flush(n_params, sweep, entry, live=live, name=name):
            live["n_params"] = n_params
            live["results"] = sweep
            _flush_partial(assemble(partial=True))
            _flush_point(name, entry, point_meta)

        try:
            n_params, sweep = _run_model(mcfg, batches, plen, gen, ctx,
                                         on_tpu, deadline, flush=flush)
        except Exception as e:
            # a later run (e.g. the conditional 8B sweep) must never zero an
            # already-measured headline — record and keep going
            live["error"] = f"{type(e).__name__}: {e}"
            continue
        live["n_params"] = n_params
        live["results"] = sweep

    # speculative-decoding A/B (its own engines; never allowed to take the
    # headline sweep down with it)
    spec_ab = None
    try:
        if time.monotonic() < deadline:
            spec_ab = _spec_ab(
                on_tpu, deadline,
                lambda e: _flush_point("spec_ab", e, point_meta))
        else:
            spec_ab = {"skipped": "time budget"}
    except Exception as e:  # noqa: BLE001
        spec_ab = {"error": f"{type(e).__name__}: {e}"}

    # the headline (and vs_baseline, a 1B-class target) is strictly the
    # first model's sweep — a later model must never stand in for it;
    # assemble() enforces that by matching runs[0][0]
    result = assemble(partial=False)
    if spec_ab is not None:
        result["spec_ab"] = spec_ab
    _flush_partial(result)
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never a bare traceback: emit a parseable line
        import traceback

        print(json.dumps({
            "metric": "decode_tok_s_per_chip", "value": 0.0, "unit": "tok/s",
            "vs_baseline": 0.0, "error": f"{type(e).__name__}: {e}",
            "trace": traceback.format_exc(limit=3),
        }), flush=True)
