"""Pallas TPU kernels for the serving hot path."""

from .attention import flash_attention, paged_attention  # noqa: F401
