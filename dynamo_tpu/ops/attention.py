"""Pallas TPU attention kernels for the serving engine.

Two kernels cover the two hot paths:

- :func:`flash_attention` — blockwise online-softmax attention for prefill
  chunks. Queries/keys carry explicit positions + validity so it drops into
  the engine's paged write-then-gather scheme unchanged: the [T,S] score
  matrix never materializes in HBM.
- :func:`paged_attention` — decode attention that reads KV *pages* directly
  from the HBM pool through a scalar-prefetched page table (one grid step per
  page, Pallas double-buffers the page DMAs). This removes the
  gather-into-contiguous-context copy entirely, which is the dominant HBM
  traffic of decode.

Both kernels run in interpreter mode off-TPU so the CPU test suite exercises
the exact same code path the TPU runs compiled.

Reference capability: the CUDA paged/flash attention vLLM supplies behind the
reference's engine adapters (SURVEY §2.1 engine rows; §7 "Pallas paged
attention + flash kernels"). This file is original TPU-first work, not a
translation.
"""

from __future__ import annotations

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(n: int, cap: int = 128) -> int:
    """Largest power-of-two block <= cap that divides n.

    The engine only calls flash_attention with power-of-two bucketed T/S,
    so this returns >= 8 on every real path; a degenerate block of 1 can
    only happen for odd ad-hoc shapes (tests), where interpret mode does
    not care about TPU tiling."""
    b = cap
    while b > 1 and n % b:
        b //= 2
    return b


# ---------------------------------------------------------------------------
# Flash attention (prefill over gathered context)
# ---------------------------------------------------------------------------

def _flash_kernel(qpos_ref, kpos_ref, kval_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, G: int,
                  softcap: Optional[float], window: Optional[int]):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qp = qpos_ref[0]                                       # [BT, 1]
    kp = kpos_ref[0]                                       # [1, BS]
    kv = kval_ref[0]
    # dead-block skip: a key block entirely in the causal future — or, on
    # sliding layers, entirely below every query's window — contributes
    # nothing; skip its matmuls (positions are dynamic, so this is a
    # run-time guard; the BlockSpec copies still happen)
    live = jnp.min(kp) <= jnp.max(qp)
    if window is not None:
        live = live & (jnp.max(kp) > jnp.min(qp) - window)

    @pl.when(live)
    def _():
        q = q_ref[0]                                       # [G, BT, Dh] bf16
        BS, Dh = k_ref.shape[-2], k_ref.shape[-1]
        k = jnp.broadcast_to(k_ref[0][None], (G, BS, Dh))  # [G, BS, Dh]
        v = jnp.broadcast_to(v_ref[0][None], (G, BS, Dh))
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale    # [G, BT, BS]
        if softcap is not None:
            # Gemma2 attention-score softcapping, BEFORE masking (tanh of
            # the NEG_INF sentinel would turn masked slots into finite ±cap)
            s = jnp.tanh(s / softcap) * softcap

        mask = ((kp <= qp) & (kv > 0))[None]               # [1, BT, BS]
        if window is not None:
            # sliding layers: keys within the last `window` positions.
            # The paged lane's per-layer-class cold programs
            # (llm/kvpage/programs.py) apply this same `kp > qp - window`
            # rule to staged segments — the two must stay in lockstep or
            # paged and dense forwards diverge on Gemma2/3-style models.
            mask = mask & (kp > qp - window)[None]

        m_prev = m_scr[:]
        m_cur = jnp.max(jnp.where(mask, s, NEG_INF), axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # mask p explicitly: with a finite NEG_INF sentinel, exp(s - m) of a
        # fully masked row would otherwise be exp(0) = 1
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)       # [G, BT, BS] f32
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [G, BT, Dh]
        m_scr[:] = m_new

    @pl.when(j == pl.num_programs(2) - 1)
    def _():
        l = l_scr[:]
        o = acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = o.astype(o_ref.dtype)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    q_pos: jax.Array, k_pos: jax.Array, k_valid: jax.Array,
                    interpret: Optional[bool] = None,
                    scale: Optional[float] = None,
                    softcap: Optional[float] = None,
                    window: Optional[int] = None) -> jax.Array:
    """Blockwise attention with explicit positions.

    q: [B, T, Hq, Dh] ; k, v: [B, S, Hkv, Dh] (gathered context, GQA)
    q_pos: [B, T] int32 ; k_pos: [B, S] int32 ; k_valid: [B, S] bool
    A query at position p attends to context slots with k_pos <= p & valid;
    with ``window`` additionally k_pos > p - window (Gemma2/3 sliding
    layers). ``softcap`` tanh-caps scores before the online softmax;
    ``scale`` overrides the rsqrt(Dh) default (query_pre_attn_scalar).
    Returns [B, T, Hq, Dh] in q.dtype.
    """
    B, T, Hq, Dh = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    G = Hq // Hkv
    if interpret is None:
        interpret = _interpret_default()
    BT = _pick_block(T)
    BS = _pick_block(S)
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)

    # head-major layouts: fold (B, Hkv) into the leading grid axis
    q5 = q.reshape(B, T, Hkv, G, Dh).transpose(0, 2, 3, 1, 4)
    q5 = q5.reshape(B * Hkv, G, T, Dh)
    k3 = k.transpose(0, 2, 1, 3).reshape(B * Hkv, S, Dh)
    v3 = v.transpose(0, 2, 1, 3).reshape(B * Hkv, S, Dh)
    # positions/validity carry a singleton middle axis: a [B, S] array with
    # block (1, BS) violates Mosaic's last-two-dims tiling rule whenever
    # B > 1 (block dim 1 is neither 8-divisible nor equal to B); as
    # [B, 1, S] the trailing dims are (1, BS) against overall (1, S), legal
    # for every batch size
    kval = k_valid.astype(jnp.int32)[:, None, :]       # [B, 1, S]
    kpos3 = k_pos[:, None, :]                          # [B, 1, S]
    qpos_col = q_pos[:, :, None]                       # [B, T, 1]

    grid = (B * Hkv, T // BT, S // BS)
    out = pl.pallas_call(
        functools.partial(_flash_kernel, scale=scale, G=G,
                          softcap=softcap, window=window),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, BT, 1), lambda bh, i, j: (bh // Hkv, i, 0)),
            pl.BlockSpec((1, 1, BS), lambda bh, i, j: (bh // Hkv, 0, j)),
            pl.BlockSpec((1, 1, BS), lambda bh, i, j: (bh // Hkv, 0, j)),
            pl.BlockSpec((1, G, BT, Dh), lambda bh, i, j: (bh, 0, i, 0)),
            pl.BlockSpec((1, BS, Dh), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, BS, Dh), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, G, BT, Dh), lambda bh, i, j: (bh, 0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * Hkv, G, T, Dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((G, BT, 1), jnp.float32),    # m
            pltpu.VMEM((G, BT, 1), jnp.float32),    # l
            pltpu.VMEM((G, BT, Dh), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(qpos_col, kpos3, kval, q5, k3, v3)

    out = out.reshape(B, Hkv, G, T, Dh).transpose(0, 3, 1, 2, 4)
    return out.reshape(B, T, Hq, Dh)


# ---------------------------------------------------------------------------
# Paged attention (decode directly over the HBM page pool)
#
# TPU path: multi-page double-buffered DMA kernel. The KV pool stays in HBM
# (memory_space=ANY); each grid step (b, j) copies the next block of
# ``pages_per_block`` pages for sequence b — ALL kv heads in one strided
# DMA per page — into a VMEM double buffer while the previous block
# computes, and accumulates online softmax in VMEM scratch. One DMA per
# page (not per page×head) matters: DMA issue overhead dominated the
# per-(b,h,j) variant, which moved the same bytes in 8× more copies and
# reached only ~9% of HBM bandwidth. Work is skipped (copies AND compute)
# for page blocks beyond a sequence's length, so cost scales with actual
# context, not the padded table width. This is the same design as
# jax.experimental.pallas.ops.tpu.paged_attention, which we cannot use
# directly: for GQA group sizes not divisible by 8 (Llama 8B/1B are 32q/8kv
# = 4) its m/l pallas outputs lower to illegal (…,1) blocks in this JAX
# version. Keeping m/l in scratch sidesteps that and drops two HBM outputs.
# ---------------------------------------------------------------------------


def _paged_dma_kernel(pt_ref, len_ref, q_ref, k_hbm, v_hbm, o_ref,
                      k_buf, v_buf, sem, m_scr, l_scr, acc_scr, state,
                      *, scale: float, page: int, ppb: int, hkv: int,
                      fold: int, dh: int, softcap: Optional[float],
                      window: Optional[int]):
    """Pools arrive pre-folded to [Hkv, n_pages, page//fold, fold*Dh] so DMA
    rows are 128-lane aligned even for Dh=64; a folded row holds ``fold``
    consecutive tokens, handled as ``fold`` score slices. Buffers are
    head-major ([2, Hkv, ppb, rows, fold*Dh]) so the per-page all-head DMA
    lands as a contiguous per-head reshape for the batched matmul.

    With ``window``, each lane's active block range is clamped at BOTH ends:
    blocks wholly below ``length - window`` are never DMA'd nor computed
    (the page-range clamp — sliding decode reads O(window) bytes, not
    O(context)), and in-block tokens below the window start are masked."""
    b = pl.program_id(0)
    j = pl.program_id(1)
    L2 = ppb * page           # tokens per compute block
    rows_pp = page // fold    # folded rows per page
    rows = L2 // fold         # folded rows per compute block

    def nblocks(bb):
        return (len_ref[bb] + L2 - 1) // L2

    def jstart(bb):
        # first block holding any in-window token. The decode query sits at
        # length-1, so the window covers [length - window, length).
        if window is None:
            return 0
        return jnp.maximum(len_ref[bb] - window, 0) // L2

    def copy_descs(bb, jj, slot):
        descs = []
        for i in range(ppb):
            pidx = pt_ref[bb, jj * ppb + i]
            # one strided DMA per page covering every kv head
            descs.append(pltpu.make_async_copy(
                k_hbm.at[:, pidx], k_buf.at[slot, :, i], sem.at[slot, 0]))
            descs.append(pltpu.make_async_copy(
                v_hbm.at[:, pidx], v_buf.at[slot, :, i], sem.at[slot, 1]))
        return descs

    def start(bb, jj, slot):
        for d in copy_descs(bb, jj, slot):
            d.start()

    nb = nblocks(b)
    j0 = jstart(b)
    active = (j >= j0) & (j < nb)

    # first grid step: prime the pipeline with lane 0's first active block.
    # Steps of lane 0 before its window start are dead, so the prime fires
    # at (0, jstart(0)) — for full attention that is (0, 0) as before.
    first = (b == 0) & (j == jstart(0))

    @pl.when(first)
    def _():
        state[0] = 0
        start(b, j, 0)

    @pl.when(active)
    def _():
        slot = state[0]

        @pl.when(j == j0)
        def _():
            m_scr[:] = jnp.full_like(m_scr, NEG_INF)
            l_scr[:] = jnp.zeros_like(l_scr)
            acc_scr[:] = jnp.zeros_like(acc_scr)

        # prefetch the next ACTIVE step's block into the other buffer.
        # flat order: j within b, then b; j outside [jstart, nblocks) is
        # dead (never copied, never computed).
        nj, nb_ = j + 1, b
        wrap_b = nj >= nb
        nb_ = jnp.where(wrap_b, b + 1, nb_)
        # clamp the lookup lane: when nb_ == num_programs there is no next
        # step (has_next gates the start), but jstart still indexes len_ref
        nj = jnp.where(wrap_b,
                       jstart(jnp.minimum(nb_, pl.num_programs(0) - 1)), nj)
        has_next = nb_ < pl.num_programs(0)

        @pl.when(has_next)
        def _():
            start(nb_, nj, slot ^ 1)

        # wait for our block's DMAs
        for d in copy_descs(b, j, slot):
            d.wait()

        q = q_ref[0]                                        # [Hkv, G, Dh]
        kf = k_buf[slot].reshape(hkv, rows, fold * dh)
        vf = v_buf[slot].reshape(hkv, rows, fold * dh)
        # token index of folded row r, slice f: within this block the page
        # is r // rows_pp and the in-page row r % rows_pp
        ridx = jax.lax.broadcasted_iota(jnp.int32, (1, 1, rows), 2)
        base = (ridx // rows_pp) * page + (ridx % rows_pp) * fold + j * L2
        length = len_ref[b]

        s_parts, mask_parts = [], []
        for f in range(fold):
            kslice = kf[:, :, f * dh:(f + 1) * dh]          # [Hkv, rows, Dh]
            s = jax.lax.dot_general(
                q, kslice, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale  # [Hkv, G, rows]
            if softcap is not None:
                # cap BEFORE masking (tanh(NEG_INF) would be a finite ±cap)
                s = jnp.tanh(s / softcap) * softcap
            mask = (base + f) < length
            if window is not None:
                mask = mask & ((base + f) >= length - window)
            s_parts.append(jnp.where(mask, s, NEG_INF))
            mask_parts.append(mask)

        m_prev = m_scr[:]
        m_cur = s_parts[0].max(axis=-1, keepdims=True)
        for s in s_parts[1:]:
            m_cur = jnp.maximum(m_cur, s.max(axis=-1, keepdims=True))
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_scr[:]
        acc = acc_scr[:] * alpha
        for f in range(fold):
            p = jnp.where(mask_parts[f], jnp.exp(s_parts[f] - m_new), 0.0)
            l_new = l_new + jnp.sum(p, axis=-1, keepdims=True)
            vslice = vf[:, :, f * dh:(f + 1) * dh]          # [Hkv, rows, Dh]
            acc = acc + jax.lax.dot_general(
                p.astype(vf.dtype), vslice, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)         # [Hkv, G, Dh]
        l_scr[:] = l_new
        acc_scr[:] = acc
        m_scr[:] = m_new
        state[0] = slot ^ 1

        @pl.when(j == nb - 1)
        def _():
            l = l_scr[:]
            o_ref[0] = (acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
                        ).astype(o_ref.dtype)


def _paged_attention_tpu(q4, k_pages, v_pages, page_tables, lengths,
                         *, pages_per_block: int = 8,
                         scale: Optional[float] = None,
                         softcap: Optional[float] = None,
                         window: Optional[int] = None,
                         interpret: bool = False) -> jax.Array:
    """q4: [B, Hkv, G, Dh]; pools [Hkv, n_pages, page, Dh]. Returns q4-shaped.
    ``interpret`` exists for the CPU test suite only — the serving path
    always compiles this variant (paged_attention gates it to real TPUs)."""
    B, Hkv, G, Dh = q4.shape
    _, n_pages, page, _ = k_pages.shape
    P = page_tables.shape[1]
    ppb = min(pages_per_block, P)
    if P % ppb:
        page_tables = jnp.pad(page_tables, ((0, 0), (0, ppb - P % ppb)))
        P = page_tables.shape[1]
    NB = P // ppb
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)

    # fold tokens so DMA rows are 128-lane aligned (free bitcast view)
    fold = max(1, 128 // Dh)
    if page % fold:
        raise ValueError(f"page size {page} not divisible by fold {fold}")
    kf = k_pages.reshape(Hkv, n_pages, page // fold, fold * Dh)
    vf = v_pages.reshape(Hkv, n_pages, page // fold, fold * Dh)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, NB),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, Dh), lambda b, j, pt, ln: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_specs=pl.BlockSpec((1, Hkv, G, Dh),
                               lambda b, j, pt, ln: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, Hkv, ppb, page // fold, fold * Dh), k_pages.dtype),
            pltpu.VMEM((2, Hkv, ppb, page // fold, fold * Dh), v_pages.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),                 # [slot, k/v]
            pltpu.VMEM((Hkv, G, 1), jnp.float32),            # m
            pltpu.VMEM((Hkv, G, 1), jnp.float32),            # l
            pltpu.VMEM((Hkv, G, Dh), jnp.float32),           # acc
            pltpu.SMEM((1,), jnp.int32),                     # buffer slot
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_dma_kernel, scale=scale, page=page,
                          ppb=ppb, hkv=Hkv, fold=fold, dh=Dh,
                          softcap=softcap, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q4.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(page_tables, lengths, q4, kf, vf)

def _paged_kernel(pt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, page: int,
                  softcap: Optional[float], window: Optional[int]):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    npages = (length + page - 1) // page
    if window is None:
        in_range = p < npages
    else:
        # page-range clamp: pages wholly below the window start contribute
        # nothing — skip their compute entirely
        pstart = jnp.maximum(length - window, 0) // page
        in_range = (p >= pstart) & (p < npages)

    @pl.when(in_range)
    def _():
        q = q_ref[0]                                       # [Hkv, G, Dh]
        k = k_ref[:, 0]                                    # [Hkv, page, Dh]
        v = v_ref[:, 0]
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale    # [Hkv, G, page]
        if softcap is not None:
            # cap BEFORE masking (tanh(NEG_INF) would be a finite ±cap)
            s = jnp.tanh(s / softcap) * softcap
        tok = jax.lax.broadcasted_iota(jnp.int32, (1, 1, page), 2) + p * page
        mask = tok < length
        if window is not None:
            mask = mask & (tok >= length - window)
        m_prev = m_scr[:]
        m_cur = jnp.max(jnp.where(mask, s, NEG_INF), axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        pw = jnp.where(mask, jnp.exp(s - m_new), 0.0)      # [Hkv, G, page]
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(pw, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pw.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)            # [Hkv, G, Dh]
        m_scr[:] = m_new

    @pl.when(p == pl.num_programs(1) - 1)
    def _():
        l = l_scr[:]
        o = acc_scr[:] / jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = o.astype(o_ref.dtype)


def paged_attention(q: jax.Array, k_pages: jax.Array, v_pages: jax.Array,
                    page_tables: jax.Array, lengths: jax.Array,
                    interpret: Optional[bool] = None,
                    scale: Optional[float] = None,
                    softcap: Optional[float] = None,
                    window: Optional[int] = None) -> jax.Array:
    """Decode attention straight over the paged KV pool.

    q: [B, Hq, Dh] (one new token per sequence, already rope'd)
    k_pages, v_pages: [Hkv, n_pages, page, Dh] — the layer's HBM pool
    page_tables: [B, P] int32 page ids (rows padded with page 0)
    lengths: [B] int32 — tokens to attend per sequence (including current)
    Returns [B, Hq, Dh]. Sequences attend to tokens [0, length); with
    ``window`` only [max(0, length - window), length). The DMA kernel
    clamps its active block range, so out-of-window pages cost neither
    copies nor compute (sliding decode reads O(window) bytes); the simple
    kernel skips only their compute — its BlockSpec pipeline still copies
    every page. ``softcap`` tanh-caps scores pre-softmax (Gemma2);
    ``scale`` overrides rsqrt(Dh) (query_pre_attn_scalar).

    On a real TPU this runs the multi-page double-buffered DMA kernel
    above (``DYNAMO_TPU_PAGED_KERNEL=simple`` falls back to the
    BlockSpec-pipelined one-page-per-step kernel below, compiled — the
    variant proven on-chip before the DMA rewrite); off-TPU (and under
    ``interpret=True``) the simple kernel runs in interpreter mode so the
    CPU test suite exercises the same contract.
    """
    B, Hq, Dh = q.shape
    Hkv, n_pages, page, _ = k_pages.shape
    G = Hq // Hkv
    P = page_tables.shape[1]
    # The TPU kernel's prefetch chain assumes every lane covers >=1 block
    # (nblocks==0 would leave a DMA slot un-consumed and stall the next
    # active lane). Enforce the invariant here rather than relying on
    # callers to pad lengths.
    lengths = jnp.maximum(lengths, 1)
    if interpret is None:
        interpret = _interpret_default()
    variant = os.environ.get("DYNAMO_TPU_PAGED_KERNEL", "dma")
    if variant not in ("dma", "simple"):
        # repo convention: a typo'd env flag must not silently select the
        # slow path (cf. DYNAMO_TPU_DATAPLANE / DYNAMO_TPU_STORE)
        raise ValueError(f"DYNAMO_TPU_PAGED_KERNEL={variant!r} "
                         f"(expected dma|simple)")
    if not interpret and variant == "dma":
        q4 = q.reshape(B, Hkv, G, Dh)
        # DMA depth knob for on-chip tuning sweeps (perf_probe) — larger
        # blocks amortize DMA issue latency, smaller ones cut the tail
        # wasted on the final partial block. Validated like the sibling
        # DYNAMO_TPU_PAGED_KERNEL knob: a typo must fail loudly, not
        # surface as a ZeroDivisionError deep in the grid math.
        raw_ppb = os.environ.get("DYNAMO_TPU_PAGED_PPB", "8")
        try:
            ppb = int(raw_ppb)
        except ValueError:
            ppb = -1
        if not 1 <= ppb <= 64:
            raise ValueError(f"DYNAMO_TPU_PAGED_PPB={raw_ppb!r} "
                             f"(expected an integer in [1, 64])")
        out = _paged_attention_tpu(q4, k_pages, v_pages, page_tables,
                                   lengths, pages_per_block=ppb,
                                   scale=scale, softcap=softcap,
                                   window=window)
        return out.reshape(B, Hq, Dh)
    if scale is None:
        scale = 1.0 / math.sqrt(Dh)

    q4 = q.reshape(B, Hkv, G, Dh)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, P),
        in_specs=[
            pl.BlockSpec((1, Hkv, G, Dh), lambda b, p, pt, ln: (b, 0, 0, 0)),
            pl.BlockSpec((Hkv, 1, page, Dh),
                         lambda b, p, pt, ln: (0, pt[b, p], 0, 0)),
            pl.BlockSpec((Hkv, 1, page, Dh),
                         lambda b, p, pt, ln: (0, pt[b, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, Hkv, G, Dh),
                               lambda b, p, pt, ln: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Hkv, G, 1), jnp.float32),    # m
            pltpu.VMEM((Hkv, G, 1), jnp.float32),    # l
            pltpu.VMEM((Hkv, G, Dh), jnp.float32),   # acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, page=page,
                          softcap=softcap, window=window),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, G, Dh), q.dtype),
        interpret=interpret,
    )(page_tables, lengths, q4, k_pages, v_pages)
    return out.reshape(B, Hq, Dh)
