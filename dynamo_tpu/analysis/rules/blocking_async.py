"""Rule ``blocking-async``: no blocking calls inside ``async def``.

A blocking call on the event loop doesn't slow one request — it freezes
EVERY coroutine sharing the loop for its full duration: heartbeats miss,
leases expire, deadline timers fire late, and the chaos soak reads it as a
fleet-wide stall. The fix is ``await asyncio.sleep``, ``asyncio.to_thread``,
``run_in_executor``, or the async variant of the library.

Detection resolves import aliases through the module's import map, so
``import time as _time; _time.sleep(...)`` and ``from subprocess import
run; run(...)`` are both caught. Only the *immediate* enclosing function
matters: a sync helper defined inside an async def runs wherever it is
called from and is the callee's problem (same convention as the legacy
unbounded-await gate).

Calls made through ``asyncio.to_thread(fn, ...)`` / ``run_in_executor``
pass the function uncalled, so they never parse as a Call and need no
special-casing.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Module, Rule, register

#: canonical dotted names that park the loop when called directly
BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.getoutput",
    "requests.get", "requests.post", "requests.put", "requests.patch",
    "requests.delete", "requests.head", "requests.request",
    "urllib.request.urlopen",
    "socket.create_connection", "socket.getaddrinfo",
    "socket.gethostbyname",
    "os.system", "os.waitpid",
}


@register
class BlockingAsyncRule(Rule):
    name = "blocking-async"
    description = ("blocking call (time.sleep / subprocess / requests / "
                   "socket) directly inside an async def")

    def check_module(self, mod: Module) -> List[Finding]:
        extra = set(self.options.get("extra_calls", ()))
        blocking = BLOCKING_CALLS | extra
        out: List[Finding] = []
        dup: dict = {}
        for node in mod.nodes():
            if not isinstance(node, ast.Call):
                continue
            canonical = mod.resolve_call(node)
            if canonical not in blocking:
                continue
            fn = mod.enclosing_function(node)
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            # discriminate repeats so one baseline entry can never
            # grandfather a second, newly added call of the same shape
            key = f"{fn.name}:{canonical}"
            n = dup.get(key, 0) + 1
            dup[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=(f"{canonical}() blocks the event loop inside "
                         f"async def {fn.name} — use the async equivalent "
                         f"or asyncio.to_thread()"),
                key=key))
        return out
