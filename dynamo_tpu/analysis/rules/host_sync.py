"""Rule ``host-sync``: implicit device→host transfers on dispatch paths.

Every ``int()`` / ``float()`` / ``bool()`` / ``np.asarray()`` / ``.item()``
/ ``.tolist()`` / ``jax.device_get()`` / ``block_until_ready()`` applied to
a JAX device array blocks the host until the device flushes its dispatch
queue and ships the buffer — on the decode path that is a per-token host
round-trip, the exact cost the ROADMAP blames for decode sitting below
baseline. The legacy statement-matching dynalint could not see these: the
sync is a property of *where the value came from*, not of the statement.

This rule runs the :mod:`..dataflow` device-taint lattice per module:
taint seeds are jitted-call results (including one-level function
summaries, so ``packed = self._run_decode_program(...)`` is tainted),
``jnp.*``/``jax.*`` constructors, and device-resident attributes
(``self.k_pool``, ``s.key``, anything assigned a device value anywhere in
the module — extendable via the ``device_attrs`` option). A flagged site
is either a bug (hoist/batch the fetch) or a *designed* transfer, which
gets a ``# dynalint: ok(host-sync) <why>`` suppression; the suppressed
inventory doubles as the decode path's documented transfer budget
(``python scripts/dynalint.py --report host-sync``).

Scoped to the JAX dirs (engine/ops/parallel/models): host-side numpy code
elsewhere would only produce noise.
"""

from __future__ import annotations

from typing import Dict, List

from ..core import Finding, Module, Rule, register
from ..dataflow import get_device_taint

SCOPE = [
    "dynamo_tpu/engine",
    "dynamo_tpu/ops",
    "dynamo_tpu/parallel",
    "dynamo_tpu/models",
    # the KV-paging plane moves pages d2h/h2d by design — every one of
    # its transfer sites must carry a reasoned suppression (they ARE the
    # documented paging budget), and a new un-reasoned sync still fails
    "dynamo_tpu/llm/kvpage",
    "dynamo_tpu/llm/kvbm/transfer.py",
    # the model-mobility swap path enqueues h2d weight slabs async and
    # barriers exactly once per swap (the annotated cutover); any other
    # sync it grows is a serving-path regression
    "dynamo_tpu/fleet/mobility",
]


@register
class HostSyncRule(Rule):
    name = "host-sync"
    description = ("device-tainted value flows into int/float/bool/"
                   "np.asarray/.item/.tolist/device_get/block_until_ready "
                   "— an implicit device->host sync")
    scope = list(SCOPE)

    def check_module(self, mod: Module) -> List[Finding]:
        opts = dict(self.options or {})
        if mod.rel.startswith("dynamo_tpu/llm/kvpage"):
            # the paged runner consumes jitted programs BUILT in
            # programs.py; per-module attribute scanning cannot see those
            # assignments, so name them — their call results are device
            # arrays, and every fetch of one must carry a reasoned
            # suppression (the paging plane's transfer budget)
            opts["jitfn_attrs"] = tuple(opts.get("jitfn_attrs", ())) + (
                "embed", "qkv", "attn_hot", "attn_cold", "layer_out",
                "head")
        taint = get_device_taint(mod, opts)
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        for func in taint.top_level_functions():
            qual = taint.qualname(func)
            for hit in taint.sink_hits(func, qual):
                key = f"{qual}:{hit.label}"
                n = dup.get(key, 0) + 1
                dup[key] = n
                if n > 1:
                    key = f"{key}#{n}"
                out.append(Finding(
                    rule=self.name, path=mod.rel, line=hit.node.lineno,
                    message=(f"{hit.label} on a device array in {qual}() "
                             f"forces a device->host sync — batch/hoist "
                             f"the fetch, or suppress with the reason it "
                             f"is a designed transfer"),
                    key=key))
        out.sort(key=lambda f: f.line)
        return out
