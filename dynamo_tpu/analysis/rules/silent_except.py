"""Rule ``swallowed-exception``: broad excepts must leave a trace.

``except Exception: pass`` in serving code turns a real failure (store
session lost, KV block corrupt, task cancelled mid-transfer) into silence:
the request above it limps on or hangs, and the operator debugging the
fleet sees *nothing*. A broad handler must do at least one observable
thing: log, mark the span, bump a counter, re-raise, or capture the bound
exception object somewhere.

Flagged: ``except:``, ``except Exception``, ``except BaseException``
(alone or in a tuple) whose body contains none of

- a ``raise`` statement,
- a call to a logging / traceback / metrics / span primitive
  (``log.warning``, ``counter.inc()``, ``span.fail(...)``, ...),
- any use of the bound exception name (``except Exception as e`` where
  ``e`` flows into a message, a state field, or a response).

The repo's pre-existing ``# noqa: BLE001 - <reason>`` annotations on the
except line are honored as suppressions when they carry a reason — they
are the same contract under an older spelling. New suppressions should use
``# dynalint: ok(swallowed-exception) <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import List

from ..core import Finding, Module, Rule, register

BROAD = {"Exception", "BaseException"}
#: call names (method attr or bare function) that count as observing
OBSERVE_CALLS = {
    # logging
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log", "print", "print_exc", "format_exc",
    # metrics
    "inc", "observe",
    # spans / request bookkeeping
    "fail", "finish", "event", "record_exception", "set_error", "annotate",
}
NOQA_BLE = re.compile(r"#\s*noqa:\s*BLE001\b\s*-?\s*(.*)")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(n, ast.Name) and n.id in BROAD for n in names)


def _walk_no_defs(nodes):
    """Walk statements without descending into nested function/class defs —
    their bodies run later (or never), so they don't observe THIS except."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _observes(handler: ast.ExceptHandler) -> bool:
    bound = handler.name  # 'e' in `except Exception as e`, else None
    for node in _walk_no_defs(handler.body):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            if name in OBSERVE_CALLS:
                return True
        if (bound and isinstance(node, ast.Name) and node.id == bound):
            return True
    return False


@register
class SilentExceptRule(Rule):
    name = "swallowed-exception"
    description = ("broad except with no logging, span, counter, re-raise, "
                   "or use of the caught exception")

    def check_module(self, mod: Module) -> List[Finding]:
        out: List[Finding] = []
        seen_keys: dict = {}
        for node in mod.nodes():
            if not isinstance(node, ast.ExceptHandler) or not _is_broad(node):
                continue
            if _observes(node):
                continue
            # legacy inline justification: `except Exception:  # noqa:
            # BLE001 - reason` — same contract, older spelling
            line = mod.lines[node.lineno - 1] \
                if node.lineno <= len(mod.lines) else ""
            m = NOQA_BLE.search(line)
            if m and m.group(1).strip():
                continue
            fn = mod.enclosing_function(node)
            where = fn.name if fn is not None else "<module>"
            typ = "bare" if node.type is None else "Exception"
            key = f"{where}:{typ}"
            n = seen_keys.get(key, 0) + 1
            seen_keys[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=(f"broad except in {where} swallows the exception "
                         f"silently — log it, bump a counter, mark the "
                         f"span, or re-raise"),
                key=key))
        out.sort(key=lambda f: f.line)
        return out
