"""Rule ``tracer-leak``: side effects escaping a jit-traced function.

A write to ``self.*``, a global, a nonlocal of an enclosing scope, or a
subscript of a closed-over object from inside a jit-traced function runs
ONCE at trace time with a tracer value, not on every call: the stored
tracer either poisons later host code with a ``TracerLeakError`` deep in
unrelated stacks, or silently freezes the first call's abstract value.
The engine's discipline is that traced code is pure — persistent state
(pools, PRNG keys, counts) is threaded through arguments and results.

Traced functions are discovered by the dataflow layer: ``@jax.jit`` /
``@partial(jax.jit, ...)`` decorated defs, defs wrapped by name in a
``jax.jit(f)`` call, and lambdas passed to jit wrappers. Nested defs
inside a traced body trace too (scan/vmap bodies) and are scanned with
the traced scope's locals visible — writes targeting names bound *within*
the traced region are fine; only stores escaping it are flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import Finding, Module, Rule, register
from ..dataflow import get_device_taint, iter_scope_nodes

SCOPE = [
    "dynamo_tpu/engine",
    "dynamo_tpu/ops",
    "dynamo_tpu/parallel",
    "dynamo_tpu/models",
]


def _bound_names(func: ast.AST) -> Set[str]:
    """Names bound inside one function scope: params + assignments +
    loop/with/comprehension targets + nested def names."""
    out: Set[str] = set()
    args = getattr(func, "args", None)
    if args is not None:
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            out.add(a.arg)
        if args.vararg:
            out.add(args.vararg.arg)
        if args.kwarg:
            out.add(args.kwarg.arg)
    body = func.body if isinstance(func.body, list) else [ast.Expr(func.body)]
    # scope-pruned walk: a name bound only INSIDE a nested def is not
    # bound here (treating it as local would mask a leak through it)
    for node in iter_scope_nodes(body):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            out.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store):
            out.add(node.id)
        elif isinstance(node, ast.NamedExpr):
            out.add(node.target.id)
    return out


@register
class TracerLeakRule(Rule):
    name = "tracer-leak"
    description = ("write to self.*/globals/nonlocals (or a closed-over "
                   "object) from inside a jit-traced function — the "
                   "stored tracer escapes the trace")
    scope = list(SCOPE)

    def check_module(self, mod: Module) -> List[Finding]:
        taint = get_device_taint(mod, self.options)
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        parents = mod.parents()
        # only analyze OUTERMOST traced functions: nested traced defs are
        # covered by their enclosing traced scope's scan
        for func in sorted(taint.traced, key=lambda f: f.lineno):
            enclosing = parents.get(func)
            inside_traced = False
            while enclosing is not None:
                if enclosing in taint.traced:
                    inside_traced = True
                    break
                enclosing = parents.get(enclosing)
            if inside_traced:
                continue
            qual = taint.qualname(func) if hasattr(func, "name") \
                else f"<lambda>@{func.lineno}"
            self._scan(mod, func, [_bound_names(func)], qual, out, dup)
        out.sort(key=lambda f: f.line)
        return out

    def _scan(self, mod: Module, func: ast.AST, bound_stack: List[Set[str]],
              qual: str, out: List[Finding], dup: Dict[str, int]) -> None:
        body = func.body if isinstance(func.body, list) \
            else [ast.Expr(func.body)]
        local = set().union(*bound_stack)
        # scope-pruned, visit-once walk: nested defs recurse with their own
        # frame (ast.walk would re-scan their bodies under the OUTER frame
        # and double-report every leak found by the recursion)
        for node in iter_scope_nodes(body):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan(mod, node, bound_stack + [_bound_names(node)],
                           qual, out, dup)
            elif isinstance(node, ast.Global):
                for name in node.names:
                    self._emit(mod, node.lineno, qual, f"global {name}",
                               out, dup)
            elif isinstance(node, ast.Nonlocal):
                # nonlocal binding INSIDE the traced region is pure wrt the
                # trace boundary; one reaching past it escapes
                for name in node.names:
                    if not any(name in frame for frame in bound_stack[:-1]):
                        self._emit(mod, node.lineno, qual,
                                   f"nonlocal {name}", out, dup)
            elif isinstance(node, (ast.Attribute, ast.Subscript)) \
                    and isinstance(node.ctx, ast.Store):
                base = node
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id not in local:
                    what = (f"{base.id}.{node.attr}"
                            if isinstance(node, ast.Attribute)
                            else f"{base.id}[...]")
                    self._emit(mod, node.lineno, qual, what, out, dup)

    def _emit(self, mod: Module, line: int, qual: str, what: str,
              out: List[Finding], dup: Dict[str, int]) -> None:
        key = f"{qual}:{what}"
        n = dup.get(key, 0) + 1
        dup[key] = n
        if n > 1:
            key = f"{key}#{n}"
        out.append(Finding(
            rule=self.name, path=mod.rel, line=line,
            message=(f"write to {what} inside jit-traced {qual} runs at "
                     f"TRACE time and leaks the tracer — thread state "
                     f"through arguments/results instead"),
            key=key))
