"""Rule ``recompile-hazard``: per-request shapes/values reaching jit.

XLA compiles one program per (shape, dtype, static-arg value) signature.
The engine's defense is the power-of-two bucketing discipline
(``_buckets`` / ``_bucket`` in engine.py, ``SpecConfig.bucket`` in
spec.py): every per-request length is rounded to a bucket before it can
shape a dispatch. Two hazard classes slip past review:

1. **unbucketed length** — a value derived from ``len(...)`` or
   ``x.shape[i]`` that reaches a jitted call without passing through a
   bucketing helper, either by sizing an array constructor's shape
   (``np.zeros((n, ...))``) or by landing in a ``static_argnums`` /
   ``static_argnames`` position. Each distinct length is a fresh XLA
   compile mid-serving.
2. **config-like traced arg** — a jit def taking ``cfg`` / ``mesh`` /
   ``*_impl``-style parameters without declaring them static: configs are
   unhashable (trace error at best) and every distinct value recompiles.
   The engine's idiom is closing over config instead of passing it.

Both checks are heuristic by design (AST-only, intra-procedural): they
encode the repo's bucketing contract, not the full JAX semantics. A
flagged site that is deliberately per-value compiled (e.g. a per-layer
``static_argnums`` gather, bounded by the layer count) carries a
``# dynalint: ok(recompile-hazard) <why>`` suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, Module, Rule, register
from ..dataflow import (JITFN, DeviceTaint, _binding_pairs,
                        get_device_taint, iter_scope_nodes,
                        iter_scope_statements)

SCOPE = [
    "dynamo_tpu/engine",
    "dynamo_tpu/ops",
    "dynamo_tpu/parallel",
    "dynamo_tpu/models",
]

#: parameter names that smell like configuration, not array data
CONFIG_PARAM_NAMES = {"cfg", "config", "mesh", "spec", "impl", "mode"}
CONFIG_PARAM_SUFFIXES = ("_cfg", "_config", "_impl", "_mode")

#: array constructors whose first argument is a shape
SHAPE_CTORS = {
    "numpy.zeros", "numpy.ones", "numpy.empty", "numpy.full",
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.empty",
    "jax.numpy.full",
}

RAW = "rawlen"        # local tag: unbucketed per-request length
RAWSHAPED = "rawarr"  # array whose shape was built from a RAW length


def _is_config_param(name: str) -> bool:
    return name in CONFIG_PARAM_NAMES or name.endswith(CONFIG_PARAM_SUFFIXES)


class _RawLen:
    """Mini-lattice over one function: which locals hold raw lengths."""

    def __init__(self, mod: Module, func: ast.AST, bucket_helpers: Set[str]):
        self.mod = mod
        self.bucket_helpers = bucket_helpers
        self.env: Dict[str, str] = {}
        for _ in range(3):
            changed = False
            for stmt in iter_scope_statements(func.body):
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                for target, value, _via in _binding_pairs(stmt):
                    tag = self.tag(value)
                    if tag is None:
                        continue
                    for t in ast.walk(target):
                        if isinstance(t, ast.Name) \
                                and self.env.get(t.id) != tag:
                            self.env[t.id] = tag
                            changed = True
            if not changed:
                break

    def _sanitized(self, call: ast.Call) -> bool:
        name = self.mod.resolve_call(call)
        last = name.rsplit(".", 1)[-1]
        return "bucket" in last or last in self.bucket_helpers

    def tag(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Call):
            if self._sanitized(expr):
                return None
            resolved = self.mod.resolve_call(expr)
            if resolved == "len":
                return RAW
            if resolved in SHAPE_CTORS and expr.args:
                if self.tag(expr.args[0]) == RAW:
                    return RAWSHAPED
            if resolved in ("max", "min", "sum", "int", "abs"):
                for a in expr.args:
                    if self.tag(a) == RAW:
                        return RAW
            return None
        if isinstance(expr, ast.Name):
            return self.env.get(expr.id)
        if isinstance(expr, ast.Subscript):
            # x.shape[i] is a raw per-request dimension
            v = expr.value
            if isinstance(v, ast.Attribute) and v.attr == "shape":
                return RAW
            return None
        if isinstance(expr, ast.BinOp):
            lt, rt = self.tag(expr.left), self.tag(expr.right)
            if RAW in (lt, rt):
                return RAW
            return None
        if isinstance(expr, ast.UnaryOp):
            return self.tag(expr.operand)
        if isinstance(expr, ast.IfExp):
            return self.tag(expr.body) or self.tag(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                if self.tag(e) == RAW:
                    return RAW
            return None
        if isinstance(expr, (ast.GeneratorExp, ast.ListComp)):
            return self.tag(expr.elt)
        return None


@register
class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    description = ("per-request length reaches a jitted call unbucketed, "
                   "or a jit def takes config-like args without "
                   "static_argnums/static_argnames")
    scope = list(SCOPE)

    def check_module(self, mod: Module) -> List[Finding]:
        taint = get_device_taint(mod, self.options)
        bucket_helpers = set(self.options.get("bucket_helpers", ()))
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        statics = self._jit_static_map(mod, taint)
        for func, argnums, argnames, wrapper_line in statics["defs"]:
            self._check_config_args(mod, func, argnums, argnames,
                                    wrapper_line, taint, out, dup)
        # EVERY function scope — closures included (the nested-def idiom
        # is exactly where per-request staging code lives) — each with its
        # own raw-length env, via a visit-once scope-pruned walk
        for func in taint._functions:
            qual = taint.qualname(func)
            raw = _RawLen(mod, func, bucket_helpers)
            env = taint._function_env(func)
            for node in iter_scope_nodes(func.body):
                if isinstance(node, ast.Call):
                    self._check_call(mod, node, env, raw, statics,
                                     taint, qual, out, dup)
        out.sort(key=lambda f: f.line)
        return out

    # -- jit def discovery -------------------------------------------------
    def _jit_static_map(self, mod: Module, taint: DeviceTaint) -> dict:
        """Traced defs with their static_argnums/static_argnames, plus the
        name->def map for call-site static matching."""
        defs = []
        by_name = {}
        parents = mod.parents()
        for func in taint.traced:
            if not hasattr(func, "name"):
                continue
            wrapper = None
            for dec in getattr(func, "decorator_list", []):
                if isinstance(dec, ast.Call) and taint.is_jit_wrap_call(dec):
                    wrapper = dec
            if wrapper is None:
                # wrapped by name: find jax.jit(f, ...) call
                for node in mod.nodes():
                    if isinstance(node, ast.Call) \
                            and taint.is_jit_wrap_call(node) and node.args \
                            and isinstance(node.args[0], ast.Name) \
                            and node.args[0].id == func.name:
                        wrapper = node
                        break
            argnums, argnames = self._statics_of(wrapper)
            line = wrapper.lineno if wrapper is not None else func.lineno
            defs.append((func, argnums, argnames, line))
            by_name[func.name] = (func, argnums, argnames)
            _ = parents
        return {"defs": defs, "by_name": by_name}

    @staticmethod
    def _statics_of(wrapper: Optional[ast.Call]):
        argnums: Set[int] = set()
        argnames: Set[str] = set()
        if wrapper is not None:
            for kw in wrapper.keywords:
                if kw.arg == "static_argnums":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) \
                                and isinstance(n.value, int):
                            argnums.add(n.value)
                elif kw.arg == "static_argnames":
                    for n in ast.walk(kw.value):
                        if isinstance(n, ast.Constant) \
                                and isinstance(n.value, str):
                            argnames.add(n.value)
        return argnums, argnames

    # -- check 2: config-like traced args ----------------------------------
    def _check_config_args(self, mod: Module, func: ast.AST, argnums,
                           argnames, line: int, taint: DeviceTaint,
                           out: List[Finding], dup: Dict[str, int]) -> None:
        params = [a.arg for a in func.args.args]
        qual = taint.qualname(func)
        for i, p in enumerate(params):
            if not _is_config_param(p):
                continue
            if i in argnums or p in argnames:
                continue
            key = f"{qual}:config-arg:{p}"
            if key in dup:
                continue
            dup[key] = 1
            out.append(Finding(
                rule=self.name, path=mod.rel, line=func.lineno,
                message=(f"jit-traced {qual}() takes config-like arg "
                         f"{p!r} as a TRACED value — every distinct "
                         f"config recompiles (or fails to hash); mark it "
                         f"static_argnums/static_argnames or close over "
                         f"it"),
                key=key))

    # -- check 1: unbucketed lengths at jit call sites ---------------------
    def _check_call(self, mod: Module, call: ast.Call, env, raw: _RawLen,
                    statics: dict, taint: DeviceTaint, qual: str,
                    out: List[Finding], dup: Dict[str, int]) -> None:
        f = call.func
        is_jit_call = False
        callee = None
        if isinstance(f, (ast.Name, ast.Attribute, ast.Subscript)):
            if taint.evaluate(f, env) == JITFN:
                is_jit_call = True
            if isinstance(f, ast.Name):
                callee = f.id
            elif isinstance(f, ast.Attribute):
                callee = f.attr
        if not is_jit_call:
            return
        known = statics["by_name"].get(callee)
        for i, arg in enumerate(call.args):
            t = raw.tag(arg)
            if t == RAWSHAPED:
                self._emit(mod, call.lineno, qual, callee or "<jit>",
                           "array shaped by an unbucketed length", out,
                           dup)
            elif t == RAW and known is not None:
                _func, argnums, argnames = known
                params = [a.arg for a in _func.args.args]
                pname = params[i] if i < len(params) else None
                if i in argnums or (pname and pname in argnames):
                    self._emit(mod, call.lineno, qual, callee or "<jit>",
                               f"unbucketed length in static arg "
                               f"position {i}", out, dup)

    def _emit(self, mod: Module, line: int, qual: str, callee: str,
              why: str, out: List[Finding], dup: Dict[str, int]) -> None:
        key = f"{qual}:{callee}:{why.split()[0]}"
        n = dup.get(key, 0) + 1
        dup[key] = n
        if n > 1:
            key = f"{key}#{n}"
        out.append(Finding(
            rule=self.name, path=mod.rel, line=line,
            message=(f"call to jitted {callee}() in {qual}() passes "
                     f"{why} — every distinct size compiles a fresh XLA "
                     f"program; round through the power-of-two bucket "
                     f"helpers first"),
            key=key))
