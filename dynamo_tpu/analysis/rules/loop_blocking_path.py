"""Rule ``loop-blocking-path``: blocking calls REACHED from async code
through module-local sync helpers.

``blocking-async`` catches ``time.sleep`` written directly inside an
``async def``; this rule catches the one-hop-removed version that gate
cannot see: an async handler calling a module-local sync helper (or a
chain of them) whose body parks the loop — the classic refactor where a
blocking call is "cleaned up" into a helper function and silently stops
being flagged. Detection builds the module-local call graph (plain
``helper(...)`` calls to module-level functions plus ``self.method(...)``
within a class), computes which sync functions transitively reach a
blocking call, and flags the async-side CALL SITE of any such helper,
naming the chain.

Boundaries, deliberately:

- only the module-local graph — cross-module reachability would need
  whole-program analysis and its false-positive budget;
- a ``lambda`` is an executor boundary: ``run_in_executor(None, lambda:
  build())`` runs off-loop, so calls inside lambdas are never attributed
  to the enclosing async def (and functions passed UNCALLED to
  ``to_thread``/``run_in_executor``/``spawn_blocking`` never parse as
  calls at all);
- direct blocking calls inside the async def itself are excluded here —
  that is exactly ``blocking-async``'s finding, and double-reporting
  would force paired suppressions.

The blocking set is shared with ``blocking-async`` (``time.sleep``,
subprocess, requests, ``urllib.request.urlopen``, socket resolution /
connect, ``os.system``...) plus this rule's own ``extra_calls`` option —
wire sync store/file I/O wrappers there as they appear.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, Module, Rule, register
from .blocking_async import BLOCKING_CALLS

FuncNode = ast.AST          # FunctionDef | AsyncFunctionDef


def _owner(mod: Module, node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda — unlike
    ``Module.enclosing_function``, a Lambda counts (it is the executor-
    thunk boundary this rule must not cross)."""
    parents = mod.parents()
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return cur
    return None


def _enclosing_class(mod: Module, node: ast.AST) -> Optional[ast.ClassDef]:
    parents = mod.parents()
    cur = node
    while cur in parents:
        cur = parents[cur]
        if isinstance(cur, ast.ClassDef):
            return cur
        # keep walking through function hops: a def nested inside a
        # method closes over the same ``self``, so its ``self.x()``
        # calls resolve against the same class
    return None


@register
class LoopBlockingPathRule(Rule):
    name = "loop-blocking-path"
    description = ("blocking call reached from an async def through "
                   "module-local sync helpers (the hop blocking-async "
                   "cannot see)")

    def check_module(self, mod: Module) -> List[Finding]:
        blocking = BLOCKING_CALLS | set(self.options.get("extra_calls", ()))
        funcs = [n for n in mod.nodes()
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        if not funcs:
            return []
        # resolution maps: module-level `helper(...)` and `self.method(...)`
        toplevel: Dict[str, FuncNode] = {}
        methods: Dict[Tuple[ast.ClassDef, str], FuncNode] = {}
        klass_of: Dict[FuncNode, Optional[ast.ClassDef]] = {}
        for fn in funcs:
            klass = _enclosing_class(mod, fn)
            klass_of[fn] = klass
            if _owner(mod, fn) is not None:
                continue     # nested def: not resolvable by bare name
            if klass is None:
                toplevel.setdefault(fn.name, fn)
            else:
                methods.setdefault((klass, fn.name), fn)

        def resolve_local(call: ast.Call, caller: FuncNode
                          ) -> Optional[FuncNode]:
            f = call.func
            if isinstance(f, ast.Name):
                return toplevel.get(f.id)
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"):
                klass = klass_of.get(caller)
                if klass is not None:
                    return methods.get((klass, f.attr))
            return None

        # per-function call lists (calls OWNED by the function — nested
        # defs and lambdas keep their own)
        calls_of: Dict[FuncNode, List[ast.Call]] = {fn: [] for fn in funcs}
        for node in mod.nodes():
            if isinstance(node, ast.Call):
                own = _owner(mod, node)
                if own in calls_of:
                    calls_of[own].append(node)

        # which sync functions reach a blocking call, and through what
        # chain: {fn: (canonical blocking name, [helper names walked])}
        reach: Dict[FuncNode, Optional[Tuple[str, List[str]]]] = {}

        def reaches(fn: FuncNode, stack: List[FuncNode]
                    ) -> Optional[Tuple[str, List[str]]]:
            if fn in reach:
                return reach[fn]
            if fn in stack:
                return None          # recursion: already being resolved
            for call in calls_of[fn]:
                canonical = mod.resolve_call(call)
                if canonical in blocking:
                    reach[fn] = (canonical, [fn.name])
                    return reach[fn]
            for call in calls_of[fn]:
                callee = resolve_local(call, fn)
                if callee is None or callee is fn \
                        or isinstance(callee, ast.AsyncFunctionDef):
                    continue
                sub = reaches(callee, stack + [fn])
                if sub is not None:
                    reach[fn] = (sub[0], [fn.name] + sub[1])
                    return reach[fn]
            reach[fn] = None
            return None

        out: List[Finding] = []
        dup: Dict[str, int] = {}
        for fn in funcs:
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for call in calls_of[fn]:
                callee = resolve_local(call, fn)
                if callee is None \
                        or isinstance(callee, ast.AsyncFunctionDef):
                    continue
                hit = reaches(callee, [])
                if hit is None:
                    continue
                canonical, chain = hit
                via = " -> ".join(chain)
                key = f"{fn.name}->{chain[0]}:{canonical}"
                n = dup.get(key, 0) + 1
                dup[key] = n
                if n > 1:
                    key = f"{key}#{n}"
                out.append(Finding(
                    rule=self.name, path=mod.rel, line=call.lineno,
                    message=(f"async def {fn.name} calls {chain[0]}() "
                             f"which reaches {canonical}() "
                             f"(via {via}) — this blocks the event loop; "
                             f"run the helper under asyncio.to_thread / "
                             f"an executor, or use the async variant"),
                    key=key))
        return out
