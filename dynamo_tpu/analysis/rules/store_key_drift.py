"""Rule ``store-key-drift``: the dynstore keyspace cannot rot.

The store keyspace is an API between processes that restart
independently — a producer writing ``planner/{ns}/decisions/…`` and a
consumer watching ``planner/{ns}/decision/…`` is a silent cross-version
outage, and the keys are mostly built via f-strings a literal grep cannot
see. This gate resolves every store API call site's **key argument**
through the def-use layer back to its origin and checks it against the
central registry (:mod:`dynamo_tpu.runtime.keyspace`):

1. **producer/consumer → registry**: each ``put``/``get``/``get_prefix``/
   ``watch_prefix``/``delete``/``create``/``q_push``/``q_pull``/``q_len``
   call on a store handle must resolve to a registered key family — via a
   registered helper (``decisions_prefix(ns)``), a registered constant
   (``MODEL_PREFIX``), or a literal head that starts with a registered
   prefix. An unresolvable key expression is itself a finding: route it
   through a keyspace helper (or suppress with the reason it is
   test-local).
2. **registry → code**: every registered family must still have at least
   one resolved call site — a stale entry is a keyspace nobody serves.
3. **docs**: ``docs/keyspace.md`` must match the generated registry
   rendering byte-for-byte (``python -m dynamo_tpu.runtime.keyspace
   --write``). The rendering also embeds the wire-field table, so one
   regenerate refreshes both protocol surfaces.

Store handles are recognized structurally: the call's receiver chain ends
in an attribute/name spelled ``store``, ``client`` or ``ctl`` (the repo's
three StoreClient spellings); the store client/server modules themselves
are exempt (they DEFINE the ops). ``publish``/``subscribe`` subjects are
event-plane names, not keys, and stay out of scope.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module, Rule, register
from ..dataflow import class_attr_bindings, scope_bindings

DOC_REL = "docs/keyspace.md"
REGISTRY_REL = "dynamo_tpu/runtime/keyspace.py"

#: ops whose FIRST positional arg (or key=/prefix=/queue= kwarg) is a key
KEY_OPS = {"put", "get", "get_prefix", "delete", "create", "watch_prefix",
           "q_push", "q_pull", "q_len"}

#: receiver spellings that mean "this is a StoreClient"
STORE_BASES = {"store", "client", "ctl"}

#: modules that define the store protocol itself (their put/get are the
#: implementation, not keyspace producers/consumers)
EXEMPT = {
    "dynamo_tpu/runtime/store_client.py",
    "dynamo_tpu/runtime/store_server.py",
    "dynamo_tpu/runtime/keyspace.py",
    # the sharded client IS the routing layer: its put/get/... bodies
    # forward caller-resolved keys through classify_key — the call
    # sites behind it are the producers/consumers this rule gates
    "dynamo_tpu/runtime/scale/shards.py",
}

KEY_KWARGS = {"key", "prefix", "queue"}


def _receiver_is_store(call: ast.Call) -> bool:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in KEY_OPS:
        return False
    base = f.value
    if isinstance(base, ast.Attribute):
        return base.attr in STORE_BASES
    if isinstance(base, ast.Name):
        return base.id in STORE_BASES
    return False


def _key_arg(call: ast.Call) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg in KEY_KWARGS:
            return kw.value
    if call.args:
        return call.args[0]
    return None


class _Resolver:
    """Resolve a key expression to ('family', name) / ('literal', head) /
    None, chasing local and self-attribute bindings one function deep."""

    MAX_DEPTH = 6

    def __init__(self, mod: Module, registry):
        self.mod = mod
        self.reg = registry

    def resolve(self, expr: ast.expr, func: Optional[ast.AST],
                depth: int = 0) -> Optional[Tuple[str, str]]:
        if depth > self.MAX_DEPTH:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return ("literal", expr.value)
        if isinstance(expr, ast.JoinedStr):
            head = ""
            for part in expr.values:
                if isinstance(part, ast.Constant):
                    head += str(part.value)
                    continue
                if head:
                    return ("literal", head)
                # leading placeholder: the head IS the placeholder's origin
                inner = part.value if isinstance(
                    part, ast.FormattedValue) else part
                return self.resolve(inner, func, depth + 1)
            return ("literal", head)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return self.resolve(expr.left, func, depth + 1)
        if isinstance(expr, ast.Await):
            return self.resolve(expr.value, func, depth + 1)
        if isinstance(expr, ast.Call):
            name = self.mod.resolve_call(expr).rsplit(".", 1)[-1]
            if name in self.reg.HELPER_INDEX:
                return ("family", self.reg.HELPER_INDEX[name].name)
            if isinstance(expr.func, ast.Attribute):
                # keys handed back by the store itself: iterating
                # `store.get_prefix(X)` yields keys under X
                if expr.func.attr in KEY_OPS:
                    karg = _key_arg(expr)
                    if karg is not None:
                        r = self.resolve(karg, func, depth + 1)
                        if r is not None:
                            return r
                # container projections: self.queues.get(...) / .values()
                # ('get' is ambiguous with the store op — the fallthrough
                # order tries both readings)
                if expr.func.attr in ("get", "values", "keys", "items",
                                      "pop"):
                    return self.resolve(expr.func.value, func, depth + 1)
            return None
        if isinstance(expr, ast.DictComp):
            return self.resolve(expr.value, func, depth + 1)
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp)):
            return self.resolve(expr.elt, func, depth + 1)
        if isinstance(expr, (ast.Tuple, ast.List)):
            for e in expr.elts:
                r = self.resolve(e, func, depth + 1)
                if r is not None:
                    return r
            return None
        if isinstance(expr, ast.Attribute):
            if expr.attr in self.reg.CONSTANT_INDEX:
                return ("family", self.reg.CONSTANT_INDEX[expr.attr].name)
            # self.<attr>: chase the class-level binding
            if isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and func is not None:
                cls = self._enclosing_class(func)
                if cls is not None:
                    for value, _via in class_attr_bindings(cls).get(
                            expr.attr, []):
                        r = self.resolve(value, None, depth + 1)
                        if r is not None:
                            return r
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.reg.CONSTANT_INDEX:
                return ("family", self.reg.CONSTANT_INDEX[expr.id].name)
            # imported constant under its own name
            imported = self.mod.imports().get(expr.id, "")
            tail = imported.rsplit(".", 1)[-1]
            if tail in self.reg.CONSTANT_INDEX:
                return ("family", self.reg.CONSTANT_INDEX[tail].name)
            if func is not None:
                for value, via in scope_bindings(func).get(expr.id, []):
                    r = self.resolve(value, func, depth + 1)
                    if r is not None:
                        return r
            return None
        if isinstance(expr, ast.Subscript):
            return self.resolve(expr.value, func, depth + 1)
        if isinstance(expr, ast.IfExp):
            return (self.resolve(expr.body, func, depth + 1)
                    or self.resolve(expr.orelse, func, depth + 1))
        return None

    def _enclosing_class(self, func: ast.AST) -> Optional[ast.ClassDef]:
        parents = self.mod.parents()
        cur = func
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, ast.ClassDef):
                return cur
        return None


@register
class StoreKeyDriftRule(Rule):
    name = "store-key-drift"
    description = ("store API call whose key does not resolve to the "
                   "keyspace registry, a stale registry family, or "
                   "docs/keyspace.md out of sync")

    def check_repo(self, modules: List[Module], repo: str) -> List[Finding]:
        from ...runtime import keyspace
        out: List[Finding] = []
        used: Set[str] = set()
        dup: Dict[str, int] = {}
        for mod in modules:
            if mod.rel in EXEMPT:
                continue
            resolver = _Resolver(mod, keyspace)
            for node in mod.nodes():
                if not (isinstance(node, ast.Call)
                        and _receiver_is_store(node)):
                    continue
                key_expr = _key_arg(node)
                if key_expr is None:
                    continue
                func = mod.enclosing_function(node)
                resolved = resolver.resolve(key_expr, func)
                op = node.func.attr
                if resolved is None:
                    self._emit(out, dup, mod, node, op,
                               "key expression does not resolve to the "
                               "keyspace registry — build it with a "
                               "registered helper/constant "
                               "(runtime/keyspace.py)")
                    continue
                kind, value = resolved
                if kind == "family":
                    used.add(value)
                    continue
                fam = keyspace.family_for_literal(value)
                if fam is None:
                    self._emit(out, dup, mod, node, op,
                               f"literal key head {value!r} matches no "
                               f"registered prefix — register the family "
                               f"in runtime/keyspace.py")
                else:
                    used.add(fam.name)
        # registry -> code
        for name, fam in sorted(keyspace.KEYSPACE.items()):
            if name not in used:
                out.append(Finding(
                    rule=self.name, path=REGISTRY_REL, line=0,
                    message=(f"key family {name!r} ({fam.pattern}) has no "
                             f"resolved store call site in scanned code — "
                             f"delete the entry or fix the resolution"),
                    key=f"stale:{name}"))
        # docs — the wire-field table is read via AST (wire_field_drift's
        # loader) so the doc compare never imports wire.py/msgpack at
        # lint time; without wire.py in the scanned set the compare is
        # skipped (the wire rule reports that situation itself)
        from .wire_field_drift import load_registry
        wire_reg = load_registry(modules)
        doc_path = os.path.join(repo, DOC_REL)
        if not os.path.exists(doc_path):
            out.append(Finding(
                rule=self.name, path=DOC_REL, line=0,
                message=("docs/keyspace.md missing — generate it: "
                         "python -m dynamo_tpu.runtime.keyspace --write"),
                key="doc:missing"))
        elif wire_reg is not None:
            with open(doc_path, "r", encoding="utf-8") as f:
                if f.read() != keyspace.render_markdown(
                        wire_fields=wire_reg["fields"]):
                    out.append(Finding(
                        rule=self.name, path=DOC_REL, line=0,
                        message=("docs/keyspace.md differs from the "
                                 "generated registry — regenerate: python "
                                 "-m dynamo_tpu.runtime.keyspace --write"),
                        key="doc:drift"))
        return out

    def _emit(self, out: List[Finding], dup: Dict[str, int], mod: Module,
              call: ast.Call, op: str, why: str) -> None:
        func = mod.enclosing_function(call)
        where = getattr(func, "name", "<module>")
        key = f"{where}:{op}"
        n = dup.get(f"{mod.rel}:{key}", 0) + 1
        dup[f"{mod.rel}:{key}"] = n
        if n > 1:
            key = f"{key}#{n}"
        out.append(Finding(
            rule=self.name, path=mod.rel, line=call.lineno,
            message=f"store.{op}() in {where}(): {why}", key=key))
