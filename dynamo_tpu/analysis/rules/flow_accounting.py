"""Rule ``flow-accounting``: byte-moving call sites must hit the ledger.

The byte-flow ledger (:mod:`dynamo_tpu.obs.flows`) only earns its claim —
"every byte the cluster moves is on one link's meter" — if no transfer
site can silently bypass it. This rule pins that invariant: every call to
a byte-moving *primitive* must sit inside a function that routes bytes
through :func:`record_flow` (or the ledger directly), or carry a
``# dynalint: ok(flow-accounting) <why>`` suppression explaining why the
bytes are deliberately off-ledger. The suppressed inventory doubles as
the documented list of unmetered copies
(``python scripts/dynalint.py --report flow-accounting``).

Primitives (the copies that physically cross a host/device/network edge):

- ``CopyStream`` transfer methods — ``d2h_pages`` / ``h2d_pages`` /
  ``scatter_blocks`` / ``h2d_param_slab``;
- ``global_put`` / ``jax.device_put`` — host tree -> device buffers
  (weight cold load, swap slabs);
- direct-mode streams — any ``client.generate(..., mode="direct", ...)``
  call: the runtime's byte plane (disagg KV push, cluster prefix fetch).

Accounting is function-granular: a site is accounted when ANY enclosing
function's body (nested defs included, so ``hot_swap``'s ``rewrite``
closure inherits the outer record) contains a ``record_flow`` /
``flow_ledger`` call. Finer would force one record per jit-enqueued
scatter — the ledger deliberately meters the bounded unit (the batch,
the slab stream), not each async copy.

Scoped to the byte-plane dirs. ``llm/kvbm/transfer.py`` — the CopyStream
implementation itself — is deliberately OUT of scope: it is the
primitive layer, and accounting belongs at its call sites, where the
batch boundary and the link identity are known.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ..core import Finding, Module, Rule, register

SCOPE = [
    "dynamo_tpu/engine",
    "dynamo_tpu/llm/kvpage",
    "dynamo_tpu/llm/kv_transfer.py",
    "dynamo_tpu/llm/kv_cluster",
    "dynamo_tpu/fleet/mobility",
]

#: last path component of a resolved call naming a transfer primitive
MOVER_SUFFIXES = {
    "d2h_pages", "h2d_pages", "scatter_blocks", "h2d_param_slab",
    "global_put",
}

#: fully-canonical primitive names (resolved through the import map)
MOVER_CANONICAL = {"jax.device_put"}

#: last path component of a call that routes bytes through the ledger
ACCOUNTING = {"record_flow", "flow_ledger"}


def _is_direct_stream(call: ast.Call) -> bool:
    """``*.generate(..., mode="direct", ...)`` — a runtime byte stream."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "generate"):
        return False
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == "direct":
            return True
    return False


@register
class FlowAccountingRule(Rule):
    name = "flow-accounting"
    description = ("byte-moving primitive (CopyStream transfer, "
                   "device_put, direct-mode stream) outside any function "
                   "that records the bytes on the flow ledger")
    scope = list(SCOPE)

    def check_module(self, mod: Module) -> List[Finding]:
        extra_movers = set(self.options.get("movers", ()))
        accounted_funcs = set()
        movers: List[tuple] = []  # (node, label)
        for node in mod.nodes():
            if not isinstance(node, ast.Call):
                continue
            name = mod.resolve_call(node)
            last = name.rsplit(".", 1)[-1]
            if last in ACCOUNTING:
                fn = mod.enclosing_function(node)
                # credit the whole nesting chain: a closure recording on
                # behalf of its outer function (or vice versa) counts
                while fn is not None:
                    accounted_funcs.add(fn)
                    fn = mod.enclosing_function(fn)
            if (last in MOVER_SUFFIXES or name in MOVER_CANONICAL
                    or last in extra_movers):
                movers.append((node, last))
            elif _is_direct_stream(node):
                movers.append((node, "generate[mode=direct]"))

        out: List[Finding] = []
        dup: Dict[str, int] = {}
        for node, label in movers:
            fn = mod.enclosing_function(node)
            accounted = False
            qual = "<module>"
            names = []
            while fn is not None:
                names.append(fn.name)
                if fn in accounted_funcs:
                    accounted = True
                fn = mod.enclosing_function(fn)
            if names:
                qual = ".".join(reversed(names))
            if accounted:
                continue
            key = f"{qual}:{label}"
            n = dup.get(key, 0) + 1
            dup[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=(f"{label} in {qual}() moves bytes no ledger "
                         f"sees — record_flow(...) the transfer, or "
                         f"suppress with the reason these bytes are "
                         f"deliberately off-ledger"),
                key=key))
        out.sort(key=lambda f: f.line)
        return out
