"""Rule ``await-holding-lock``: network awaits under an async lock.

``async with self._send_lock: await write_frame(...)`` holds the lock
across a network wait: one slow/stalled peer parks every other task at
the lock acquire, converting a single backpressured connection into a
process-wide convoy. Sometimes that is the *point* (a send lock exists
precisely to serialize frame writes) — then the site carries a
``# dynalint: ok(await-holding-lock) <why>`` suppression stating the
bound; anything else should copy the data under the lock and await
outside it.

Reuses the lock-discipline recognizer: the context manager is
``self.<attr>`` (or a bare name) whose name contains ``lock``. The
network-capable call set mirrors the unbounded-await rule's primitives
plus the repo's frame writer.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ..core import Finding, Module, Rule, register
from .lock_discipline import _lock_ctx_attrs

#: awaited callables that can park on the network (by terminal name)
NETWORK_CALLS = {
    "write_frame", "drain", "open_connection", "read", "readexactly",
    "readuntil", "readline", "sendall", "connect", "q_pull", "publish",
}


def _lock_ctx(node: ast.AST, pattern: str) -> bool:
    """Async-with over self.<lock> (lock-discipline recognizer) or a bare
    ``async with lock:`` name."""
    if _lock_ctx_attrs(node, pattern):
        return isinstance(node, ast.AsyncWith)
    if isinstance(node, ast.AsyncWith):
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Name) and pattern in ctx.id.lower():
                return True
    return False


@register
class AwaitHoldingLockRule(Rule):
    name = "await-holding-lock"
    description = ("await of a network-capable call inside `async with "
                   "<lock>` — one slow peer convoys every lock waiter")

    def check_module(self, mod: Module) -> List[Finding]:
        pattern = self.options.get("lock_attr_pattern", "lock")
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        def pruned_walk(root: ast.AST):
            for child in ast.iter_child_nodes(root):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue   # a def under the lock runs later
                yield child
                yield from pruned_walk(child)

        for node in mod.nodes():
            if not _lock_ctx(node, pattern):
                continue
            for inner in pruned_walk(node):
                if not isinstance(inner, ast.Await):
                    continue
                call = inner.value
                if not isinstance(call, ast.Call):
                    continue
                name = mod.resolve_call(call).rsplit(".", 1)[-1]
                if name not in NETWORK_CALLS:
                    continue
                func = mod.enclosing_function(inner)
                where = getattr(func, "name", "<module>")
                key = f"{where}:{name}"
                n = dup.get(key, 0) + 1
                dup[key] = n
                if n > 1:
                    key = f"{key}#{n}"
                out.append(Finding(
                    rule=self.name, path=mod.rel, line=inner.lineno,
                    message=(f"await {name}() inside `async with "
                             f"<{pattern}>` in {where}() holds the lock "
                             f"across a network wait — move the await out, "
                             f"or suppress with the serialization bound"),
                    key=key))
        out.sort(key=lambda f: f.line)
        return out
