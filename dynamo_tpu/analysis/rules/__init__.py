"""dynalint rule implementations.

Importing this package registers every rule with the framework registry
(:func:`dynamo_tpu.analysis.core.all_rules` triggers the import). Each rule
lives in its own module; adding a rule = adding a module here with a
``@register``-decorated ``Rule`` subclass and importing it below.
"""

from . import await_lock          # noqa: F401
from . import blocking_async      # noqa: F401
from . import fire_forget         # noqa: F401
from . import flow_accounting     # noqa: F401
from . import host_sync           # noqa: F401
from . import knob_drift          # noqa: F401
from . import lock_discipline     # noqa: F401
from . import loop_blocking_path  # noqa: F401
from . import metrics_catalog     # noqa: F401
from . import recompile_hazard    # noqa: F401
from . import silent_except       # noqa: F401
from . import store_key_drift     # noqa: F401
from . import tracer_leak         # noqa: F401
from . import unbounded_await     # noqa: F401
from . import wire_field_drift    # noqa: F401
