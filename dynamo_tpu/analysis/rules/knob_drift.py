"""Rule ``knob-drift``: the ``DYN_*`` knob surface cannot rot.

Three checks against the central registry
(:mod:`dynamo_tpu.utils.knobs`), mirroring the metrics-catalog gate:

1. every literal ``DYN_*`` string constant in scanned code must be a
   registered knob — an env read nobody declared is an operational
   surface nobody documented;
2. every non-``derived`` registry entry must still appear as a literal
   somewhere — a stale entry is a knob operators set to no effect;
3. ``docs/configuration.md`` (generated from the registry) must contain
   exactly the registered names, two-way — regenerate with
   ``python -m dynamo_tpu.utils.knobs --write`` after touching the
   registry.

Literal collection is AST-based (``ast.Constant`` full-matching
``DYN_[A-Z0-9_]+`` not ending in ``_``), so docstrings, prose, and prefix
fragments used to *build* names never false-positive. The registry file
itself is excluded from read collection, or the reverse check would be
trivially satisfied.

As a whole-repo rule this only runs on full-tree scans (the dynalint CLI
skips repo rules when given an explicit path subset).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Tuple

from ..core import Finding, Module, Rule, register

KNOB_RE = re.compile(r"DYN_[A-Z0-9_]*[A-Z0-9]")
DOC_REL = "docs/configuration.md"
REGISTRY_REL = "dynamo_tpu/utils/knobs.py"


def _literal_reads(modules: List[Module]) -> Dict[str, List[Tuple[str, int]]]:
    """{knob name: [(rel_path, line), ...]} for every full-match literal."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for mod in modules:
        if mod.rel == REGISTRY_REL:
            continue
        for node in mod.nodes():
            if isinstance(node, ast.Constant) and isinstance(
                    node.value, str) and KNOB_RE.fullmatch(node.value):
                out.setdefault(node.value, []).append(
                    (mod.rel, node.lineno))
    return out


@register
class KnobDriftRule(Rule):
    name = "knob-drift"
    description = ("DYN_* env knob not in the central registry, stale "
                   "registry entry, or docs/configuration.md out of sync")

    def check_repo(self, modules: List[Module], repo: str) -> List[Finding]:
        from ...utils.knobs import KNOBS, render_markdown
        reads = _literal_reads(modules)
        out: List[Finding] = []
        for name in sorted(reads):
            if name in KNOBS:
                continue
            path, line = reads[name][0]
            out.append(Finding(
                rule=self.name, path=path, line=line,
                message=(f"env knob {name!r} is not registered — add it to "
                         f"dynamo_tpu/utils/knobs.py (type/default/"
                         f"description) and regenerate "
                         f"docs/configuration.md"),
                key=f"unregistered:{name}"))
        for name, knob in sorted(KNOBS.items()):
            if knob.derived or name in reads:
                continue
            out.append(Finding(
                rule=self.name, path=REGISTRY_REL, line=0,
                message=(f"registered knob {name!r} is never read in "
                         f"scanned code — delete the entry or mark it "
                         f"derived=True"),
                key=f"stale:{name}"))
        # ---- doc sync: the generated table IS the registry ----
        doc_path = os.path.join(repo, DOC_REL)
        if not os.path.exists(doc_path):
            out.append(Finding(
                rule=self.name, path=DOC_REL, line=0,
                message=("docs/configuration.md missing — generate it: "
                         "python -m dynamo_tpu.utils.knobs --write"),
                key="doc:missing"))
            return out
        with open(doc_path, "r", encoding="utf-8") as f:
            text = f.read()
        doc_tokens = set(KNOB_RE.findall(text))
        for name in sorted(set(KNOBS) - doc_tokens):
            out.append(Finding(
                rule=self.name, path=DOC_REL, line=0,
                message=(f"knob {name!r} is registered but missing from "
                         f"the doc table — regenerate: "
                         f"python -m dynamo_tpu.utils.knobs --write"),
                key=f"doc-missing:{name}"))
        for name in sorted(doc_tokens - set(KNOBS)):
            out.append(Finding(
                rule=self.name, path=DOC_REL, line=0,
                message=(f"doc table names unregistered knob {name!r} — "
                         f"stale entry (or a typo); regenerate the doc"),
                key=f"doc-stale:{name}"))
        if text != render_markdown():
            out.append(Finding(
                rule=self.name, path=DOC_REL, line=0,
                message=("docs/configuration.md differs from the "
                         "generated table — regenerate: "
                         "python -m dynamo_tpu.utils.knobs --write"),
                key="doc:drift"))
        return out
