"""Rule ``fire-and-forget``: every spawned task handle must be retained.

``asyncio.create_task`` / ``ensure_future`` used as a bare statement drops
the only reference to the task. Two distinct failure modes follow:

- an exception inside the task is swallowed until the task object is
  garbage collected, then surfaces as an unactionable "Task exception was
  never retrieved" log line — long after the request it belonged to
  returned garbage;
- CPython's event loop holds only a *weak* reference to tasks, so a
  dropped handle can be collected mid-flight and the work silently
  vanishes.

Retaining means anything that keeps the Call's value alive or observed:
assignment, append into a registry, passing it onward, awaiting it, or an
immediate method call on it (``ensure_future(aw).cancel()``). Statically
that is simply: the Call must not be an expression-statement. Flagged on
``asyncio.create_task`` / ``asyncio.ensure_future`` (alias-resolved) and
on ``<anything>.create_task`` / ``<anything>.ensure_future`` so
``loop.create_task(...)`` is covered too.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Module, Rule, register

SPAWN_ATTRS = {"create_task", "ensure_future"}
SPAWN_CANONICAL = {"asyncio.create_task", "asyncio.ensure_future"}


@register
class FireForgetRule(Rule):
    name = "fire-and-forget"
    description = ("asyncio task spawned as a bare statement — the handle "
                   "(and any exception in it) is dropped")

    def check_module(self, mod: Module) -> List[Finding]:
        parents = mod.parents()
        out: List[Finding] = []
        dup: dict = {}
        for node in mod.nodes():
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else "")
            # alias-resolved: `from asyncio import ensure_future as bg`
            # canonicalizes to asyncio.ensure_future; a method spelled
            # create_task/ensure_future on ANY object (loop.create_task)
            # also counts. A bare local helper that merely shares the
            # name resolves to neither and is skipped.
            canonical = mod.resolve_call(node)
            if canonical in SPAWN_CANONICAL:
                attr = canonical.rsplit(".", 1)[-1]
            elif not (isinstance(f, ast.Attribute)
                      and attr in SPAWN_ATTRS):
                continue
            if not isinstance(parents.get(node), ast.Expr):
                continue
            fn = mod.enclosing_function(node)
            where = fn.name if fn is not None else "<module>"
            # discriminate repeats so one baseline entry can never
            # grandfather a second, newly added drop of the same shape
            key = f"{where}:{attr}"
            n = dup.get(key, 0) + 1
            dup[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=node.lineno,
                message=(f"{attr}() handle dropped in {where} — retain it "
                         f"(task set / attribute) or add a done-callback "
                         f"that logs the exception"),
                key=key))
        return out
