"""Rule ``unbounded-await``: network awaits must be deadline-bounded.

Re-homed from ``scripts/check_unbounded_awaits.py`` (the original ad-hoc
gate), behavior-pinned by ``tests/test_churn.py::
test_no_unbounded_network_awaits``. Every ``await`` of a network primitive
(``asyncio.open_connection``, frame/stream ``read``/``readexactly``,
writer ``drain``, queue ``q_pull``) is a potential hang: if the peer
stalls without closing the socket, the coroutine parks forever and the
request above it never reaches a terminal state.

An await passes when it is

- wrapped in a ``wait_for`` (``asyncio.wait_for`` or the deadline layer's
  ``deadline.wait_for``) somewhere between the await and its enclosing
  function, or
- annotated — the legacy ``# unbounded-ok`` spelling and the framework's
  ``# dynalint: ok(unbounded-await) <reason>`` are both honored — on the
  await's line or the contiguous comment block above it.

The scope stays the curated list the standalone gate grew PR over PR:
the runtime layer plus every standing control loop added since (planner,
spec, roofline/slo/dyntop, overload). New standing-daemon modules must be
added to :data:`LEGACY_SCOPE`.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Finding, Module, Rule, register

#: method/function names whose await parks on the network
NETWORK_CALLS = {"open_connection", "readexactly", "read", "drain",
                 "q_pull"}
#: enclosing call names that bound the await
GUARD_CALLS = {"wait_for"}
LEGACY_ANNOTATION = "unbounded-ok"

#: the curated path list the standalone gate accumulated (see its
#: docstring for the per-entry rationale)
LEGACY_SCOPE = [
    "dynamo_tpu/runtime",
    "dynamo_tpu/planner",
    "dynamo_tpu/engine/spec.py",
    "dynamo_tpu/utils/roofline.py",
    "dynamo_tpu/utils/slo.py",
    "dynamo_tpu/cli/dyntop.py",
    "dynamo_tpu/utils/overload.py",
    "dynamo_tpu/llm/kv_cluster",
    "dynamo_tpu/llm/kvpage",
    "dynamo_tpu/fleet",
    "dynamo_tpu/llm/resume.py",
    "dynamo_tpu/cli/aggregator.py",
    "scripts/overload_soak.py",
    "scripts/fleet_soak.py",
]


def _call_name(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
    return ""


def _legacy_annotated(mod: Module, lineno: int) -> bool:
    lines = mod.lines
    if LEGACY_ANNOTATION in lines[lineno - 1]:
        return True
    i = lineno - 2
    while i >= 0 and lines[i].strip().startswith("#"):
        if LEGACY_ANNOTATION in lines[i]:
            return True
        i -= 1
    return False


def unbounded_awaits(mod: Module) -> List["tuple"]:
    """``(lineno, primitive_name, enclosing_function)`` for every
    unguarded, un-annotated network await — the structural API both
    :class:`UnboundedAwaitRule` and the legacy wrapper CLI build from
    (the wrapper must never recover the primitive name by parsing the
    human-readable message)."""
    parents = mod.parents()
    out: List[tuple] = []
    for node in mod.nodes():
        if not isinstance(node, ast.Await):
            continue
        name = _call_name(node.value)
        if name not in NETWORK_CALLS:
            continue
        cur, guarded = node, False
        while cur in parents:
            cur = parents[cur]
            if _call_name(cur) in GUARD_CALLS:
                guarded = True
                break
            if isinstance(cur, (ast.AsyncFunctionDef, ast.FunctionDef)):
                break
        if guarded or _legacy_annotated(mod, node.lineno):
            continue
        fn = mod.enclosing_function(node)
        out.append((node.lineno, name,
                    fn.name if fn is not None else "<module>"))
    out.sort()
    return out


@register
class UnboundedAwaitRule(Rule):
    name = "unbounded-await"
    description = ("await of a network primitive with no wait_for bound "
                   "and no annotation (legacy check_unbounded_awaits gate)")
    scope = LEGACY_SCOPE

    def check_module(self, mod: Module) -> List[Finding]:
        out: List[Finding] = []
        seen: dict = {}
        for lineno, name, where in unbounded_awaits(mod):
            key = f"{where}:{name}"
            n = seen.get(key, 0) + 1
            seen[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=lineno,
                message=(f"unbounded network await ({name}) — wrap in "
                         f"wait_for()/deadline.wait_for() or annotate "
                         f"'# unbounded-ok: <why bounded>'"),
                key=key))
        return out
