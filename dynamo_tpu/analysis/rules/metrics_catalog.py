"""Rule ``metrics-catalog``: docs/observability.md cannot rot.

Re-homed from ``scripts/check_metrics_catalog.py``, behavior-pinned by
``tests/test_goodput.py::test_metrics_catalog_in_sync``. Collects every
metric name registered through the in-tree registry (``.counter("name",
...)`` / ``.gauge`` / ``.histogram`` with a literal first argument,
including local aliases ``g = registry.gauge``) and cross-checks the
catalog in ``docs/observability.md`` two-way:

- every registered metric must appear in the doc;
- every metric-shaped doc token (``dyn_*`` / ``llm_*``, minus wildcard
  families and histogram exposition suffixes) must be registered —
  documented metrics no code exports are exactly how operators end up
  alerting on series that never appear;
- the **type** column of a catalog table row (``counter`` / ``gauge`` /
  ``histogram``, optionally followed by a label list) must match the
  register method actually used — a doc claiming ``gauge`` for a
  counter sends operators writing ``rate()`` over resets the wrong way.

The collection functions are module-level so the legacy standalone CLI
(and its pinned test asserting specific registered names) can reuse them
unchanged.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set

from ..core import Finding, Module, Rule, register

REGISTER_METHODS = {"counter", "gauge", "histogram"}
DOC_TOKEN = re.compile(r"\b(?:dyn|llm)_[a-z0-9_]+\b")
DOC_REL = "docs/observability.md"
CODE_PREFIX = "dynamo_tpu/"


def registered_in_module(mod: Module) -> Dict[str, List[str]]:
    """{metric name: [``rel:line``, ...]} for one parsed module."""
    out: Dict[str, List[str]] = {}
    # local aliases of a register method (`g = registry.gauge`) register
    # through a bare Name call — resolve them too
    aliases: Set[str] = set()
    for node in mod.nodes():
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr in REGISTER_METHODS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)
    for node in mod.nodes():
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if (name not in REGISTER_METHODS and name not in aliases) \
                or not node.args:
            continue
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(
                arg0.value, str) and DOC_TOKEN.fullmatch(arg0.value):
            out.setdefault(arg0.value, []).append(
                f"{mod.rel}:{node.lineno}")
    return out


def registered_types_in_module(mod: Module) -> Dict[str, Set[str]]:
    """{metric name: {register method kinds}} for one parsed module —
    the same literal-first-argument scan as :func:`registered_in_module`,
    keeping the ``counter``/``gauge``/``histogram`` method instead of the
    site. A set because nothing stops two files registering one name
    through different methods (itself a bug the mismatch check surfaces
    against the doc's single claimed type)."""
    out: Dict[str, Set[str]] = {}
    aliases: Dict[str, str] = {}   # local alias name -> register method
    for node in mod.nodes():
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr in REGISTER_METHODS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases[t.id] = node.value.attr
    for node in mod.nodes():
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        kind = name if name in REGISTER_METHODS else aliases.get(name)
        if kind is None or not node.args:
            continue
        arg0 = node.args[0]
        if isinstance(arg0, ast.Constant) and isinstance(
                arg0.value, str) and DOC_TOKEN.fullmatch(arg0.value):
            out.setdefault(arg0.value, set()).add(kind)
    return out


def documented_types(doc_path: str) -> Dict[str, str]:
    """{metric name: claimed type} from the catalog tables: rows shaped
    ``| `name` | type ... | ...`` where the type cell LEADS with
    ``counter``/``gauge``/``histogram`` (label lists and prose after it
    are fine). Non-table mentions carry no type claim and are skipped."""
    out: Dict[str, str] = {}
    with open(doc_path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.lstrip().startswith("|"):
                continue
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if len(cells) < 2:
                continue
            m = DOC_TOKEN.fullmatch(cells[0].strip("`"))
            if m is None:
                continue
            claimed = cells[1].split()[0].rstrip(",") if cells[1] else ""
            if claimed in REGISTER_METHODS:
                out[m.group(0)] = claimed
    return out


def documented_tokens(doc_path: str) -> Set[str]:
    with open(doc_path, "r", encoding="utf-8") as f:
        text = f.read()
    # drop wildcard families like `llm_kv_blocks_*`: they are prose
    # shorthand, not catalog entries (the expanded names must still appear)
    text = re.sub(r"\b(?:dyn|llm)_[a-z0-9_]+\*", " ", text)
    return set(DOC_TOKEN.findall(text))


def catalog_findings(registered: Dict[str, List[str]],
                     documented: Set[str], rule: str = "metrics-catalog",
                     registered_kinds: Dict[str, Set[str]] = None,
                     claimed_types: Dict[str, str] = None
                     ) -> List[Finding]:
    findings: List[Finding] = []
    # type column vs register method (only for names both sides know;
    # presence mismatches are reported by the two-way checks below)
    for name in sorted(claimed_types or {}):
        kinds = (registered_kinds or {}).get(name)
        claimed = claimed_types[name]
        if not kinds or claimed in kinds:
            continue
        where = registered.get(name, [f"{DOC_REL}:0"])[0]
        path, _, line = where.rpartition(":")
        findings.append(Finding(
            rule=rule, path=path, line=int(line),
            message=(f"metric {name!r} is documented as {claimed!r} but "
                     f"registered as {'/'.join(sorted(kinds))} — fix the "
                     f"type column in docs/observability.md (or the "
                     f"registration)"),
            key=f"type-mismatch:{name}"))
    for name in sorted(registered):
        if name not in documented:
            where = registered[name][0]
            path, _, line = where.rpartition(":")
            findings.append(Finding(
                rule=rule, path=path, line=int(line),
                message=(f"undocumented metric {name!r} — add it to "
                         f"docs/observability.md"),
                key=f"undocumented:{name}"))
    # exposition-format suffixes of registered histograms/counters are
    # legitimate doc tokens (e.g. `llm_ttft_seconds_bucket`)
    expanded = set(registered)
    for name in registered:
        for sfx in ("_bucket", "_sum", "_count", "_total"):
            expanded.add(name + sfx)
    for token in sorted(documented):
        if token not in expanded:
            findings.append(Finding(
                rule=rule, path=DOC_REL, line=0,
                message=(f"documented metric {token!r} is not registered "
                         f"anywhere under dynamo_tpu/ — stale catalog "
                         f"entry (or a typo)"),
                key=f"stale:{token}"))
    return findings


@register
class MetricsCatalogRule(Rule):
    name = "metrics-catalog"
    description = ("registered Prometheus metrics <-> docs/observability.md "
                   "catalog, two-way (legacy check_metrics_catalog gate)")

    def check_repo(self, modules: List[Module], repo: str) -> List[Finding]:
        registered: Dict[str, List[str]] = {}
        kinds: Dict[str, Set[str]] = {}
        for mod in modules:
            if not mod.rel.startswith(CODE_PREFIX):
                continue
            for name, sites in registered_in_module(mod).items():
                registered.setdefault(name, []).extend(sites)
            for name, ks in registered_types_in_module(mod).items():
                kinds.setdefault(name, set()).update(ks)
        doc_path = os.path.join(repo, DOC_REL)
        if not os.path.exists(doc_path):
            return [Finding(rule=self.name, path=DOC_REL, line=0,
                            message="docs/observability.md is missing",
                            key="doc:missing")]
        return catalog_findings(registered, documented_tokens(doc_path),
                                rule=self.name, registered_kinds=kinds,
                                claimed_types=documented_types(doc_path))
