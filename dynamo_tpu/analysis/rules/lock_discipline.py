"""Rule ``lock-discipline``: lock-guarded fields stay lock-guarded.

If any method of a class writes ``self.x`` under ``with self._lock:``, the
author decided ``x`` is shared mutable state. A second write site WITHOUT
the lock silently breaks that invariant: under free-threading (or plain
callback reentrancy) the unguarded write races the guarded read-modify-
write and the field tears — exactly the class of bug that produced the
unlocked-reads fix in ``utils/prometheus.py``.

Mechanics, per ``class`` statement:

- guard set = every ``self.<attr>`` assigned (``=``, ``+=``, annotated)
  anywhere inside a ``with self.<lock>:`` block, where the context
  manager's attribute name contains ``lock``;
- violation = a write to a guarded attr outside every such block, in any
  method except ``__init__``/``__new__`` (construction happens-before
  publication, so the constructor may write freely).

Writes inside functions nested in a method are treated as unguarded —
they run later, when the enclosing ``with`` is long gone; if the closure
is only ever called under the lock, say so in a suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from ..core import Finding, Module, Rule, register

CTOR = {"__init__", "__new__"}


def _lock_ctx_attrs(node: ast.AST, pattern: str) -> bool:
    """True when a With/AsyncWith item is ``self.<attr>`` with ``pattern``
    in the attribute name (``self._lock``, ``self.metrics_lock``, ...)."""
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        ctx = item.context_expr
        if isinstance(ctx, ast.Attribute) and isinstance(
                ctx.value, ast.Name) and ctx.value.id == "self" \
                and pattern in ctx.attr.lower():
            return True
    return False


def _self_write_attrs(stmt: ast.stmt) -> List[Tuple[str, int]]:
    """``self.<attr>`` names written by an assignment statement."""
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        if isinstance(stmt, ast.AnnAssign) and stmt.value is None:
            return []
        targets = [stmt.target]
    out: List[Tuple[str, int]] = []
    for t in targets:
        for node in ast.walk(t):     # unpack tuples: self.a, self.b = ...
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id == "self" \
                    and isinstance(node.ctx, ast.Store):
                out.append((node.attr, stmt.lineno))
    return out


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    description = ("attribute written under `with self.<lock>` is also "
                   "written outside it in the same class")

    def check_module(self, mod: Module) -> List[Finding]:
        pattern = self.options.get("lock_attr_pattern", "lock")
        out: List[Finding] = []
        for cls in mod.nodes():
            if isinstance(cls, ast.ClassDef):
                out.extend(self._check_class(mod, cls, pattern))
        out.sort(key=lambda f: f.line)
        return out

    def _check_class(self, mod: Module, cls: ast.ClassDef,
                     pattern: str) -> List[Finding]:
        # (attr, line, method, guarded) for every self.<attr> write
        writes: List[Tuple[str, int, str, bool]] = []

        def scan(stmts, method: str, in_lock: bool) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    # a def at class level is a method; any deeper def is
                    # a closure, attributed to its enclosing method —
                    # closures run after the with-block exits, so their
                    # writes never inherit the guard (in_lock resets)
                    is_method = method == "<class>"
                    scan(stmt.body, stmt.name if is_method else method,
                         False)
                    continue
                if isinstance(stmt, ast.ClassDef):
                    continue      # nested class: its own _check_class run
                for attr, line in _self_write_attrs(stmt):
                    writes.append((attr, line, method, in_lock))
                lock_here = _lock_ctx_attrs(stmt, pattern)
                for _fname, body in ast.iter_fields(stmt):
                    if not (isinstance(body, list) and body):
                        continue
                    if isinstance(body[0], ast.stmt):
                        scan(body, method, in_lock or lock_here)
                    elif isinstance(body[0], ast.ExceptHandler):
                        for h in body:
                            scan(h.body, method, in_lock)

        scan(cls.body, "<class>", False)
        guarded = {attr for attr, _l, _m, g in writes if g}
        out: List[Finding] = []
        dup: Dict[str, int] = {}
        for attr, line, method, g in writes:
            if g or attr not in guarded or method in CTOR:
                continue
            key = f"{cls.name}.{attr}@{method}"
            n = dup.get(key, 0) + 1
            dup[key] = n
            if n > 1:
                key = f"{key}#{n}"
            out.append(Finding(
                rule=self.name, path=mod.rel, line=line,
                message=(f"{cls.name}.{attr} is written under "
                         f"self.*{pattern}* elsewhere but written without "
                         f"it in {method}() — take the lock or document "
                         f"why this write cannot race"),
                key=key))
        return out
