"""Rule ``wire-field-drift``: control-header field names come from wire.py.

The two-part frame control header is the request/data plane's protocol
surface: ``context_id``, ``trace``, ``priority``, the error-frame fields.
Planes that drop unknown fields degrade gracefully — which is exactly why
a misspelled field never errors, it silently forks the protocol. The
registry (``WIRE_FIELDS`` + the ``*_KEY`` constants in
``dynamo_tpu/runtime/wire.py``) is gated three ways:

1. **code → registry** (dataplane modules): a control-header dict literal
   key, or a ``.get()``/subscript on a control-named variable, spelled as
   a string literal fails — spell it through the constant. A literal that
   is not even a registered field is flagged as an unregistered field.
   Control dicts are recognized structurally: dict literals carrying a
   ``kind`` discriminator (literal or ``KIND_KEY``), and variables named
   ``control``/``base_control``/``req_control``/``ctrl``.
2. **registry → code**: every registered field's constant must be read
   somewhere outside wire.py — a constant nobody spells is a stale field.
3. **docs**: every registered field appears in docs/keyspace.md and vice
   versa (the full byte-for-byte check rides store-key-drift, which owns
   the generated file).

The registry is read via AST (no import of wire.py — and thus msgpack —
at lint time): ``WIRE_FIELDS`` is a literal dict and the constants are
literal assignments, by design.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set

from ..core import Finding, Module, Rule, register

WIRE_REL = "dynamo_tpu/runtime/wire.py"
DOC_REL = "docs/keyspace.md"

#: modules that build/parse control headers (the per-file literal check)
DATAPLANE = (
    "dynamo_tpu/runtime/component.py",
    "dynamo_tpu/runtime/native_dataplane.py",
)

CONTROL_NAME_RE = re.compile(r"^(control|base_control|req_control|ctrl)$")


def load_registry(modules: List[Module]
                  ) -> Optional[Dict[str, Dict[str, str]]]:
    """{'fields': {name: desc}, 'constants': {CONST: field}} parsed from
    wire.py's AST; None when wire.py is not in the scanned set."""
    wire = next((m for m in modules if m.rel == WIRE_REL), None)
    if wire is None:
        return None
    fields: Dict[str, str] = {}
    constants: Dict[str, str] = {}
    for node in wire.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        name = node.targets[0].id
        if name == "WIRE_FIELDS" and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) \
                        and isinstance(v, ast.Constant):
                    fields[str(k.value)] = str(v.value)
        elif name.endswith("_KEY") and isinstance(node.value, ast.Constant):
            constants[name] = str(node.value.value)
    return {"fields": fields, "constants": constants}


@register
class WireFieldDriftRule(Rule):
    name = "wire-field-drift"
    description = ("control-header field spelled as a literal in dataplane "
                   "code, unregistered wire field, stale registry "
                   "constant, or docs out of sync")

    def check_repo(self, modules: List[Module], repo: str) -> List[Finding]:
        reg = load_registry(modules)
        if reg is None:
            return []
        fields, constants = reg["fields"], reg["constants"]
        out: List[Finding] = []
        # constants must cover the field table exactly
        const_fields = set(constants.values())
        for f in sorted(set(fields) - const_fields):
            out.append(Finding(
                rule=self.name, path=WIRE_REL, line=0,
                message=(f"WIRE_FIELDS entry {f!r} has no *_KEY constant "
                         f"— add one so code can spell it"),
                key=f"no-constant:{f}"))
        for c, f in sorted(constants.items()):
            if f not in fields:
                out.append(Finding(
                    rule=self.name, path=WIRE_REL, line=0,
                    message=(f"constant {c} = {f!r} is not in WIRE_FIELDS "
                             f"— register the field (or delete the "
                             f"constant)"),
                    key=f"unregistered-constant:{c}"))
        # code -> registry: literal spellings in dataplane modules
        dup: Dict[str, int] = {}
        for mod in modules:
            if mod.rel not in DATAPLANE:
                continue
            for lit, line, ctxdesc in self._literal_fields(mod):
                if lit in fields:
                    why = (f"spell it through wire."
                           f"{self._const_for(constants, lit)}")
                else:
                    why = ("not a registered wire field — register it in "
                           "WIRE_FIELDS + a *_KEY constant")
                key = f"literal:{lit}"
                n = dup.get(f"{mod.rel}:{key}", 0) + 1
                dup[f"{mod.rel}:{key}"] = n
                if n > 1:
                    key = f"{key}#{n}"
                out.append(Finding(
                    rule=self.name, path=mod.rel, line=line,
                    message=(f"control-header field {lit!r} spelled as a "
                             f"literal in {ctxdesc} — {why}"),
                    key=key))
        # registry -> code: each constant read outside wire.py
        read: Set[str] = set()
        for mod in modules:
            if mod.rel == WIRE_REL:
                continue
            for node in mod.nodes():
                if isinstance(node, ast.Name) and node.id in constants:
                    read.add(node.id)
                elif isinstance(node, ast.Attribute) \
                        and node.attr in constants:
                    read.add(node.attr)
                elif isinstance(node, ast.ImportFrom):
                    for a in node.names:
                        if a.name in constants:
                            read.add(a.name)
        for c in sorted(set(constants) - read):
            out.append(Finding(
                rule=self.name, path=WIRE_REL, line=0,
                message=(f"wire-field constant {c} is never read outside "
                         f"wire.py — stale field, or a producer still "
                         f"spells the literal"),
                key=f"stale:{c}"))
        # docs two-way (field tokens in the generated doc)
        doc_path = os.path.join(repo, DOC_REL)
        if os.path.exists(doc_path):
            with open(doc_path, "r", encoding="utf-8") as f:
                text = f.read()
            doc_fields = set(re.findall(r"^\| `([a-z_]+)` \|", text,
                                        re.MULTILINE))
            for f2 in sorted(set(fields) - doc_fields):
                out.append(Finding(
                    rule=self.name, path=DOC_REL, line=0,
                    message=(f"wire field {f2!r} missing from the doc "
                             f"table — regenerate: python -m "
                             f"dynamo_tpu.runtime.keyspace --write"),
                    key=f"doc-missing:{f2}"))
        return out

    @staticmethod
    def _const_for(constants: Dict[str, str], field: str) -> str:
        for c, f in constants.items():
            if f == field:
                return c
        return "<add a constant>"

    def _literal_fields(self, mod: Module):
        """(literal, line, context) for every literal field spelling in
        control-header contexts of one dataplane module."""
        for node in mod.nodes():
            if isinstance(node, ast.Dict) \
                    and self._is_control_dict(node, mod):
                for k in node.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        yield k.value, k.lineno, "a control dict literal"
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and self._is_control_base(node.func.value) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                yield (node.args[0].value, node.lineno,
                       "a control .get()")
            elif isinstance(node, ast.Subscript) \
                    and self._is_control_base(node.value) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                # unrestricted on purpose: a TYPO'D field written via
                # subscript (`base_control["prority"] = ...`) is the
                # silent protocol fork this rule exists to catch
                yield node.slice.value, node.lineno, "a control subscript"

    @staticmethod
    def _is_control_base(expr: ast.expr) -> bool:
        return isinstance(expr, ast.Name) \
            and CONTROL_NAME_RE.match(expr.id) is not None

    @staticmethod
    def _is_control_dict(node: ast.Dict, mod: Module) -> bool:
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and k.value == "kind":
                return True
            if isinstance(k, ast.Name) and k.id == "KIND_KEY":
                return True
            if isinstance(k, ast.Attribute) and k.attr == "KIND_KEY":
                return True
            # {**base_control, ...}: a spread OF a control dict IS one
            if k is None and isinstance(v, ast.Name) \
                    and CONTROL_NAME_RE.match(v.id):
                return True
        # a dict literal assigned to a control-named variable is a control
        # dict even without a kind discriminator
        parent = mod.parents().get(node)
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name) and CONTROL_NAME_RE.match(t.id):
                    return True
        return False
