"""dynalint core: parsed-module cache, rule registry, suppressions.

A :class:`Rule` sees :class:`Module` objects — one parsed Python file with
lazily built parent links and an import-alias map — and returns
:class:`Finding`\\ s. Findings carry a **stable key** (no line number) so the
baseline survives unrelated edits shifting lines.

Suppression syntax (checked by :func:`suppressed`)::

    do_thing()   # dynalint: ok(rule-name) one-line reason why this is fine

The annotation may sit on the flagged line itself or anywhere in the
contiguous comment block directly above it (same convention the legacy
``# unbounded-ok`` annotation used). A reason is mandatory: a bare
``ok(rule)`` suppresses the finding but raises a ``suppression`` meta
finding instead, so un-justified mutes cannot accumulate silently.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple, Type

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SUPPRESS_RE = re.compile(
    r"#\s*dynalint:\s*ok\(\s*([a-z0-9_\-]+)\s*\)\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation.

    ``key`` is the baseline identity: stable across line drift (e.g.
    ``"func_name:time.sleep"``), unique enough within (rule, path) that a
    grandfathered finding doesn't mask a new one of the same shape — rules
    append a discriminator when a key would collide.
    """

    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str
    key: str

    def location(self) -> str:
        return f"{self.path}:{self.line}"


class Module:
    """One parsed source file, shared across rules (parse once per run)."""

    def __init__(self, path: str, repo: str = REPO):
        self.path = os.path.abspath(path)
        self.rel = os.path.relpath(self.path, repo).replace(os.sep, "/")
        with open(self.path, "r", encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree = ast.parse(self.src, filename=self.path)
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._imports: Optional[Dict[str, str]] = None
        self._nodes: Optional[List[ast.AST]] = None

    # -- structure helpers ------------------------------------------------
    def nodes(self) -> List[ast.AST]:
        """Flat cached list of every AST node (``ast.walk`` order). Rules
        that scan the whole module iterate this instead of re-walking the
        tree — with a dozen rules over a hundred files, the repeated
        ``ast.walk`` traversals were the suite's dominant cost."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in self.nodes():
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing (Async)FunctionDef, or None at module level."""
        parents = self.parents()
        cur = node
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None

    # -- import resolution ------------------------------------------------
    def imports(self) -> Dict[str, str]:
        """{local name: canonical dotted name} for every import binding.

        ``import time as _time`` -> ``{"_time": "time"}``;
        ``from subprocess import run`` -> ``{"run": "subprocess.run"}``.
        A dotted ``import a.b`` binds only the top-level ``a`` — mapping
        it to itself keeps attribute chains canonical (``a.b.c()``
        resolves to ``"a.b.c"``, not ``"a.b.b.c"``). Relative imports
        keep their textual module path — rules match stdlib canonical
        names, which are never relative.
        """
        if self._imports is None:
            m: Dict[str, str] = {}
            for node in ast.walk(self.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.asname:
                            m[a.asname] = a.name
                        else:
                            top = a.name.split(".")[0]
                            m[top] = top
                elif isinstance(node, ast.ImportFrom) and node.module:
                    prefix = "." * node.level + node.module
                    for a in node.names:
                        m[a.asname or a.name] = f"{prefix}.{a.name}"
            self._imports = m
        return self._imports

    def resolve_call(self, call: ast.Call) -> str:
        """Best-effort canonical dotted name of a call's target.

        ``_time.sleep(1)`` -> ``"time.sleep"`` (through the alias map);
        ``run(...)`` where run came ``from subprocess import run`` ->
        ``"subprocess.run"``; an unresolvable base keeps its local name
        (``"loop.create_task"``).
        """
        parts: List[str] = []
        f: ast.AST = call.func
        while isinstance(f, ast.Attribute):
            parts.append(f.attr)
            f = f.value
        if isinstance(f, ast.Name):
            base = self.imports().get(f.id, f.id)
        else:
            base = "?"          # call on an expression, e.g. foo().bar()
        return ".".join([base] + list(reversed(parts)))

    # -- suppressions -----------------------------------------------------
    def suppressions_at(self, lineno: int) -> List[Tuple[str, str, int]]:
        """``(rule, reason, comment_line)`` annotations covering ``lineno``:
        on the line itself or in the contiguous comment block above. A
        block annotation's reason continues across the following comment
        lines (until another annotation or the end of the block), so
        reasons can be written out in full."""
        out: List[Tuple[str, str, int]] = []
        if 1 <= lineno <= len(self.lines):
            m = SUPPRESS_RE.search(self.lines[lineno - 1])
            if m:
                out.append((m.group(1), m.group(2).strip(), lineno))
        i = lineno - 2
        while i >= 0 and self.lines[i].strip().startswith("#"):
            m = SUPPRESS_RE.search(self.lines[i])
            if m:
                reason = [m.group(2).strip()]
                j = i + 1
                while j < lineno - 1:
                    cont = self.lines[j].strip()
                    if not cont.startswith("#") or SUPPRESS_RE.search(cont):
                        break
                    reason.append(cont.lstrip("#").strip())
                    j += 1
                out.append((m.group(1), " ".join(r for r in reason if r),
                            i + 1))
            i -= 1
        return out


class Rule:
    """Base class: subclass, set ``name``/``description``, register.

    Per-file rules override :meth:`check_module`; whole-repo rules
    (cross-file state, doc sync) override :meth:`check_repo` and are fed
    the full module list once. ``scope`` (optional list of repo-relative
    prefixes) narrows which files a per-file rule sees — the legacy
    unbounded-await gate keeps its curated path list this way.

    ``options`` comes from the per-rule config dict the runner was given;
    rules read what they understand and ignore the rest.
    """

    name: str = ""
    description: str = ""
    scope: Optional[List[str]] = None

    def __init__(self, options: Optional[dict] = None):
        self.options = dict(options or {})
        if self.options.get("scope") is not None:
            self.scope = list(self.options["scope"])

    def in_scope(self, mod: Module) -> bool:
        if self.scope is None:
            return True
        return any(mod.rel == p or mod.rel.startswith(p.rstrip("/") + "/")
                   for p in self.scope)

    def check_module(self, mod: Module) -> List[Finding]:
        return []

    def check_repo(self, modules: List[Module], repo: str) -> List[Finding]:
        return []


_REGISTRY: Dict[str, Type[Rule]] = {}


def register(cls: Type[Rule]) -> Type[Rule]:
    """Class decorator adding a Rule to the global registry."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if cls.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {cls.name!r}")
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, Type[Rule]]:
    # importing .rules populates the registry exactly once
    from . import rules  # noqa: F401
    return dict(_REGISTRY)


def get_rule(name: str) -> Type[Rule]:
    rules = all_rules()
    if name not in rules:
        known = ", ".join(sorted(rules))
        raise KeyError(f"unknown rule {name!r} (known: {known})")
    return rules[name]


def iter_python_files(paths: Iterable[str]) -> List[str]:
    """Expand files/directories to a sorted list of ``.py`` files."""
    out: List[str] = []
    for root in paths:
        if root.endswith(".py"):
            out.append(root)
            continue
        for dirpath, dirs, files in os.walk(root):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            out.extend(os.path.join(dirpath, fn)
                       for fn in sorted(files) if fn.endswith(".py"))
    return sorted(set(out))
