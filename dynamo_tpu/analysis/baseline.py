"""dynalint baseline: checked-in grandfather list for pre-existing findings.

The baseline lets a new rule land with teeth (CI fails on NEW findings
immediately) while the existing findings are burned down over time. Format
(``scripts/dynalint_baseline.json``)::

    {
      "rule-name": [
        {"path": "dynamo_tpu/x.py", "key": "func:time.sleep",
         "reason": "one-line justification — mandatory"},
        ...
      ]
    }

Matching is on ``(rule, path, key)`` — no line numbers, so unrelated edits
don't churn the file. The gate is two-way: a finding not in the baseline
fails the run, and a baseline entry whose finding no longer exists is
reported *stale* and must be deleted (the baseline only ever shrinks).
An entry without a reason fails loading — un-justified grandfathering is
exactly the rot this file exists to prevent.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Set, Tuple

from .core import Finding

BaselineKey = Tuple[str, str, str]          # (rule, path, key)


def load(path: str) -> Dict[BaselineKey, str]:
    """{(rule, path, key): reason}; missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    out: Dict[BaselineKey, str] = {}
    for rule, entries in raw.items():
        for e in entries:
            reason = (e.get("reason") or "").strip()
            if not reason:
                raise ValueError(
                    f"baseline entry {rule}:{e.get('path')}:{e.get('key')} "
                    f"has no reason — every grandfathered finding needs a "
                    f"one-line justification")
            out[(rule, e["path"], e["key"])] = reason
    return out


def save(path: str, findings: List[Finding],
         default_reason: str = "TODO: justify or fix") -> None:
    """Write ``findings`` as a baseline skeleton, preserving reasons already
    present in the file for entries that still match."""
    existing = {}
    try:
        existing = load(path)
    except (ValueError, json.JSONDecodeError, OSError):
        pass
    by_rule: Dict[str, List[dict]] = {}
    for f in sorted(findings, key=lambda f: (f.rule, f.path, f.key)):
        reason = existing.get((f.rule, f.path, f.key), default_reason)
        by_rule.setdefault(f.rule, []).append(
            {"path": f.path, "key": f.key, "reason": reason})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(by_rule, fh, indent=2, sort_keys=True)
        fh.write("\n")


def split(findings: List[Finding], baseline: Dict[BaselineKey, str]
          ) -> Tuple[List[Finding], List[Finding], List[BaselineKey]]:
    """(new, grandfathered, stale_entries)."""
    seen: Set[BaselineKey] = set()
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        k = (f.rule, f.path, f.key)
        if k in baseline:
            seen.add(k)
            old.append(f)
        else:
            new.append(f)
    stale = [k for k in baseline if k not in seen]
    return new, old, sorted(stale)
