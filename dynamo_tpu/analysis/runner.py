"""dynalint runner: parse once, run every rule, apply suppressions+baseline.

The runner is the only piece that sees the whole picture: it expands the
path set, parses each file exactly once into a :class:`~.core.Module`,
feeds per-file rules the modules in their scope and repo rules the full
list, then filters raw findings through inline suppressions and the
baseline. The result object renders as human text or machine JSON.

Suppression semantics (see :mod:`.core`): a matching
``# dynalint: ok(<rule>) <reason>`` mutes the finding; a reason-less one
still mutes it but surfaces a ``suppression`` meta finding, so the run
fails until the mute is justified. Stale baseline entries fail the run
too — the baseline only shrinks.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import baseline as baseline_mod
from .core import (REPO, Finding, Module, Rule, all_rules, get_rule,
                   iter_python_files)

#: repo-relative roots a plain ``python scripts/dynalint.py`` covers
DEFAULT_ROOTS = ("dynamo_tpu", "scripts")


@dataclass
class LintResult:
    findings: List[Finding]                 # actionable: new + meta
    grandfathered: List[Finding]            # matched a baseline entry
    suppressed: List[Tuple[Finding, str]]   # (finding, reason)
    stale_baseline: List[Tuple[str, str, str]]
    files: int = 0
    rules_run: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.stale_baseline)

    # -- rendering --------------------------------------------------------
    def to_text(self, verbose: bool = False) -> str:
        out: List[str] = []
        for f in self.findings:
            out.append(f"{f.location()}: [{f.rule}] {f.message}")
        for key in self.stale_baseline:
            rule, path, k = key
            out.append(f"{path}: [baseline] stale entry ({rule}, key={k!r}) "
                       f"— the finding is gone, delete it from the baseline")
        if verbose:
            for f, reason in self.suppressed:
                out.append(f"{f.location()}: [{f.rule}] suppressed: {reason}")
            for f in self.grandfathered:
                out.append(f"{f.location()}: [{f.rule}] baselined")
        n = len(self.findings) + len(self.stale_baseline)
        if n:
            out.append(f"\n{n} dynalint finding(s) "
                       f"({len(self.grandfathered)} baselined, "
                       f"{len(self.suppressed)} suppressed)")
        else:
            out.append(f"ok: {len(self.rules_run)} rules over "
                       f"{self.files} files in {self.elapsed_s:.1f}s "
                       f"({len(self.grandfathered)} baselined, "
                       f"{len(self.suppressed)} suppressed)")
        return "\n".join(out)

    def to_json(self) -> str:
        def enc(f: Finding) -> dict:
            return {"rule": f.rule, "path": f.path, "line": f.line,
                    "message": f.message, "key": f.key}
        return json.dumps({
            "failed": self.failed,
            "findings": [enc(f) for f in self.findings],
            "grandfathered": [enc(f) for f in self.grandfathered],
            "suppressed": [dict(enc(f), reason=r)
                           for f, r in self.suppressed],
            "stale_baseline": [
                {"rule": r, "path": p, "key": k}
                for r, p, k in self.stale_baseline],
            "files": self.files, "rules": self.rules_run,
            "elapsed_s": round(self.elapsed_s, 3),
        }, indent=2)


def _parse_tree(roots: List[str], repo: str,
                cache: Dict[str, Optional[Module]], raw: List[Finding]
                ) -> Tuple[List[Module], int]:
    files = iter_python_files(roots)
    modules: List[Module] = []
    for path in files:
        if path in cache:
            # None = already reported as a syntax error; a full-tree
            # reparse for a repo rule must not report it twice
            if cache[path] is not None:
                modules.append(cache[path])
            continue
        try:
            cache[path] = Module(path, repo=repo)
            modules.append(cache[path])
        except SyntaxError as e:
            cache[path] = None
            raw.append(Finding(
                rule="parse", path=os.path.relpath(path, repo),
                line=e.lineno or 0, message=f"syntax error: {e.msg}",
                key="syntax-error"))
    return modules, len(files)


def run_lint(paths: Optional[List[str]] = None,
             rule_names: Optional[List[str]] = None,
             baseline_path: Optional[str] = None,
             config: Optional[Dict[str, dict]] = None,
             repo: str = REPO) -> LintResult:
    """Run ``rule_names`` (default: all registered) over ``paths``.

    Per-file rules see exactly the files under ``paths``; whole-repo rules
    reason about two-way sync, so they ALWAYS analyze the full default
    tree regardless of ``paths`` (a narrowed module set would misreport
    e.g. every knob read outside the subset as a stale registry entry).

    ``config`` maps rule name -> options dict handed to the rule's
    constructor (e.g. ``{"unbounded-await": {"scope": [...]}}``).
    """
    t0 = time.monotonic()
    default_roots = [os.path.join(repo, r) for r in DEFAULT_ROOTS]
    roots = paths or default_roots
    cache: Dict[str, Optional[Module]] = {}
    raw: List[Finding] = []
    modules, n_files = _parse_tree(roots, repo, cache, raw)
    config = config or {}
    names = rule_names or sorted(all_rules())
    repo_rules_run = []
    full_modules: Optional[List[Module]] = None
    for name in names:
        cls = get_rule(name)
        rule = cls(config.get(name))
        for mod in modules:
            if rule.in_scope(mod):
                raw.extend(rule.check_module(mod))
        if cls.check_repo is not Rule.check_repo:
            repo_rules_run.append(name)
            if full_modules is None:
                full_modules = modules if paths is None else _parse_tree(
                    default_roots, repo, cache, raw)[0]
            raw.extend(rule.check_repo(full_modules, repo))

    # inline suppressions (+ meta finding for reason-less ones) — resolve
    # against every parsed module: repo-rule findings may point at files
    # outside the narrowed per-file subset
    by_rel = {m.rel: m for m in cache.values() if m is not None}
    kept: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    meta: List[Finding] = []
    for f in raw:
        mod = by_rel.get(f.path)
        sup = mod.suppressions_at(f.line) if mod is not None else []
        hit = next((s for s in sup if s[0] == f.rule), None)
        if hit is None:
            kept.append(f)
            continue
        _, reason, comment_line = hit
        if reason:
            suppressed.append((f, reason))
        else:
            suppressed.append((f, "(no reason)"))
            meta.append(Finding(
                rule="suppression", path=f.path, line=comment_line,
                message=f"suppression of [{f.rule}] has no reason — "
                        f"write '# dynalint: ok({f.rule}) <why>'",
                key=f"{f.rule}:{f.key}"))

    base = baseline_mod.load(baseline_path) if baseline_path else {}
    # a subset scan can only vouch for what it saw: keep an entry in the
    # stale comparison iff its rule ran AND its finding could have been
    # produced (repo rules always see the full tree; per-file entries
    # need their file in the scanned subset)
    scanned = {m.rel for m in modules}
    base = {k: v for k, v in base.items()
            if k[0] in names and (k[0] in repo_rules_run
                                  or k[1] in scanned)}
    new, grandfathered, stale = baseline_mod.split(kept, base)
    new.extend(meta)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=new, grandfathered=grandfathered,
                      suppressed=suppressed, stale_baseline=stale,
                      files=n_files, rules_run=list(names),
                      elapsed_s=time.monotonic() - t0)
