"""dynalint — repo-native static analysis for the serving control plane.

The framework generalizes the two ad-hoc AST gates that already paid for
themselves (``check_unbounded_awaits``, ``check_metrics_catalog``) into a
shared rule engine: every hang, dropped task, or unguarded shared field in
async serving code becomes a stuck request at fleet scale, so whole bug
classes are caught at commit time instead of in chaos soaks.

Pieces:

- :mod:`.core` — ``Finding``/``Rule``/``Module`` plus the rule registry and
  the ``# dynalint: ok(<rule>) <reason>`` suppression scanner;
- :mod:`.baseline` — checked-in grandfather file for pre-existing findings
  (every entry carries a one-line justification);
- :mod:`.runner` — walks paths, runs rules, applies suppressions +
  baseline, renders text/JSON;
- :mod:`.rules` — the rule implementations (importing it populates the
  registry).

Everything here is stdlib-only (``ast``/``re``/``json``) — importing the
package never pulls in jax or the runtime, so the tier-1 gate stays cheap.

Entry point: ``python scripts/dynalint.py`` (see docs/static_analysis.md).
"""

from .core import (Finding, Module, Rule, all_rules, get_rule,  # noqa: F401
                   register)
from .runner import LintResult, run_lint  # noqa: F401
