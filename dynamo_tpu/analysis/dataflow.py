"""dynalint dataflow: intra-procedural def-use chains + a pluggable taint
lattice, built on the parse-once :class:`~.core.Module` cache.

Two layers, both AST-only (no jax import — the analysis must run in the
tier-1 budget on machines with no accelerator stack):

1. **Def-use chains** (:func:`scope_bindings`, :func:`class_attr_bindings`):
   every binding of a local name / ``self.<attr>`` inside one function or
   class scope, in source order. Rules use these to resolve "where did this
   value come from" questions — e.g. the store-key-drift gate resolving an
   f-string key back to its keyspace helper.

2. **Device taint** (:class:`DeviceTaint`): a three-point lattice
   ``host < jitfn < device`` seeded by "this expression produces a JAX
   device array" — results of jit-compiled callables, ``jnp.*`` / ``jax.*``
   constructors, and known engine pool/state attributes — and propagated
   through assignments, arithmetic, subscripts, containers, loops and
   comprehension targets until fixpoint. The seeds are pluggable per rule
   via options (``device_attrs``, ``jit_wrappers``), which is what makes
   the lattice reusable for the three JAX dispatch-hygiene rules.

The analysis is **flow-insensitive within a function** (a name tainted by
ANY binding stays tainted) and uses a one-level module summary: a function
whose return value is device-tainted taints its call sites, a function
returning a jit callable makes ``fn = self._prefill_fn(...); fn(...)``
device-tainted. That is exactly deep enough for the engine's
stage-dispatch-fetch idiom without whole-program analysis.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .core import Module

# lattice points (host is represented as None)
DEVICE = "device"    # a jax.Array living on an accelerator
JITFN = "jitfn"      # a jit-compiled callable: calling it yields DEVICE
DEVBOX = "devbox"    # host container HOLDING device values: its truthiness
#                      and len() are host metadata (no sync), but
#                      subscripting it hands back a DEVICE value and
#                      converting it wholesale (np.asarray) syncs

#: attribute loads that read host-side metadata off a device array —
#: following them does NOT transfer the buffer
HOST_META_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "itemsize", "sharding",
    "device", "devices", "aval", "weak_type",
}

#: method calls on a device array whose RESULT lives on host (they are
#: sync sinks; the host-sync rule reports them, the lattice drops taint)
HOST_RESULT_METHODS = {"item", "tolist"}

#: resolved call prefixes that construct/transform device arrays
DEVICE_CALL_PREFIXES = (
    "jax.numpy.", "jax.lax.", "jax.random.", "jax.nn.", "jax.scipy.",
    "jax.image.", "jax.ops.",
)

#: resolved calls producing device arrays (beyond the prefixes above)
DEVICE_PRODUCERS = {
    "jax.device_put", "jax.make_array_from_callback", "jax.vmap",
    "jax.pmap", "jax.checkpoint",
}

#: resolved calls whose result is a HOST value even with device args
HOST_RESULT_CALLS = {"jax.device_get"}

#: default jit-wrapper spellings: a call to one of these produces a JITFN.
#: ``instrument_compile`` is the repo's roofline wrapper around jitted
#: programs (utils/roofline.py) — its result dispatches like the jit fn.
DEFAULT_JIT_WRAPPERS = {"jax.jit", "jax.pmap", "instrument_compile"}


# ---------------------------------------------------------------------------
# def-use chains
# ---------------------------------------------------------------------------

def iter_scope_statements(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    """Statements of one function/class scope, recursing into compound
    statements but NOT into nested function/class definitions (those are
    their own scopes). The nested def/class statement itself IS yielded."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for _f, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value \
                    and isinstance(value[0], ast.stmt):
                yield from iter_scope_statements(value)
            elif isinstance(value, list) and value \
                    and isinstance(value[0], ast.excepthandler):
                for h in value:
                    yield from iter_scope_statements(h.body)


def iter_scope_nodes(body: List[ast.stmt]) -> Iterator[ast.AST]:
    """Every AST node of one scope, visited exactly once, with nested
    function/class/lambda BODIES pruned (the scope-introducing node itself
    is yielded — its name binding is visible here — but nothing inside
    it). This is the walker scope-sensitive rules need: ``ast.walk`` over
    statements double-visits compound-statement bodies and leaks into
    nested scopes."""
    stack: List[ast.AST] = list(reversed(body))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue        # the binding is visible; the body is not
        stack.extend(ast.iter_child_nodes(node))


def _binding_pairs(stmt: ast.stmt) -> List[Tuple[ast.expr, ast.expr, str]]:
    """(target, value, via) bindings introduced by one statement. ``via``
    is 'assign' | 'aug' | 'for' | 'with' — loop/with bindings bind each
    ELEMENT of the iterable, which taint consumers treat differently."""
    out: List[Tuple[ast.expr, ast.expr, str]] = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.append((t, stmt.value, "assign"))
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        out.append((stmt.target, stmt.value, "assign"))
    elif isinstance(stmt, ast.AugAssign):
        out.append((stmt.target, stmt.value, "aug"))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        out.append((stmt.target, stmt.iter, "for"))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.append((item.optional_vars, item.context_expr, "with"))
    return out


def scope_bindings(func: ast.AST) -> Dict[str, List[Tuple[ast.expr, str]]]:
    """{local name: [(value_expr, via), ...]} for one function scope, in
    source order. Tuple targets bind every name to the whole value (the
    consumer decides how to project). Walrus (:=) bindings included."""
    out: Dict[str, List[Tuple[ast.expr, str]]] = {}

    def bind(target: ast.expr, value: ast.expr, via: str) -> None:
        for node in ast.walk(target):
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Store):
                out.setdefault(node.id, []).append((value, via))

    body = func.body if hasattr(func, "body") else []
    for stmt in iter_scope_statements(body):
        for target, value, via in _binding_pairs(stmt):
            bind(target, value, via)
        # walrus anywhere inside the statement's expressions
        for node in ast.walk(stmt):
            if isinstance(node, ast.NamedExpr):
                bind(node.target, node.value, "assign")
    return out


def class_attr_bindings(cls: ast.ClassDef
                        ) -> Dict[str, List[Tuple[ast.expr, str]]]:
    """{attr: [(value_expr, via), ...]} for every ``self.<attr> = ...``
    across all methods of one class (plus class-level assignments)."""
    out: Dict[str, List[Tuple[ast.expr, str]]] = {}

    def scan(body: List[ast.stmt]) -> None:
        for stmt in iter_scope_statements(body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan(stmt.body)
                continue
            for target, value, via in _binding_pairs(stmt):
                for node in ast.walk(target):
                    if isinstance(node, ast.Attribute) and isinstance(
                            node.value, ast.Name) \
                            and node.value.id == "self" \
                            and isinstance(node.ctx, ast.Store):
                        out.setdefault(node.attr, []).append((value, via))

    scan(cls.body)
    return out


# ---------------------------------------------------------------------------
# device taint
# ---------------------------------------------------------------------------

class SinkHit:
    """One device→host synchronization point found by the taint sweep."""

    __slots__ = ("node", "label", "func_name")

    def __init__(self, node: ast.Call, label: str, func_name: str):
        self.node = node
        self.label = label          # e.g. "np.asarray", ".item()"
        self.func_name = func_name  # qualified enclosing function


def _last_segment(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def get_device_taint(mod: Module, options: Optional[dict] = None
                     ) -> "DeviceTaint":
    """Per-module DeviceTaint, cached on the Module (three rules share the
    same index; options only vary the seeds, so they key the cache)."""
    key = tuple(sorted(
        (k, tuple(v) if isinstance(v, (list, set, tuple)) else v)
        for k, v in (options or {}).items()
        if k in ("device_attrs", "jit_wrappers", "jitfn_attrs")))
    cache = getattr(mod, "_taint_cache", None)
    if cache is None:
        cache = mod._taint_cache = {}
    if key not in cache:
        cache[key] = DeviceTaint(mod, options)
    return cache[key]


class DeviceTaint:
    """Module-wide device-taint index + per-function analysis.

    Construction walks the module once to build:

    - ``traced``: every function/lambda whose body runs under jax tracing
      (``@jax.jit`` / ``@partial(jax.jit, ...)`` decorated, wrapped by name
      in a jit call, or a lambda argument of one);
    - ``attr_tags``: attribute names assigned a DEVICE/JITFN value anywhere
      in the module (``self.k_pool``, ``self._prefill_fns``, ``s.key``);
    - ``summaries``: function name -> lattice tag of its return value,
      iterated to fixpoint so methods that return jitted-call results
      (``_run_prefill_program``) taint their own call sites.

    Options (all additive, so rules can plug extra lattice seeds):
    ``device_attrs`` — attribute names assumed device-resident;
    ``jit_wrappers`` — extra callables whose result is a jit callable.
    """

    MAX_PASSES = 4

    def __init__(self, mod: Module, options: Optional[dict] = None):
        options = options or {}
        self.mod = mod
        self.jit_wrappers = (set(DEFAULT_JIT_WRAPPERS)
                             | set(options.get("jit_wrappers", ())))
        self.attr_tags: Dict[str, str] = {
            a: DEVICE for a in options.get("device_attrs", ())}
        # jitfn_attrs: attribute names known to hold jit-compiled
        # callables ACROSS module boundaries (e.g. the kvpage runner
        # calling programs built in programs.py) — per-module attribute
        # scanning cannot see those assignments
        self.attr_tags.update(
            {a: JITFN for a in options.get("jitfn_attrs", ())})
        self.global_tags: Dict[str, str] = {}
        self.summaries: Dict[str, Optional[str]] = {}
        self.traced: Set[ast.AST] = set()
        self._env_cache: Dict[int, Dict[str, str]] = {}
        self._prog_cache: Dict[int, dict] = {}
        self._shim_cache: Dict[int, ast.AST] = {}
        self._functions: List[ast.AST] = [
            n for n in mod.nodes()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self._collect_traced()
        self._module_fixpoint()

    # -- jit wrapping detection -------------------------------------------
    def is_jit_wrap_call(self, call: ast.Call) -> bool:
        """``jax.jit(...)`` / ``partial(jax.jit, ...)`` / instrument_compile
        — a call whose RESULT is a jit-compiled callable."""
        resolved = self.mod.resolve_call(call)
        if resolved in self.jit_wrappers \
                or _last_segment(resolved) in self.jit_wrappers:
            return True
        if _last_segment(resolved) == "partial" and call.args:
            first = call.args[0]
            if isinstance(first, ast.Call):
                first = first.func  # partial(jax.jit(...), ...) — unusual
            if isinstance(first, (ast.Name, ast.Attribute)):
                probe = ast.Call(func=first, args=[], keywords=[])
                inner = self.mod.resolve_call(probe)
                if inner in self.jit_wrappers \
                        or _last_segment(inner) in self.jit_wrappers:
                    return True
        return False

    def _jit_decorated(self, func: ast.AST) -> bool:
        for dec in getattr(func, "decorator_list", []):
            if isinstance(dec, ast.Call) and self.is_jit_wrap_call(dec):
                return True
            if isinstance(dec, (ast.Name, ast.Attribute)):
                probe = ast.Call(func=dec, args=[], keywords=[])
                name = self.mod.resolve_call(probe)
                if name in self.jit_wrappers \
                        or _last_segment(name) in self.jit_wrappers:
                    return True
        return False

    def _collect_traced(self) -> None:
        by_name = {f.name: f for f in self._functions}
        for f in self._functions:
            if self._jit_decorated(f):
                self.traced.add(f)
        for node in self.mod.nodes():
            if not (isinstance(node, ast.Call)
                    and self.is_jit_wrap_call(node)):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    self.traced.add(arg)
                elif isinstance(arg, ast.Name) and arg.id in by_name:
                    self.traced.add(by_name[arg.id])
        # a named traced def IS a jit callable under its own name
        for f in self.traced:
            if hasattr(f, "name"):
                self.global_tags.setdefault(f.name, JITFN)

    # -- per-scope program cache -------------------------------------------
    def _prog(self, scope: ast.AST) -> dict:
        """One-time extraction of everything the fixpoint passes consume
        from a scope: bindings, attr stores, container appends, walrus +
        comprehension targets, nested traced defs, return exprs. The
        fixpoint then iterates these flat lists instead of re-walking the
        AST on every pass (the suite's dominant cost before this cache)."""
        prog = self._prog_cache.get(id(scope))
        if prog is not None:
            return prog
        binds: List[Tuple[List[str], List[str], ast.expr]] = []
        named: List[Tuple[str, ast.expr]] = []
        comps: List[Tuple[List[str], ast.expr]] = []
        appends: List[ast.Call] = []
        nested_jit: List[str] = []
        returns: List[ast.expr] = []
        for stmt in iter_scope_statements(scope.body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt in self.traced:
                    nested_jit.append(stmt.name)
                continue
            if isinstance(stmt, ast.Return) and stmt.value is not None:
                returns.append(stmt.value)
            for target, value, _via in _binding_pairs(stmt):
                names, attrs = [], []
                for t in ast.walk(target):
                    if not isinstance(getattr(t, "ctx", None), ast.Store):
                        continue
                    if isinstance(t, ast.Name):
                        names.append(t.id)
                    elif isinstance(t, ast.Attribute):
                        attrs.append(t.attr)
                binds.append((names, attrs, value))
            for node in ast.walk(stmt):
                if isinstance(node, ast.NamedExpr):
                    named.append((node.target.id, node.value))
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.GeneratorExp, ast.DictComp)):
                    for comp in node.generators:
                        tnames = [t.id for t in ast.walk(comp.target)
                                  if isinstance(t, ast.Name)]
                        comps.append((tnames, comp.iter))
                elif isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in ("append", "appendleft",
                                               "add") \
                        and isinstance(node.func.value, ast.Attribute) \
                        and node.args:
                    appends.append(node)
        prog = {"binds": binds, "named": named, "comps": comps,
                "appends": appends, "nested_jit": nested_jit,
                "returns": returns}
        self._prog_cache[id(scope)] = prog
        return prog

    # -- module fixpoint ---------------------------------------------------
    def _module_fixpoint(self) -> None:
        module_scope = ast.Module(body=self.mod.tree.body, type_ignores=[])
        rank = {DEVICE: 3, DEVBOX: 2, JITFN: 1}
        for _ in range(self.MAX_PASSES):
            changed = False
            # per-pass cache only: envs depend on attr_tags/summaries,
            # which this pass may still be growing
            self._env_cache.clear()
            for scope in [module_scope] + self._functions:
                if scope is module_scope:
                    env = dict(self.global_tags)
                else:
                    env = self._function_env(scope)
                changed |= self._scan_stores(scope, env,
                                             scope is module_scope)
                # function summaries (DEVICE beats DEVBOX beats JITFN)
                if scope is not module_scope:
                    tag = None
                    for value in self._prog(scope)["returns"]:
                        t = self.evaluate(value, env)
                        if t is not None and rank[t] > rank.get(tag, 0):
                            tag = t
                    if tag is not None \
                            and self.summaries.get(scope.name) != tag:
                        self.summaries[scope.name] = tag
                        changed = True
            if not changed:
                break

    def _scan_stores(self, scope: ast.AST, env: Dict[str, str],
                     module_level: bool) -> bool:
        """Record attr/global tags from one scope's stores + appends."""
        changed = False
        prog = self._prog(scope)
        for names, attrs, value in prog["binds"]:
            if not attrs and not (module_level and names):
                continue
            tag = self.evaluate(value, env)
            if tag is None:
                continue
            for attr in attrs:
                if self.attr_tags.get(attr) not in (tag, DEVICE):
                    self.attr_tags[attr] = tag
                    changed = True
            if module_level:
                for name in names:
                    if self.global_tags.get(name) != tag:
                        self.global_tags[name] = tag
                        changed = True
        # device containers filled via .append/.appendleft/.add
        for node in prog["appends"]:
            if self.evaluate(node.args[0], env) in (DEVICE, DEVBOX):
                holder = node.func.value.attr
                if self.attr_tags.get(holder) not in (DEVICE, DEVBOX):
                    self.attr_tags[holder] = DEVBOX
                    changed = True
        return changed

    # -- per-function analysis --------------------------------------------
    def _function_env(self, func: ast.AST,
                      outer: Optional[Dict[str, str]] = None
                      ) -> Dict[str, str]:
        """Union (flow-insensitive) taint env for one function scope,
        iterated to local fixpoint."""
        if outer is None and id(func) in self._env_cache:
            return self._env_cache[id(func)]
        env: Dict[str, str] = dict(outer or {})
        # parameters are fresh local bindings: they SHADOW any same-named
        # device value inherited from an enclosing scope
        args = getattr(func, "args", None)
        if args is not None:
            for a in (list(args.posonlyargs) + list(args.args)
                      + list(args.kwonlyargs)
                      + [x for x in (args.vararg, args.kwarg) if x]):
                env.pop(a.arg, None)
        prog = self._prog(func)
        for name in prog["nested_jit"]:
            env[name] = JITFN
        for _ in range(self.MAX_PASSES):
            changed = False
            for names, _attrs, value in prog["binds"]:
                if not names:
                    continue
                tag = self.evaluate(value, env)
                if tag is None:
                    continue
                for name in names:
                    if env.get(name) not in (tag, DEVICE):
                        env[name] = tag
                        changed = True
            for name, value in prog["named"]:
                tag = self.evaluate(value, env)
                if tag and env.get(name) not in (tag, DEVICE):
                    env[name] = tag
                    changed = True
            for tnames, it in prog["comps"]:
                tag = self.evaluate(it, env)
                if tag is None:
                    continue
                for name in tnames:
                    if env.get(name) not in (tag, DEVICE):
                        env[name] = tag
                        changed = True
            if not changed:
                break
        if outer is None:
            self._env_cache[id(func)] = env
        return env

    def evaluate(self, expr: ast.expr, env: Dict[str, str]
                 ) -> Optional[str]:
        """Lattice tag of an expression under ``env`` (None = host)."""
        if isinstance(expr, ast.Name):
            return env.get(expr.id) or self.global_tags.get(expr.id)
        if isinstance(expr, ast.Attribute):
            if expr.attr in HOST_META_ATTRS:
                return None
            base = self.evaluate(expr.value, env)
            if base is not None:
                return base
            return self.attr_tags.get(expr.attr)
        if isinstance(expr, ast.Subscript):
            base = self.evaluate(expr.value, env)
            if base == DEVBOX:
                return DEVICE      # an element handed out of the container
            return base
        if isinstance(expr, ast.Await):
            return self.evaluate(expr.value, env)
        if isinstance(expr, ast.BinOp):
            return (self.evaluate(expr.left, env)
                    or self.evaluate(expr.right, env))
        if isinstance(expr, ast.UnaryOp):
            return self.evaluate(expr.operand, env)
        if isinstance(expr, ast.Compare):
            for e in [expr.left] + list(expr.comparators):
                if self.evaluate(e, env) == DEVICE:
                    return DEVICE
            return None
        if isinstance(expr, ast.BoolOp):
            for e in expr.values:
                t = self.evaluate(e, env)
                if t is not None:
                    return t
            return None
        if isinstance(expr, ast.IfExp):
            return (self.evaluate(expr.body, env)
                    or self.evaluate(expr.orelse, env))
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            for e in expr.elts:
                if self.evaluate(e, env) in (DEVICE, DEVBOX):
                    return DEVBOX
            return None
        if isinstance(expr, ast.Dict):
            for e in expr.values:
                if e is not None and self.evaluate(e, env) in (DEVICE,
                                                               DEVBOX):
                    return DEVBOX
            return None
        if isinstance(expr, ast.Starred):
            return self.evaluate(expr.value, env)
        if isinstance(expr, ast.Call):
            return self._call_tag(expr, env)
        return None

    def _call_tag(self, call: ast.Call, env: Dict[str, str]
                  ) -> Optional[str]:
        resolved = self.mod.resolve_call(call)
        if self.is_jit_wrap_call(call):
            return JITFN
        if resolved in HOST_RESULT_CALLS:
            return None
        if resolved.startswith(DEVICE_CALL_PREFIXES) \
                or resolved in DEVICE_PRODUCERS:
            return DEVICE
        f = call.func
        # sinks produce host values (np.asarray result is a numpy array);
        # block_until_ready returns the same device array
        if isinstance(f, ast.Attribute):
            if f.attr in HOST_RESULT_METHODS:
                return None
            # the attribute itself may BE a jit callable (self._gather_fn)
            if self.evaluate(f, env) == JITFN:
                return DEVICE
            base = self.evaluate(f.value, env)
            if base == JITFN:
                return DEVICE        # calling a jit-compiled callable
            if base == DEVICE:
                # method on a device array (.astype, .at[i].set, ...)
                return DEVICE
            if base == DEVBOX:
                # .popleft()/.pop()/.get() hand out container contents —
                # which may themselves be containers (dicts of arrays)
                return DEVBOX
        elif isinstance(f, (ast.Name, ast.Subscript)):
            if self.evaluate(f, env) == JITFN:
                return DEVICE
        elif isinstance(f, ast.Call):
            # immediate application: jax.jit(lambda: ...)()
            if self.evaluate(f, env) == JITFN:
                return DEVICE
        if resolved in ("numpy.asarray", "numpy.array", "int", "float",
                        "bool"):
            return None              # host result regardless of args
        if resolved in ("dict", "list", "tuple", "deque",
                        "collections.deque"):
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                if self.evaluate(a, env) in (DEVICE, DEVBOX):
                    return DEVBOX
            return None
        summary = self.summaries.get(_last_segment(resolved))
        if summary is not None:
            return summary
        return None

    # -- sink sweep --------------------------------------------------------
    def sink_hits(self, func: ast.AST, qualname: str,
                  outer_env: Optional[Dict[str, str]] = None
                  ) -> List[SinkHit]:
        """Device→host sync points inside one function scope (nested defs
        are visited with the enclosing env inherited, attributed to the
        same qualname — a closure fetching device state is still a sync)."""
        env = self._function_env(func, outer_env)
        hits: List[SinkHit] = []
        nested: List[ast.AST] = []

        def walk(node: ast.AST) -> Iterator[ast.AST]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    nested.append(child)
                    continue
                yield child
                yield from walk(child)

        for stmt in func.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a statement-level closure is a nested scope like any
                # other — its body must NOT be scanned under this env
                nested.append(stmt)
                continue
            for node in [stmt] + list(walk(stmt)):
                if not isinstance(node, ast.Call):
                    continue
                hit = self._sink_label(node, env)
                if hit:
                    hits.append(SinkHit(node, hit, qualname))
        for nfunc in nested:
            if nfunc in self.traced:
                continue             # traced bodies never sync at runtime
            # shims are cached by the ORIGINAL node (which the Module
            # keeps alive): a transient shim freed between sweeps could
            # otherwise recycle its id() into a stale _prog/_env entry
            shim = self._shim_cache.get(id(nfunc))
            if shim is None:
                body = nfunc.body if isinstance(nfunc.body, list) \
                    else [ast.Expr(nfunc.body)]
                shim = ast.FunctionDef(
                    name=getattr(nfunc, "name", "<lambda>"), body=body,
                    args=nfunc.args, decorator_list=[], returns=None)
                self._shim_cache[id(nfunc)] = shim
            hits.extend(self.sink_hits(shim, qualname, env))
        return hits

    def _sink_label(self, call: ast.Call, env: Dict[str, str]
                    ) -> Optional[str]:
        resolved = self.mod.resolve_call(call)
        f = call.func
        if isinstance(f, ast.Attribute) and f.attr in (
                HOST_RESULT_METHODS | {"block_until_ready"}):
            if self.evaluate(f.value, env) == DEVICE:
                return f".{f.attr}()"
        if not call.args:
            return None
        arg0 = call.args[0]
        if resolved in ("int", "float", "bool"):
            # container truthiness/len is host metadata — only a DEVICE
            # array here forces the sync
            if self.evaluate(arg0, env) == DEVICE:
                return f"{resolved}()"
        elif resolved in ("numpy.asarray", "numpy.array"):
            if self.evaluate(arg0, env) in (DEVICE, DEVBOX):
                return f"np.{_last_segment(resolved)}"
        elif resolved in ("jax.device_get", "jax.block_until_ready"):
            if self.evaluate(arg0, env) in (DEVICE, DEVBOX):
                return f"jax.{_last_segment(resolved)}"
        return None

    # -- helpers for rules -------------------------------------------------
    def qualname(self, func: ast.AST) -> str:
        parts = [getattr(func, "name", "<lambda>")]
        parents = self.mod.parents()
        cur = func
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, ast.ClassDef):
                parts.append(cur.name)
            elif isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                parts.append(cur.name)
        return ".".join(reversed(parts))

    def top_level_functions(self) -> List[ast.AST]:
        """Functions that are not nested inside another function (methods
        count as top-level; their nested defs are swept by sink_hits)."""
        parents = self.mod.parents()
        out = []
        for f in self._functions:
            cur = parents.get(f)
            nested = False
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    nested = True
                    break
                cur = parents.get(cur)
            if not nested:
                out.append(f)
        return out
