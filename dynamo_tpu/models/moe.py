"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh axis.

Mixtral-style block: a router picks ``top_k`` of ``E`` experts per token;
each expert is a SwiGLU FFN; outputs combine weighted by renormalized
router probabilities. Under expert parallelism the expert dimension of the
weights is sharded over ``ep`` — each shard computes only its local
experts' contribution for the full token batch and a ``psum`` over the ep
axis combines them (gate weights for non-local experts are zero on each
shard, so the sum is exact).

This dense-dispatch formulation (every local expert sees every token) is
compile-friendly and exact; capacity-based sorted dispatch is a later
throughput optimization, not a semantic change.

Reference capability: the reference inherits MoE/EP from its engines
(SURVEY §2.5 — vllm patch touches deepseek_v2.py); on TPU the in-tree
engine owns it, so this module IS the capability.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_EP, AXIS_TP


def _ep_size(mesh) -> int:
    if mesh is None or AXIS_EP not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS_EP]


def _tp_size(mesh) -> int:
    if mesh is None or AXIS_TP not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS_TP]


def moe_ffn(x: jax.Array,           # [B, T, D]
            wr: jax.Array,          # [D, E] router
            wg: jax.Array,          # [E, D, F] expert gate projections
            wu: jax.Array,          # [E, D, F] expert up projections
            wd: jax.Array,          # [E, F, D] expert down projections
            top_k: int,
            mesh=None) -> jax.Array:
    """Routed MoE feed-forward. Returns [B, T, D] in x.dtype."""
    E = wr.shape[1]
    logits = jnp.einsum("btd,de->bte", x, wr.astype(x.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)               # [B,T,K]
    vals = vals / jnp.sum(vals, axis=-1, keepdims=True)   # renormalize
    gates = jnp.sum(jax.nn.one_hot(idx, E, dtype=jnp.float32)
                    * vals[..., None], axis=-2)           # [B,T,E]

    def experts(x, wg, wu, wd, gates):
        # shapes per shard: wg/wu [El, D, F], wd [El, F, D], gates [B,T,El]
        g = jnp.einsum("btd,edf->btef", x, wg)
        u = jnp.einsum("btd,edf->btef", x, wu)
        a = jax.nn.silu(g) * u
        return jnp.einsum("btef,efd,bte->btd", a, wd,
                          gates.astype(x.dtype))

    ep = _ep_size(mesh)
    tp = _tp_size(mesh)
    F = wg.shape[2]
    tp_ffn = tp if tp > 1 and F % tp == 0 else 1
    if ep <= 1 and tp_ffn <= 1:
        return experts(x, wg, wu, wd, gates)

    # expert dim shards over ep; the FFN intermediate dim additionally
    # shards over tp (each shard computes an F/tp slice of its local
    # experts — the down-projection contraction leaves partial sums, so
    # the combine is one psum over BOTH axes)
    axes = tuple(a for a, n in ((AXIS_EP, ep), (AXIS_TP, tp_ffn)) if n > 1)

    def local(x, wg, wu, wd, gates):
        y = experts(x, wg, wu, wd, gates)
        return jax.lax.psum(y, axes)

    ftp = AXIS_TP if tp_ffn > 1 else None
    eax = AXIS_EP if ep > 1 else None
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, None),
                  P(eax, None, ftp), P(eax, None, ftp), P(eax, ftp, None),
                  P(None, None, eax)),
        out_specs=P(None, None, None),
        check_vma=False,
    )(x, wg, wu, wd, gates)
