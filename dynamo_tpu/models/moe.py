"""Mixture-of-Experts FFN with expert parallelism over the ``ep`` mesh axis.

Mixtral-style block: a router picks ``top_k`` of ``E`` experts per token;
each expert is a SwiGLU FFN; outputs combine weighted by renormalized
router probabilities. Under expert parallelism the expert dimension of the
weights is sharded over ``ep`` — each shard computes only its local
experts' contribution for the full token batch and a ``psum`` over the ep
axis combines them (gate weights for non-local experts are zero on each
shard, so the sum is exact).

Two dispatch formulations, both exact (no capacity limit, no dropped
tokens): compute-bound prefill chunks on an unsharded mesh use SORTED
dispatch (stable-sort assignments by expert + ``lax.ragged_dot`` segment
matmuls — K-per-token FFN cost); tiny decode batches and ep/tp-sharded
meshes use DENSE dispatch (every local expert sees every token —
compile-friendly, combines across shards with one psum).

Reference capability: the reference inherits MoE/EP from its engines
(SURVEY §2.5 — vllm patch touches deepseek_v2.py); on TPU the in-tree
engine owns it, so this module IS the capability.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_EP, AXIS_TP


def _ep_size(mesh) -> int:
    if mesh is None or AXIS_EP not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS_EP]


def _tp_size(mesh) -> int:
    if mesh is None or AXIS_TP not in mesh.axis_names:
        return 1
    return mesh.shape[AXIS_TP]


def _sorted_dispatch(x: jax.Array,            # [B, T, D]
                     wg: jax.Array, wu: jax.Array, wd: jax.Array,
                     vals: jax.Array,          # [B, T, K] renormalized gates
                     idx: jax.Array            # [B, T, K] expert ids
                     ) -> jax.Array:
    """Exact sorted MoE dispatch: flatten (token, k) assignments, stable-sort
    by expert, run each expert's contiguous group through `lax.ragged_dot`,
    scatter-add the weighted outputs back. No capacity limit, no dropped
    tokens — same math as the dense formulation (summation order aside) —
    at K-per-token FFN cost
    instead of E-per-token. TPU lowers ragged_dot onto the MXU with
    group-size prefetch."""
    B, T, D = x.shape
    E = wg.shape[0]
    K = idx.shape[-1]
    N = B * T
    xf = x.reshape(N, D)
    flat_e = idx.reshape(N * K)
    flat_g = vals.reshape(N * K)
    order = jnp.argsort(flat_e, stable=True)           # [N*K]
    tok = order // K                                   # source token per slot
    xs = xf[tok]                                       # [N*K, D]
    counts = jnp.zeros((E,), jnp.int32).at[flat_e].add(1)
    g = jax.lax.ragged_dot(xs, wg, counts)             # [N*K, F]
    u = jax.lax.ragged_dot(xs, wu, counts)
    a = (jax.nn.silu(g.astype(jnp.float32))
         * u.astype(jnp.float32)).astype(x.dtype)
    y = jax.lax.ragged_dot(a, wd, counts)              # [N*K, D]
    y = y.astype(jnp.float32) * flat_g[order][:, None]
    out = jnp.zeros((N, D), jnp.float32).at[tok].add(y)
    return out.reshape(B, T, D).astype(x.dtype)


def route_topk(x: jax.Array, wr: jax.Array, top_k: int):
    """Router: renormalized top-k gate values + expert ids ([B,T,K] each).
    Shared by every dispatch formulation (incl. forward_pp's in-stage MoE)
    so the gating policy has exactly one implementation."""
    logits = jnp.einsum("btd,de->bte", x, wr.astype(x.dtype))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    vals, idx = jax.lax.top_k(probs, top_k)               # [B,T,K]
    return vals / jnp.sum(vals, axis=-1, keepdims=True), idx


def dense_gates(vals: jax.Array, idx: jax.Array, n_experts: int) -> jax.Array:
    """One-hot gate matrix [B,T,E] for dense dispatch."""
    return jnp.sum(jax.nn.one_hot(idx, n_experts, dtype=jnp.float32)
                   * vals[..., None], axis=-2)


def expert_ffn(x: jax.Array, wg: jax.Array, wu: jax.Array, wd: jax.Array,
               gates: jax.Array) -> jax.Array:
    """Dense-dispatch expert compute for ONE shard's local experts.
    Shapes per shard: wg/wu [El, D, F], wd [El, F, D], gates [B,T,El].
    Pure per-shard math — safe inside any enclosing shard_map (forward_pp's
    pp x ep stage body psums the result over ep/tp itself)."""
    g = jnp.einsum("btd,edf->btef", x, wg)
    u = jnp.einsum("btd,edf->btef", x, wu)
    a = jax.nn.silu(g) * u
    return jnp.einsum("btef,efd,bte->btd", a, wd, gates.astype(x.dtype))


def moe_ffn(x: jax.Array,           # [B, T, D]
            wr: jax.Array,          # [D, E] router
            wg: jax.Array,          # [E, D, F] expert gate projections
            wu: jax.Array,          # [E, D, F] expert up projections
            wd: jax.Array,          # [E, F, D] expert down projections
            top_k: int,
            mesh=None) -> jax.Array:
    """Routed MoE feed-forward. Returns [B, T, D] in x.dtype."""
    E = wr.shape[1]
    vals, idx = route_topk(x, wr, top_k)

    ep = _ep_size(mesh)
    tp = _tp_size(mesh)
    F = wg.shape[2]
    tp_ffn = tp if tp > 1 and F % tp == 0 else 1
    if ep <= 1 and tp_ffn <= 1:
        B, T, _ = x.shape
        if B * T >= 16:
            # compute-bound chunks: sorted exact dispatch costs K-per-token
            # FFN work instead of dense dispatch's E-per-token
            return _sorted_dispatch(x, wg, wu, wd, vals, idx)

    # dense dispatch (tiny decode batches / sharded meshes) consumes the
    # one-hot gates tensor; only built where used
    gates = dense_gates(vals, idx, E)                     # [B,T,E]
    experts = expert_ffn

    if ep <= 1 and tp_ffn <= 1:
        return experts(x, wg, wu, wd, gates)

    # expert dim shards over ep; the FFN intermediate dim additionally
    # shards over tp (each shard computes an F/tp slice of its local
    # experts — the down-projection contraction leaves partial sums, so
    # the combine is one psum over BOTH axes)
    axes = tuple(a for a, n in ((AXIS_EP, ep), (AXIS_TP, tp_ffn)) if n > 1)

    def local(x, wg, wu, wd, gates):
        y = experts(x, wg, wu, wd, gates)
        return jax.lax.psum(y, axes)

    ftp = AXIS_TP if tp_ffn > 1 else None
    eax = AXIS_EP if ep > 1 else None
    return jax.shard_map(
        local, mesh=mesh,
        in_specs=(P(None, None, None),
                  P(eax, None, ftp), P(eax, None, ftp), P(eax, ftp, None),
                  P(None, None, eax)),
        out_specs=P(None, None, None),
        check_vma=False,
    )(x, wg, wu, wd, gates)
