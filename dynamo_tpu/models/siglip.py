"""SigLIP vision tower + Gemma3 multimodal projector (JAX/TPU-native).

The vision half of Gemma3 VLM serving: images -> patch embeddings -> ViT
encoder -> avg-pooled, RMS-normed, projected soft tokens the language model
consumes in place of ``<image_soft_token>`` embeddings. Pure functions over
a params pytree, bf16-friendly, everything jittable — the tower is one
more XLA program on the serving device, not a separate runtime.

Layout notes (TPU-first): the patch conv is expressed as an unfold+matmul
(patches are non-overlapping, stride == kernel), which lowers onto the MXU
as a single [N*P², 3*ps²] x [3*ps², D] matmul instead of a conv; attention
is full bidirectional over P² patches (no masking, no KV cache — images
are encoded once per request at prefill).

Reference capability: the reference serves Gemma3 VLM through its engine
zoo (support_matrix.md); HF parity target:
transformers Gemma3 vision_tower (SiglipVisionModel) +
Gemma3MultiModalProjector (modeling_gemma3.py:693-726).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class SiglipVisionConfig:
    hidden_size: int = 1152          # SigLIP-400M defaults (Gemma3's tower)
    num_layers: int = 27
    num_heads: int = 16
    intermediate_size: int = 4304
    image_size: int = 896
    patch_size: int = 14
    num_channels: int = 3
    layer_norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16

    @property
    def patches_per_side(self) -> int:
        return self.image_size // self.patch_size

    @property
    def num_patches(self) -> int:
        return self.patches_per_side ** 2

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any],
                       dtype=jnp.bfloat16) -> "SiglipVisionConfig":
        return cls(
            hidden_size=cfg["hidden_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            intermediate_size=cfg["intermediate_size"],
            image_size=cfg["image_size"],
            patch_size=cfg["patch_size"],
            num_channels=cfg.get("num_channels", 3),
            layer_norm_eps=cfg.get("layer_norm_eps", 1e-6),
            dtype=dtype,
        )


def init_params(cfg: SiglipVisionConfig, key: jax.Array) -> Dict[str, Any]:
    """Random-init tower params (tests / benching without checkpoints).
    Patch embedding is stored PRE-UNFOLDED: [ps*ps*3, D] (HWIO flattened),
    ready for the matmul formulation."""
    D, F, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    Dh = D // cfg.num_heads
    ps, C = cfg.patch_size, cfg.num_channels
    ks = jax.random.split(key, 12)
    dt = cfg.dtype

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(shape[0])).astype(dt)

    return {
        "patch_w": norm(ks[0], ps * ps * C, D),
        "patch_b": jnp.zeros((D,), dt),
        "pos_embed": norm(ks[1], cfg.num_patches, D),
        "layers": {
            "ln1_w": jnp.ones((L, D), jnp.float32),
            "ln1_b": jnp.zeros((L, D), jnp.float32),
            "ln2_w": jnp.ones((L, D), jnp.float32),
            "ln2_b": jnp.zeros((L, D), jnp.float32),
            "wq": norm(ks[2], L, D, D), "bq": jnp.zeros((L, D), dt),
            "wk": norm(ks[3], L, D, D), "bk": jnp.zeros((L, D), dt),
            "wv": norm(ks[4], L, D, D), "bv": jnp.zeros((L, D), dt),
            "wo": norm(ks[5], L, D, D), "bo": jnp.zeros((L, D), dt),
            "fc1": norm(ks[6], L, D, F), "fb1": jnp.zeros((L, F), dt),
            "fc2": norm(ks[7], L, F, D), "fb2": jnp.zeros((L, D), dt),
        },
        "post_ln_w": jnp.ones((D,), jnp.float32),
        "post_ln_b": jnp.zeros((D,), jnp.float32),
    }


def _layer_norm(x: jax.Array, w: jax.Array, b: jax.Array,
                eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps) * w + b).astype(x.dtype)


def patchify(cfg: SiglipVisionConfig, pixels: jax.Array) -> jax.Array:
    """[N, C, H, W] -> [N, P², ps*ps*C] non-overlapping patch unfold, rows
    ordered row-major over the patch grid (matching Conv2d stride=kernel).
    Inner layout per row is (ph, pw, C) — HWIO — so one matmul against the
    pre-flattened conv kernel reproduces the convolution exactly."""
    N, C, H, W = pixels.shape
    ps = cfg.patch_size
    gh, gw = H // ps, W // ps
    x = pixels.reshape(N, C, gh, ps, gw, ps)
    #            N  gh  gw  ps  ps  C   -> rows (gh*gw), inner (ps, ps, C)
    x = x.transpose(0, 2, 4, 3, 5, 1)
    return x.reshape(N, gh * gw, ps * ps * C)


def forward(params: Dict[str, Any], cfg: SiglipVisionConfig,
            pixels: jax.Array) -> jax.Array:
    """Vision tower: [N, C, H, W] (normalized pixels) -> [N, P², D]."""
    lp = params["layers"]
    D = cfg.hidden_size
    H = cfg.num_heads
    Dh = D // H
    x = patchify(cfg, pixels.astype(cfg.dtype)) @ params["patch_w"] \
        + params["patch_b"]
    x = x + params["pos_embed"][None]
    N, P, _ = x.shape

    scale = 1.0 / math.sqrt(Dh)
    for l in range(cfg.num_layers):
        h = _layer_norm(x, lp["ln1_w"][l], lp["ln1_b"][l],
                        cfg.layer_norm_eps)
        q = (h @ lp["wq"][l] + lp["bq"][l]).reshape(N, P, H, Dh)
        k = (h @ lp["wk"][l] + lp["bk"][l]).reshape(N, P, H, Dh)
        v = (h @ lp["wv"][l] + lp["bv"][l]).reshape(N, P, H, Dh)
        s = jnp.einsum("nqhd,nkhd->nhqk", q, k).astype(jnp.float32) * scale
        a = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("nhqk,nkhd->nqhd", a, v).reshape(N, P, D)
        x = x + (o @ lp["wo"][l] + lp["bo"][l])
        h2 = _layer_norm(x, lp["ln2_w"][l], lp["ln2_b"][l],
                         cfg.layer_norm_eps)
        f = jax.nn.gelu(h2 @ lp["fc1"][l] + lp["fb1"][l], approximate=True)
        x = x + (f @ lp["fc2"][l] + lp["fb2"][l])
    return _layer_norm(x, params["post_ln_w"], params["post_ln_b"],
                       cfg.layer_norm_eps)


# ---------------------------------------------------------------------------
# Gemma3 multimodal projector
# ---------------------------------------------------------------------------

def init_projector_params(cfg: SiglipVisionConfig, text_hidden: int,
                          key: jax.Array) -> Dict[str, Any]:
    return {
        # Gemma RMS convention: stored weight is the OFFSET from 1 (HF
        # Gemma3RMSNorm initializes to zeros; effective scale is 1+w)
        "norm": jnp.zeros((cfg.hidden_size,), jnp.float32),
        "proj": (jax.random.normal(key, (cfg.hidden_size, text_hidden),
                                   jnp.float32)
                 / math.sqrt(cfg.hidden_size)).astype(cfg.dtype),
    }


def project(params: Dict[str, Any], cfg: SiglipVisionConfig,
            vision_out: jax.Array, mm_tokens_per_image: int,
            rms_eps: float = None) -> jax.Array:
    """[N, P², Dv] -> [N, mm_tokens, Dtext]: avg-pool the patch grid down
    to tokens_per_side², Gemma-RMSNorm with the stored weight as a +1
    offset (HF Gemma3RMSNorm semantics), project. Mirrors
    Gemma3MultiModalProjector (modeling_gemma3.py:693-726)."""
    N, P2, Dv = vision_out.shape
    pps = cfg.patches_per_side
    tps = int(math.isqrt(mm_tokens_per_image))
    assert tps * tps == mm_tokens_per_image, \
        f"mm_tokens_per_image {mm_tokens_per_image} must be a square"
    kern = pps // tps
    x = vision_out.reshape(N, pps, pps, Dv)
    x = x.reshape(N, tps, kern, tps, kern, Dv).mean(axis=(2, 4))  # avgpool
    x = x.reshape(N, tps * tps, Dv)
    # Gemma3RMSNorm: output = x * rsqrt(mean(x²)+eps) * (1 + weight)
    xf = x.astype(jnp.float32)
    nrm = xf * jax.lax.rsqrt(
        jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        + (cfg.layer_norm_eps if rms_eps is None else rms_eps))
    nrm = nrm * (1.0 + params["norm"])
    return (nrm @ params["proj"].astype(jnp.float32)).astype(vision_out.dtype)


# ---------------------------------------------------------------------------
# HF weight loading (numpy dict of tensors, names as in Gemma3 checkpoints)
# ---------------------------------------------------------------------------

def params_from_hf(tensors: Dict[str, np.ndarray], cfg: SiglipVisionConfig,
                   prefix: str = "vision_tower.vision_model."
                   ) -> Dict[str, Any]:
    """Map HF SiglipVisionModel tensors onto our pytree. ``tensors`` maps
    full names -> numpy arrays (the loader's safetensors accessor)."""
    D, L = cfg.hidden_size, cfg.num_layers
    ps, C = cfg.patch_size, cfg.num_channels
    dt = cfg.dtype

    def g(name):
        return np.asarray(tensors[prefix + name])

    # Conv2d weight [D, C, ph, pw] -> unfold layout [(ph pw C), D]
    conv = g("embeddings.patch_embedding.weight")
    patch_w = conv.transpose(2, 3, 1, 0).reshape(ps * ps * C, D)

    def lay(i, name):
        return np.asarray(tensors[f"{prefix}encoder.layers.{i}.{name}"])

    def stack(name, t=False):
        ws = [lay(i, name) for i in range(L)]
        return np.stack([w.T if t else w for w in ws])

    return {
        "patch_w": jnp.asarray(patch_w, dt),
        "patch_b": jnp.asarray(g("embeddings.patch_embedding.bias"), dt),
        "pos_embed": jnp.asarray(g("embeddings.position_embedding.weight"),
                                 dt),
        "layers": {
            "ln1_w": jnp.asarray(stack("layer_norm1.weight"), jnp.float32),
            "ln1_b": jnp.asarray(stack("layer_norm1.bias"), jnp.float32),
            "ln2_w": jnp.asarray(stack("layer_norm2.weight"), jnp.float32),
            "ln2_b": jnp.asarray(stack("layer_norm2.bias"), jnp.float32),
            # HF Linear stores [out, in]; ours is [in, out]
            "wq": jnp.asarray(stack("self_attn.q_proj.weight", t=True), dt),
            "bq": jnp.asarray(stack("self_attn.q_proj.bias"), dt),
            "wk": jnp.asarray(stack("self_attn.k_proj.weight", t=True), dt),
            "bk": jnp.asarray(stack("self_attn.k_proj.bias"), dt),
            "wv": jnp.asarray(stack("self_attn.v_proj.weight", t=True), dt),
            "bv": jnp.asarray(stack("self_attn.v_proj.bias"), dt),
            "wo": jnp.asarray(stack("self_attn.out_proj.weight", t=True), dt),
            "bo": jnp.asarray(stack("self_attn.out_proj.bias"), dt),
            "fc1": jnp.asarray(stack("mlp.fc1.weight", t=True), dt),
            "fb1": jnp.asarray(stack("mlp.fc1.bias"), dt),
            "fc2": jnp.asarray(stack("mlp.fc2.weight", t=True), dt),
            "fb2": jnp.asarray(stack("mlp.fc2.bias"), dt),
        },
        "post_ln_w": jnp.asarray(g("post_layernorm.weight"), jnp.float32),
        "post_ln_b": jnp.asarray(g("post_layernorm.bias"), jnp.float32),
    }


def projector_from_hf(tensors: Dict[str, np.ndarray],
                      cfg: SiglipVisionConfig,
                      prefix: str = "multi_modal_projector."
                      ) -> Dict[str, Any]:
    return {
        "norm": jnp.asarray(
            np.asarray(tensors[prefix + "mm_soft_emb_norm.weight"]),
            jnp.float32),
        "proj": jnp.asarray(
            np.asarray(tensors[prefix + "mm_input_projection_weight"]),
            cfg.dtype),
    }
