"""Llama model family (Llama 2/3/3.x, DeepSeek-R1-Distill-Llama) — functional
JAX implementation built for paged-KV serving.

Design (TPU-first, not a torch translation):
- Params are a plain pytree of stacked per-layer weights; sharding is declared
  once as PartitionSpecs (tp over heads / ffn) and applied with NamedSharding —
  XLA inserts all collectives.
- The KV cache is a flat paged pool ([L, N_tokens_pool, H_kv, D_h]); sequences
  own pages via integer page tables. Writes are scatters at token indices,
  reads are gathers — both static-shaped so every step compiles once.
- One forward function serves both prefill chunks (T>1) and decode (T=1):
  write-then-gather with a causal+length mask. Static shapes everywhere
  (bucketed T and S) per XLA's compile-once model.
- bf16 weights/activations, fp32 norms/softmax/logits (MXU-friendly).

Reference capability equivalent: the in-engine model executed by vLLM/TRT-LLM
behind the reference's engine adapters (SURVEY §2.1, §7 step 3).
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import AXIS_TP


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

_GEMMA_ARCHS = ("GemmaForCausalLM", "Gemma2ForCausalLM",
                "Gemma3ForCausalLM")


_GEMMA_VLM_ARCH = "Gemma3ForConditionalGeneration"

# Gemma3TextConfig defaults (transformers): real hub checkpoints ship sparse
# text_configs that omit these entirely (e.g. google/gemma-3-4b-it's
# text_config has no vocab_size) and rely on the class defaults — without
# them from_hf_config KeyErrors at startup on a real checkpoint.
_GEMMA3_TEXT_DEFAULTS: Dict[str, Any] = {
    "vocab_size": 262208,
    "hidden_size": 2304,
    "intermediate_size": 9216,
    "num_hidden_layers": 26,
    "num_attention_heads": 8,
    "num_key_value_heads": 4,
    "head_dim": 256,
    "rope_theta": 1e6,
    "rope_local_base_freq": 10000.0,
    "query_pre_attn_scalar": 256,
    "max_position_embeddings": 131072,
    "rms_norm_eps": 1e-6,
    # omitting sliding_window must NOT read as "no sliding attention":
    # the class default (4096) keeps layer_sliding() live
    "sliding_window": 4096,
}


def _is_gemma(cfg: Dict[str, Any]) -> bool:
    archs = cfg.get("architectures", []) or []
    # VLM Gemma3 configs are nested (text_config/vision_config) and handled
    # by from_hf_config before this runs on the flat text config
    unsupported = [a for a in archs
                   if "Gemma" in a and a not in _GEMMA_ARCHS
                   and a != _GEMMA_VLM_ARCH]
    if unsupported:
        raise ValueError(f"unsupported architecture {unsupported[0]!r} "
                         f"(text Gemma v1/v2/v3 and Gemma3 VLM are "
                         f"supported)")
    return any(a in _GEMMA_ARCHS for a in archs)


def _is_gemma2(cfg: Dict[str, Any]) -> bool:
    return "Gemma2ForCausalLM" in (cfg.get("architectures", []) or [])


def _is_gemma3(cfg: Dict[str, Any]) -> bool:
    return "Gemma3ForCausalLM" in (cfg.get("architectures", []) or [])


def _map_act(cfg: Dict[str, Any]) -> str:
    """HF activation name -> ours; exact vs tanh-approx GELU matters for
    logits parity, so unknown names raise instead of guessing."""
    if _is_gemma(cfg):
        return "gelu_tanh"
    act = str(cfg.get("hidden_activation")
              or cfg.get("hidden_act") or "silu")
    if act in ("silu", "swish"):
        return "silu"
    if act in ("gelu_pytorch_tanh", "gelu_tanh", "gelu_new",
               "gelu_fast"):
        return "gelu_tanh"
    if act == "gelu":
        return "gelu"
    raise ValueError(f"unsupported hidden_act {act!r}")


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    intermediate_size: int = 14336
    rope_theta: float = 500000.0
    rope_scaling: Optional[Dict[str, Any]] = None
    rms_eps: float = 1e-5
    max_position: int = 8192
    tie_embeddings: bool = False
    # q/k/v projection biases (Qwen2-style attention; Llama/Mistral: False)
    attention_bias: bool = False
    # Gemma-style family knobs: tanh-GELU gating (GeGLU), zero-centered
    # RMSNorm weights (output scales by 1+w), sqrt(D)-scaled embeddings
    hidden_act: str = "silu"            # "silu" | "gelu_tanh"
    norm_offset: bool = False
    embed_scale: bool = False
    # Gemma2-style knobs: 4 norms per layer (post-attn + post-ffn sandwich
    # norms), tanh softcapping of attention scores / final logits, sliding-
    # window attention on even layers, and an explicit attention scale
    # (rsqrt(query_pre_attn_scalar) instead of rsqrt(head_dim))
    sandwich_norms: bool = False
    attn_logit_softcap: Optional[float] = None
    final_logit_softcap: Optional[float] = None
    sliding_window: Optional[int] = None
    query_pre_attn_scalar: Optional[float] = None
    # Gemma3-style knobs: every Nth layer is FULL attention, the rest
    # sliding (gemma2: 2 — alternating; gemma3: 6 — 5:1); sliding layers
    # rope at their own base frequency; per-head RMSNorm on q/k
    sliding_pattern: int = 2
    rope_local_theta: Optional[float] = None
    qk_norm: bool = False
    dtype: Any = jnp.bfloat16
    # MoE (0 experts = dense FFN). Experts shard over the ep mesh axis.
    num_experts: int = 0
    experts_per_token: int = 2
    # Gemma3 VLM: a SigLIP vision tower rides alongside the text stack
    # (HF vision_config dict; models/siglip.py builds from it). Image soft
    # tokens replace ``image_token_id`` placeholder embeddings at prefill.
    vision: Optional[Dict[str, Any]] = None
    mm_tokens_per_image: int = 256
    image_token_id: Optional[int] = None

    def layer_sliding(self, layer: int) -> bool:
        """Every ``sliding_pattern``-th layer is full attention, the rest
        sliding (gemma2: 2 — alternating, even layers slide; gemma3: 6 —
        five sliding then one full)."""
        return (self.sliding_window is not None
                and (layer + 1) % self.sliding_pattern != 0)

    @property
    def attn_scale(self) -> float:
        base = (self.query_pre_attn_scalar
                if self.query_pre_attn_scalar is not None else self.head_dim)
        return 1.0 / math.sqrt(base)

    @classmethod
    def from_hf_config(cls, cfg: Dict[str, Any], dtype=jnp.bfloat16) -> "LlamaConfig":
        """Map a HF ``config.json`` (LlamaForCausalLM family) onto ours.
        Gemma3 VLM configs nest the text model under ``text_config``: the
        text half maps recursively; the vision tower + mm wiring land on
        the vision fields."""
        if _GEMMA_VLM_ARCH in (cfg.get("architectures", []) or []):
            if "text_config" not in cfg or "vision_config" not in cfg:
                raise ValueError(
                    f"{_GEMMA_VLM_ARCH} config must nest text_config and "
                    f"vision_config; refusing to guess a flat layout")
            text = dict(cfg["text_config"])
            # the nested text config usually omits architectures — restore
            # the family marker so the gemma3 mapping rules fire
            text.setdefault("architectures", ["Gemma3ForCausalLM"])
            base = cls.from_hf_config(text, dtype=dtype)
            return cls(**{
                **base.__dict__,
                "vision": dict(cfg["vision_config"]),
                "mm_tokens_per_image": int(cfg.get("mm_tokens_per_image",
                                                   256)),
                # the hub config spells it image_token_index (boi/eoi
                # likewise); newer transformers re-exports *_id — accept both
                "image_token_id": int(
                    cfg.get("image_token_id",
                            cfg.get("image_token_index", 262144))),
            })
        if cfg.get("model_type") == "gemma3_text":
            # sparse real-checkpoint text_config: class defaults fill the gaps
            cfg = {**_GEMMA3_TEXT_DEFAULTS, **cfg}
        return cls(
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg.get("num_key_value_heads", cfg["num_attention_heads"]),
            head_dim=cfg.get("head_dim",
                             cfg["hidden_size"] // cfg["num_attention_heads"]),
            intermediate_size=cfg["intermediate_size"],
            rope_theta=cfg.get("rope_theta", 10000.0),
            rope_scaling=cfg.get("rope_scaling"),
            rms_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position=cfg.get("max_position_embeddings", 8192),
            tie_embeddings=cfg.get("tie_word_embeddings", False),
            # Qwen2 has qkv bias baked into the architecture; HF encodes it
            # via model class, newer configs carry attention_bias explicitly
            attention_bias=bool(cfg.get(
                "attention_bias",
                any("Qwen2" in a for a in cfg.get("architectures", []) or []))),
            hidden_act=_map_act(cfg),
            norm_offset=_is_gemma(cfg),
            embed_scale=_is_gemma(cfg),
            sandwich_norms=_is_gemma2(cfg) or _is_gemma3(cfg),
            attn_logit_softcap=(cfg.get("attn_logit_softcapping")
                                if _is_gemma2(cfg) else None),
            final_logit_softcap=(cfg.get("final_logit_softcapping")
                                 if _is_gemma2(cfg) else None),
            sliding_window=(cfg.get("sliding_window")
                            if _is_gemma2(cfg) or _is_gemma3(cfg) else None),
            query_pre_attn_scalar=(cfg.get("query_pre_attn_scalar")
                                   if _is_gemma2(cfg) or _is_gemma3(cfg)
                                   else None),
            sliding_pattern=_sliding_pattern(cfg),
            rope_local_theta=(cfg.get("rope_local_base_freq", 10000.0)
                              if _is_gemma3(cfg) else None),
            qk_norm=_is_gemma3(cfg),
            dtype=dtype,
        )


def _sliding_pattern(cfg: Dict[str, Any]) -> int:
    """Period of the full-attention layers: from ``layer_types`` when the
    config carries it (position of the first 'full_attention' + 1), else
    the family default (gemma2: 2, gemma3: 6)."""
    lt = cfg.get("layer_types")
    if lt:
        period = None
        for i, t in enumerate(lt):
            if t == "full_attention":
                period = i + 1
                break
        if period is None:
            return len(lt) + 1   # all sliding
        # refuse rather than mis-serve: the whole list must actually
        # follow the "(period-1) sliding, then full" repetition
        for i, t in enumerate(lt):
            want = ("full_attention" if (i + 1) % period == 0
                    else "sliding_attention")
            if t != want:
                raise ValueError(
                    f"layer_types is not periodic with full every "
                    f"{period} layers (index {i} is {t!r})")
        return period
    return 6 if _is_gemma3(cfg) else 2


# test/bench presets (shapes only; weights are random or loaded)
PRESETS: Dict[str, Dict[str, Any]] = {
    # tiny model over the byte tokenizer vocab — the hermetic test model
    "tiny-byte": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4,
                      num_kv_heads=2, head_dim=16, intermediate_size=128,
                      rope_theta=10000.0, max_position=1024),
    # tiny MoE over the byte vocab: 4 experts, top-2 routing (EP tests)
    "tiny-moe": dict(vocab_size=259, hidden_size=64, num_layers=2, num_heads=4,
                     num_kv_heads=2, head_dim=16, intermediate_size=96,
                     rope_theta=10000.0, max_position=1024, num_experts=4,
                     experts_per_token=2),
    "llama-3.2-1b": dict(vocab_size=128256, hidden_size=2048, num_layers=16,
                         num_heads=32, num_kv_heads=8, head_dim=64,
                         intermediate_size=8192, rope_theta=500000.0,
                         max_position=131072, tie_embeddings=True),
    "llama-3-8b": dict(vocab_size=128256, hidden_size=4096, num_layers=32,
                       num_heads=32, num_kv_heads=8, head_dim=128,
                       intermediate_size=14336, rope_theta=500000.0,
                       max_position=8192),
    "llama-3-70b": dict(vocab_size=128256, hidden_size=8192, num_layers=80,
                        num_heads=64, num_kv_heads=8, head_dim=128,
                        intermediate_size=28672, rope_theta=500000.0,
                        max_position=8192),
    # tiny Qwen2-style model (qkv bias) over the byte vocab
    "tiny-qwen": dict(vocab_size=259, hidden_size=64, num_layers=2,
                      num_heads=4, num_kv_heads=2, head_dim=16,
                      intermediate_size=128, rope_theta=10000.0,
                      max_position=1024, attention_bias=True,
                      tie_embeddings=True),
    "qwen2-1.5b": dict(vocab_size=151936, hidden_size=1536, num_layers=28,
                       num_heads=12, num_kv_heads=2, head_dim=128,
                       intermediate_size=8960, rope_theta=1000000.0,
                       max_position=32768, attention_bias=True,
                       tie_embeddings=True, rms_eps=1e-6),
    "qwen2-7b": dict(vocab_size=152064, hidden_size=3584, num_layers=28,
                     num_heads=28, num_kv_heads=4, head_dim=128,
                     intermediate_size=18944, rope_theta=1000000.0,
                     max_position=32768, attention_bias=True, rms_eps=1e-6),
    "mistral-7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32,
                       num_heads=32, num_kv_heads=8, head_dim=128,
                       intermediate_size=14336, rope_theta=10000.0,
                       max_position=32768, rms_eps=1e-5),
    # tiny Gemma-style model (GeGLU, offset norms, scaled embed)
    "tiny-gemma": dict(vocab_size=259, hidden_size=64, num_layers=2,
                       num_heads=4, num_kv_heads=1, head_dim=16,
                       intermediate_size=128, rope_theta=10000.0,
                       max_position=1024, tie_embeddings=True,
                       hidden_act="gelu_tanh", norm_offset=True,
                       embed_scale=True, rms_eps=1e-6),
    # tiny Gemma2-style model: sandwich norms, softcaps, sliding window
    "tiny-gemma2": dict(vocab_size=259, hidden_size=64, num_layers=2,
                        num_heads=4, num_kv_heads=1, head_dim=16,
                        intermediate_size=128, rope_theta=10000.0,
                        max_position=1024, tie_embeddings=True,
                        hidden_act="gelu_tanh", norm_offset=True,
                        embed_scale=True, rms_eps=1e-6,
                        sandwich_norms=True, attn_logit_softcap=50.0,
                        final_logit_softcap=30.0, sliding_window=8,
                        query_pre_attn_scalar=24.0),
    "gemma2-9b": dict(vocab_size=256000, hidden_size=3584, num_layers=42,
                      num_heads=16, num_kv_heads=8, head_dim=256,
                      intermediate_size=14336, rope_theta=10000.0,
                      max_position=8192, tie_embeddings=True,
                      hidden_act="gelu_tanh", norm_offset=True,
                      embed_scale=True, rms_eps=1e-6,
                      sandwich_norms=True, attn_logit_softcap=50.0,
                      final_logit_softcap=30.0, sliding_window=4096,
                      query_pre_attn_scalar=256.0),
    "gemma2-27b": dict(vocab_size=256000, hidden_size=4608, num_layers=46,
                       num_heads=32, num_kv_heads=16, head_dim=128,
                       intermediate_size=36864, rope_theta=10000.0,
                       max_position=8192, tie_embeddings=True,
                       hidden_act="gelu_tanh", norm_offset=True,
                       embed_scale=True, rms_eps=1e-6,
                       sandwich_norms=True, attn_logit_softcap=50.0,
                       final_logit_softcap=30.0, sliding_window=4096,
                       query_pre_attn_scalar=144.0),
    # tiny Gemma3-style model: qk-norm, dual-base rope, 5:1 sliding
    "tiny-gemma3": dict(vocab_size=259, hidden_size=64, num_layers=6,
                        num_heads=4, num_kv_heads=2, head_dim=16,
                        intermediate_size=128, rope_theta=1000000.0,
                        max_position=1024, tie_embeddings=True,
                        hidden_act="gelu_tanh", norm_offset=True,
                        embed_scale=True, rms_eps=1e-6,
                        sandwich_norms=True, sliding_window=8,
                        sliding_pattern=3, rope_local_theta=10000.0,
                        qk_norm=True, query_pre_attn_scalar=24.0),
    "gemma3-4b": dict(vocab_size=262208, hidden_size=2560, num_layers=34,
                      num_heads=8, num_kv_heads=4, head_dim=256,
                      intermediate_size=10240, rope_theta=1000000.0,
                      rope_scaling={"rope_type": "linear", "factor": 8.0},
                      max_position=131072, tie_embeddings=True,
                      hidden_act="gelu_tanh", norm_offset=True,
                      embed_scale=True, rms_eps=1e-6, sandwich_norms=True,
                      sliding_window=1024, sliding_pattern=6,
                      rope_local_theta=10000.0, qk_norm=True,
                      query_pre_attn_scalar=256.0),
    "gemma3-12b": dict(vocab_size=262208, hidden_size=3840, num_layers=48,
                       num_heads=16, num_kv_heads=8, head_dim=256,
                       intermediate_size=15360, rope_theta=1000000.0,
                       rope_scaling={"rope_type": "linear", "factor": 8.0},
                       max_position=131072, tie_embeddings=True,
                       hidden_act="gelu_tanh", norm_offset=True,
                       embed_scale=True, rms_eps=1e-6, sandwich_norms=True,
                       sliding_window=1024, sliding_pattern=6,
                       rope_local_theta=10000.0, qk_norm=True,
                       query_pre_attn_scalar=256.0),
    # tiny Gemma3 VLM: text stack of tiny-gemma3 + a 2-layer SigLIP tower
    # (56x56 images, 14px patches -> 16 patches -> 4 soft tokens/image)
    "tiny-gemma3-vlm": dict(vocab_size=259, hidden_size=64, num_layers=6,
                            num_heads=4, num_kv_heads=2, head_dim=16,
                            intermediate_size=128, rope_theta=1000000.0,
                            max_position=1024, tie_embeddings=True,
                            hidden_act="gelu_tanh", norm_offset=True,
                            embed_scale=True, rms_eps=1e-6,
                            sandwich_norms=True, sliding_window=8,
                            sliding_pattern=3, rope_local_theta=10000.0,
                            qk_norm=True, query_pre_attn_scalar=24.0,
                            mm_tokens_per_image=4, image_token_id=250,
                            vision=dict(hidden_size=32, num_hidden_layers=2,
                                        num_attention_heads=4,
                                        intermediate_size=48, image_size=56,
                                        patch_size=14)),
    "gemma-2b": dict(vocab_size=256000, hidden_size=2048, num_layers=18,
                     num_heads=8, num_kv_heads=1, head_dim=256,
                     intermediate_size=16384, rope_theta=10000.0,
                     max_position=8192, tie_embeddings=True,
                     hidden_act="gelu_tanh", norm_offset=True,
                     embed_scale=True, rms_eps=1e-6),
    "gemma-7b": dict(vocab_size=256000, hidden_size=3072, num_layers=28,
                     num_heads=16, num_kv_heads=16, head_dim=256,
                     intermediate_size=24576, rope_theta=10000.0,
                     max_position=8192, tie_embeddings=True,
                     hidden_act="gelu_tanh", norm_offset=True,
                     embed_scale=True, rms_eps=1e-6),
}


def preset(name: str, **overrides) -> LlamaConfig:
    d = dict(PRESETS[name])
    d.update(overrides)
    return LlamaConfig(**d)


# ---------------------------------------------------------------------------
# Params: init + shardings
# ---------------------------------------------------------------------------

def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Random-init params (testing/benching without checkpoint files)."""
    D, Hq, Hkv, Dh, F, L, V = (cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads,
                               cfg.head_dim, cfg.intermediate_size,
                               cfg.num_layers, cfg.vocab_size)
    ks = jax.random.split(key, 10)
    s = lambda *shape: 1.0 / math.sqrt(shape[0])

    def norm(k, *shape):
        return (jax.random.normal(k, shape, jnp.float32) * s(*shape)).astype(cfg.dtype)

    E = cfg.num_experts
    if E:
        ffn = {
            "wr": norm(ks[9], L, D, E),
            "wg": norm(ks[5], L, E, D, F),
            "wu": norm(ks[6], L, E, D, F),
            "wd": norm(ks[7], L, E, F, D),
        }
    else:
        ffn = {
            "wg": norm(ks[5], L, D, F),
            "wu": norm(ks[6], L, D, F),
            "wd": norm(ks[7], L, F, D),
        }
    params = {
        "embed": norm(ks[0], V, D),
        "layers": {
            "ln1": jnp.ones((L, D), jnp.float32),
            "ln2": jnp.ones((L, D), jnp.float32),
            "wq": norm(ks[1], L, D, Hq * Dh).reshape(L, D, Hq, Dh),
            "wk": norm(ks[2], L, D, Hkv * Dh).reshape(L, D, Hkv, Dh),
            "wv": norm(ks[3], L, D, Hkv * Dh).reshape(L, D, Hkv, Dh),
            "wo": norm(ks[4], L, Hq * Dh, D).reshape(L, Hq, Dh, D),
            **ffn,
        },
        "final_norm": jnp.ones((D,), jnp.float32),
    }
    if cfg.sandwich_norms:
        # random (not ones) so parity tests catch a dropped/ misplaced norm
        kn = jax.random.split(ks[8], 2)
        params["layers"]["ln1_post"] = norm(kn[0], L, D).astype(jnp.float32)
        params["layers"]["ln2_post"] = norm(kn[1], L, D).astype(jnp.float32)
    if cfg.qk_norm:
        kq = jax.random.split(ks[6], 2)
        params["layers"]["ln_q"] = norm(kq[0], L, Dh).astype(jnp.float32)
        params["layers"]["ln_k"] = norm(kq[1], L, Dh).astype(jnp.float32)
    if cfg.attention_bias:
        kb = jax.random.split(ks[9], 3)
        # non-zero random biases so parity tests would catch a dropped bias
        params["layers"]["bq"] = norm(kb[0], L, Hq, Dh)
        params["layers"]["bk"] = norm(kb[1], L, Hkv, Dh)
        params["layers"]["bv"] = norm(kb[2], L, Hkv, Dh)
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(ks[8], D, V)
    return params


def param_specs(cfg: LlamaConfig, tp_size: int = 1,
                pp: int = 1) -> Dict[str, Any]:
    """PartitionSpecs: tp shards attention heads, the ffn dimension, and —
    when the model is untied and the vocab divides tp — the LM head's vocab
    dim. KV projections replicate when GQA kv_heads aren't divisible by tp;
    the embedding stays replicated (token gathers need the full table).
    With ``pp > 1`` the stacked layer dim of every per-layer param shards
    over the pipeline axis (each stage materializes only its layers)."""
    from ..parallel.mesh import AXIS_EP, AXIS_PP

    st = AXIS_PP if pp > 1 else None     # the [L, ...] stack dim
    tp = AXIS_TP
    kv = tp if cfg.num_kv_heads % max(tp_size, 1) == 0 else None
    if cfg.num_experts:
        # experts shard over ep ([L, E, D, F] / [L, E, F, D]); router
        # replicated; the FFN intermediate dim additionally shards over tp
        # when divisible (matching moe_ffn's shard_map specs)
        ftp = tp if cfg.intermediate_size % max(tp_size, 1) == 0 else None
        ffn = {
            "wr": P(st, None, None),
            "wg": P(st, AXIS_EP, None, ftp),
            "wu": P(st, AXIS_EP, None, ftp),
            "wd": P(st, AXIS_EP, ftp, None),
        }
    else:
        ffn = {
            "wg": P(st, None, tp),
            "wu": P(st, None, tp),
            "wd": P(st, tp, None),
        }
    specs = {
        "embed": P(None, None),
        "layers": {
            "ln1": P(st, None),
            "ln2": P(st, None),
            "wq": P(st, None, tp, None),
            "wk": P(st, None, kv, None),
            "wv": P(st, None, kv, None),
            "wo": P(st, tp, None, None),
            **ffn,
        },
        "final_norm": P(None),
    }
    if cfg.sandwich_norms:
        specs["layers"]["ln1_post"] = P(st, None)
        specs["layers"]["ln2_post"] = P(st, None)
    if cfg.qk_norm:
        specs["layers"]["ln_q"] = P(st, None)
        specs["layers"]["ln_k"] = P(st, None)
    if cfg.attention_bias:
        specs["layers"]["bq"] = P(st, tp, None)
        specs["layers"]["bk"] = P(st, kv, None)
        specs["layers"]["bv"] = P(st, kv, None)
    if not cfg.tie_embeddings:
        # vocab-sharded head: the [B,D]x[D,V] logits matmul partitions over
        # tp (each chip computes V/tp columns); GSPMD all-gathers the row
        # only where sampling consumes it. Weight memory drops V*D/tp too.
        head_tp = tp if cfg.vocab_size % max(tp_size, 1) == 0 else None
        specs["lm_head"] = P(None, head_tp)
    return specs


def validate_tp(cfg: LlamaConfig, tp: int, ep: int = 1) -> None:
    if cfg.num_heads % tp:
        raise ValueError(f"num_heads {cfg.num_heads} not divisible by tp={tp}")
    if not cfg.num_experts and cfg.intermediate_size % tp:
        raise ValueError(f"ffn {cfg.intermediate_size} not divisible by tp={tp}")
    if ep > 1:
        if not cfg.num_experts:
            raise ValueError("ep > 1 needs an MoE model (num_experts > 0)")
        if cfg.num_experts % ep:
            raise ValueError(f"num_experts {cfg.num_experts} not divisible "
                             f"by ep={ep}")


def validate_pp(cfg: LlamaConfig, pp: int, tp: int = 1) -> None:
    """Pipeline-parallel constraints for the staged serving path."""
    if pp <= 1:
        return
    if cfg.num_layers % pp:
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by pp={pp}")
    if tp > 1 and cfg.num_kv_heads % tp:
        raise ValueError(
            f"pp > 1 with tp={tp} needs kv heads divisible by tp "
            f"(got {cfg.num_kv_heads}): the staged path shards the KV pool")


def kv_block_bytes(cfg: LlamaConfig, page_size: int) -> int:
    """Bytes of one KV block (k+v, all layers) at device precision — the
    ONE unit the byte-honest planes price in (engine residency gauges,
    paged-lane admission, router bytes scoring). ml_dtypes registers
    bfloat16 with numpy, so np.dtype resolves every served precision."""
    return (2 * cfg.num_layers * cfg.num_kv_heads * page_size
            * cfg.head_dim * np.dtype(cfg.dtype).itemsize)


def kv_cache_spec(cfg: LlamaConfig, tp: int, pp: int = 1) -> P:
    """KV pool sharding ([L, Hkv, n_pages, page, Dh]): shard kv heads over tp
    when divisible, else replicate (GQA with kv_heads < tp). With ``pp > 1``
    the layer dim additionally shards over the pipeline axis — each stage
    holds only its layers' pages (the memory win that fits 70B on slices)."""
    from ..parallel.mesh import AXIS_PP

    st = AXIS_PP if pp > 1 else None
    if cfg.num_kv_heads % tp == 0:
        return P(st, AXIS_TP, None, None, None)
    return P(st, None, None, None, None)


# ---------------------------------------------------------------------------
# Ops
# ---------------------------------------------------------------------------

def rms_norm(x: jax.Array, w: jax.Array, eps: float,
             offset: bool = False) -> jax.Array:
    """RMSNorm; ``offset=True`` = Gemma convention (weights stored
    zero-centered, output scales by 1 + w)."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    wf = w.astype(jnp.float32)
    if offset:
        wf = 1.0 + wf
    return (xf * scale * wf).astype(x.dtype)


def _act(cfg: "LlamaConfig"):
    if cfg.hidden_act == "gelu_tanh":
        return partial(jax.nn.gelu, approximate=True)
    if cfg.hidden_act == "gelu":
        return partial(jax.nn.gelu, approximate=False)
    return jax.nn.silu


def _embed(params: Dict[str, Any], cfg: "LlamaConfig",
           tokens: jax.Array) -> jax.Array:
    x = params["embed"][tokens]
    if cfg.embed_scale:
        # Gemma scales inputs by sqrt(D), rounded through the embed dtype
        x = x * jnp.asarray(math.sqrt(cfg.hidden_size), x.dtype)
    return x


def _rope_inv_freq(cfg: LlamaConfig, local: bool = False) -> np.ndarray:
    Dh = cfg.head_dim
    if local:
        # gemma3 sliding layers: own base frequency, NO scaling (HF builds
        # the local rotary with default rope_type regardless of
        # config.rope_scaling)
        theta = cfg.rope_local_theta or cfg.rope_theta
        return (1.0 / (theta ** (np.arange(0, Dh, 2, dtype=np.float64) / Dh))
                ).astype(np.float32)
    inv = 1.0 / (cfg.rope_theta ** (np.arange(0, Dh, 2, dtype=np.float64) / Dh))
    rs = cfg.rope_scaling or {}
    if rs.get("rope_type") == "linear" or rs.get("type") == "linear":
        # linear position scaling (gemma3 4b+): frequencies divide by factor
        inv = inv / rs.get("factor", 1.0)
    if rs.get("rope_type") == "ggml_factors":
        # llama.cpp exports llama3-style scaling as a rope_freqs tensor of
        # per-frequency divisors (ggml applies inv_freq / factor[i])
        factors = np.asarray(rs["factors"], dtype=np.float64)
        if factors.shape != inv.shape:
            raise ValueError(
                f"rope_freqs tensor has {factors.shape[0]} factors but "
                f"head_dim {Dh} needs {inv.shape[0]}")
        inv = inv / factors
    if rs.get("rope_type") == "llama3" or rs.get("type") == "llama3":
        # llama3 frequency-dependent NTK-style scaling
        factor = rs.get("factor", 8.0)
        lo = rs.get("low_freq_factor", 1.0)
        hi = rs.get("high_freq_factor", 4.0)
        orig = rs.get("original_max_position_embeddings", 8192)
        wavelen = 2 * np.pi / inv
        ratio = orig / wavelen
        smooth = np.clip((ratio - lo) / (hi - lo), 0.0, 1.0)
        scaled = np.where(ratio < lo, inv / factor,
                          np.where(ratio > hi, inv,
                                   (1 - smooth) * inv / factor + smooth * inv))
        inv = scaled
    return inv.astype(np.float32)


def rope_tables(cfg: LlamaConfig, positions: jax.Array,
                local: bool = False) -> Tuple[jax.Array, jax.Array]:
    """cos/sin tables for given integer positions [...]: -> [..., Dh/2].
    ``local=True`` = the sliding layers' table (gemma3 dual-base rope)."""
    inv = jnp.asarray(_rope_inv_freq(cfg, local=local))
    ang = positions[..., None].astype(jnp.float32) * inv
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [..., H, Dh]; cos/sin: [..., Dh/2] (broadcast over H)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[..., None, :]
    s = sin[..., None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


NEG_INF = -1e30


def attend(q: jax.Array, k: jax.Array, v: jax.Array, mask: jax.Array,
           scale: Optional[float] = None,
           softcap: Optional[float] = None) -> jax.Array:
    """GQA attention. q: [B,T,Hq,Dh]; k,v: [B,S,Hkv,Dh]; mask: [B,T,S] bool
    (True = attend). Returns [B,T,Hq,Dh]. fp32 softmax. ``softcap`` applies
    Gemma2's tanh capping to the scores BEFORE masking (HF order)."""
    B, T, Hq, Dh = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    qg = q.reshape(B, T, Hkv, G, Dh)
    scores = jnp.einsum("bthgd,bshd->bhgts", qg, k,
                        preferred_element_type=jnp.float32)
    scores = scores * (scale if scale is not None else 1.0 / math.sqrt(Dh))
    if softcap:
        scores = jnp.tanh(scores / softcap) * softcap
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgts,bshd->bthgd", w.astype(v.dtype), v)
    return out.reshape(B, T, Hq, Dh)


def _attn_residual(x: jax.Array, attn_out: jax.Array, lp: Dict[str, Any],
                   l: int, cfg: LlamaConfig) -> jax.Array:
    """Residual add after attention; Gemma2 norms the branch output first."""
    if cfg.sandwich_norms:
        attn_out = rms_norm(attn_out, lp["ln1_post"][l], cfg.rms_eps,
                            cfg.norm_offset)
    return x + attn_out


def _ffn_block(x: jax.Array, lp: Dict[str, Any], l: int, cfg: LlamaConfig,
               mesh=None) -> jax.Array:
    """Pre-norm FFN (dense or MoE) + residual; Gemma2 adds a post-norm on
    the branch output (sandwich norms)."""
    h2 = rms_norm(x, lp["ln2"][l], cfg.rms_eps, cfg.norm_offset)
    if cfg.num_experts:
        from .moe import moe_ffn
        out = moe_ffn(h2, lp["wr"][l], lp["wg"][l], lp["wu"][l],
                      lp["wd"][l], cfg.experts_per_token, mesh=mesh)
    else:
        g = jnp.einsum("btd,df->btf", h2, lp["wg"][l])
        u = jnp.einsum("btd,df->btf", h2, lp["wu"][l])
        out = jnp.einsum("btf,fd->btd", _act(cfg)(g) * u, lp["wd"][l])
    if cfg.sandwich_norms:
        out = rms_norm(out, lp["ln2_post"][l], cfg.rms_eps, cfg.norm_offset)
    return x + out


def _lm_head(x: jax.Array, params: Dict[str, Any],
             cfg: LlamaConfig) -> jax.Array:
    """Final norm + vocab projection (+ Gemma2 final logit softcap), fp32."""
    x = rms_norm(x, params["final_norm"], cfg.rms_eps, cfg.norm_offset)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("btd,dv->btv", x, head.astype(x.dtype))
    logits = logits.astype(jnp.float32)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = jnp.tanh(logits / cap) * cap
    return logits


def _require_xla_attn(cfg: LlamaConfig, attn_impl: str) -> None:
    """Ring attention is the one path left without softcap/sliding support
    (cross-shard windows don't compose with the ring schedule); the Pallas
    flash/paged kernels take window+softcap+scale natively (round 5 —
    Gemma2/3 no longer forfeit the fast path)."""
    if attn_impl == "ring" and (cfg.attn_logit_softcap
                                or cfg.sliding_window is not None):
        raise ValueError(
            "attn_impl='ring' does not support score softcapping / sliding "
            "windows (Gemma2/3); use attn_impl='pallas' or 'xla'")


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def forward(params: Dict[str, Any], cfg: LlamaConfig,
            tokens: jax.Array,           # [B, T] int32 (decode: T=1)
            positions: jax.Array,        # [B, T] int32 position of each token
            k_pool: jax.Array,           # [L, Hkv, n_pages, page, Dh] KV pool
            v_pool: jax.Array,
            write_idx: jax.Array,        # [B, T] int32 pool token-slot per new token
            read_idx: jax.Array,         # [B, S] int32 pool token-slots to attend over
            read_pos: jax.Array,         # [B, S] int32 position of each read slot
            read_valid: jax.Array,       # [B, S] bool slot holds a real token
            attn_impl: str = "xla",      # "xla" | "flash" Pallas | "ring" sp
            mesh=None,                   # required for attn_impl="ring"
            logits_idx: Optional[jax.Array] = None,  # [B] per-lane position
            embed_override: Optional[Tuple[jax.Array, jax.Array]] = None,
            attn_spans: Optional[Tuple[jax.Array, jax.Array]] = None,
            ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One forward pass over a token chunk against the paged KV pool.

    The pool is head-major ([L, Hkv, n_pages, page, Dh] — so ``pool[l]`` is
    directly the layout TPU paged-attention kernels consume); token-slot
    indices (page_id * page_size + offset) address it. The new chunk's K/V
    are scattered into the pool at ``write_idx`` first; attention then
    gathers ``read_idx`` (which must cover the chunk itself) and masks
    causally by position: token at position p attends to slots with
    ``read_pos <= p``. Works for prefill chunks and single-token decode
    alike.

    Returns (logits [B, T, vocab] fp32, k_pool, v_pool). With ``logits_idx``
    ([B] int32), the LM head runs only on each lane's hidden state at that
    chunk position and logits are [B, 1, vocab] — the prefill fast path,
    which never materializes the [B, T, vocab] tensor.

    Multimodal (Gemma3 VLM, xla attention only):

    - ``embed_override`` = (vals [B,T,D], mask [B,T] bool) replaces the
      masked positions' embeddings AFTER the embed scale — projected image
      soft tokens are injected raw, exactly HF's masked_scatter
      (modeling_gemma3.py:908-914).
    - ``attn_spans`` = (q_span [B,T], read_span [B,S]) int32 image-group
      ids (0 = text): tokens of the SAME image attend bidirectionally —
      the or-mask applies to full and sliding layers alike
      (modeling_gemma3.py:936-953).
    """
    B, T = tokens.shape
    page = k_pool.shape[3]
    lp = params["layers"]
    x = _embed(params, cfg, tokens)  # [B,T,D] bf16
    if embed_override is not None:
        ov_vals, ov_mask = embed_override
        x = jnp.where(ov_mask[..., None], ov_vals.astype(x.dtype), x)
    cos, sin = rope_tables(cfg, positions)
    if cfg.rope_local_theta is not None:
        cos_l, sin_l = rope_tables(cfg, positions, local=True)
    flat_w = write_idx.reshape(-1)
    wp, wo = flat_w // page, flat_w % page
    rp, ro = read_idx // page, read_idx % page
    if attn_impl == "ring":
        from ..parallel.mesh import AXIS_TP as _TP
        from ..parallel.ring_attention import ring_attention
        head_axis = _TP if (
            mesh is not None and _TP in mesh.axis_names
            and mesh.shape[_TP] > 1
            and cfg.num_heads % mesh.shape[_TP] == 0
            and cfg.num_kv_heads % mesh.shape[_TP] == 0) else None
    elif attn_impl == "flash":
        tp_sz = _tp_size(mesh)
        from ..ops.attention import flash_attention as _flash
        _flash_cache: Dict[Optional[int], Any] = {}

        def flash_for(layer: int):
            """Kernel variant for this layer (softcap/scale always, window
            on sliding layers) — window is a static kernel param, so the
            two layer classes get two compiled variants, built once."""
            w = cfg.sliding_window if cfg.layer_sliding(layer) else None
            if w not in _flash_cache:
                fn = partial(
                    _flash, scale=cfg.attn_scale,
                    softcap=cfg.attn_logit_softcap, window=w)
                if tp_sz > 1:
                    # per-shard flash kernel: heads sharded over tp, kv
                    # heads when divisible (replicated otherwise);
                    # sequence dims replicated
                    kv_spec = (P(None, None, AXIS_TP, None)
                               if cfg.num_kv_heads % tp_sz == 0
                               else P(None, None, None, None))
                    fn = jax.shard_map(
                        fn, mesh=mesh,
                        in_specs=(P(None, None, AXIS_TP, None), kv_spec,
                                  kv_spec, P(None, None), P(None, None),
                                  P(None, None)),
                        out_specs=P(None, None, AXIS_TP, None),
                        check_vma=False)   # pallas_call can't declare vma
                _flash_cache[w] = fn
            return _flash_cache[w]
    else:
        # causal/validity mask [B,T,S]
        mask = (read_valid[:, None, :]
                & (read_pos[:, None, :] <= positions[:, :, None]))
        if cfg.sliding_window is not None:
            # Gemma2 even layers: keys within the last `window` positions
            sliding_mask = mask & (
                read_pos[:, None, :]
                > positions[:, :, None] - cfg.sliding_window)
        if attn_spans is not None:
            # same-image bidirectional attention ORs into BOTH masks
            q_span, read_span = attn_spans
            bidir = ((q_span[:, :, None] > 0)
                     & (q_span[:, :, None] == read_span[:, None, :])
                     & read_valid[:, None, :])
            mask = mask | bidir
            if cfg.sliding_window is not None:
                sliding_mask = sliding_mask | bidir
    if attn_spans is not None and attn_impl != "xla":
        raise ValueError(
            "image-span bidirectional attention (Gemma3 VLM) runs on "
            "attn_impl='xla' only; flash/ring kernels take no span inputs")
    _require_xla_attn(cfg, attn_impl)

    # NOTE: forward_pp.apply_stage mirrors this layer body for the
    # pipeline-parallel stages; test_forward_pp pins their exactness —
    # change them together.
    for l in range(cfg.num_layers):
        h = rms_norm(x, lp["ln1"][l], cfg.rms_eps, cfg.norm_offset)
        q = jnp.einsum("btd,dhk->bthk", h, lp["wq"][l])
        k = jnp.einsum("btd,dhk->bthk", h, lp["wk"][l])
        v = jnp.einsum("btd,dhk->bthk", h, lp["wv"][l])
        if cfg.attention_bias:
            q = q + lp["bq"][l]
            k = k + lp["bk"][l]
            v = v + lp["bv"][l]
        if cfg.qk_norm:
            # gemma3: per-head RMSNorm on q/k AFTER projection, BEFORE rope
            q = rms_norm(q, lp["ln_q"][l], cfg.rms_eps, cfg.norm_offset)
            k = rms_norm(k, lp["ln_k"][l], cfg.rms_eps, cfg.norm_offset)
        if cfg.rope_local_theta is not None and cfg.layer_sliding(l):
            q = apply_rope(q, cos_l, sin_l)
            k = apply_rope(k, cos_l, sin_l)
        else:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        # scatter chunk KV into the pool (write-then-gather). The scalar
        # layer index is itself an "advanced" index, so the batched dims of
        # [l, :, wp, wo] land in FRONT of the Hkv slice: shape [n, Hkv, Dh]
        k_pool = k_pool.at[l, :, wp, wo].set(k.reshape(B * T, *k.shape[2:]))
        v_pool = v_pool.at[l, :, wp, wo].set(v.reshape(B * T, *v.shape[2:]))
        # gather this sequence's context (same rule): [B, S, Hkv, Dh]
        k_ctx = k_pool[l, :, rp, ro]
        v_ctx = v_pool[l, :, rp, ro]
        if attn_impl == "flash":
            attn = flash_for(l)(q, k_ctx, v_ctx, positions, read_pos,
                                read_valid)
        elif attn_impl == "ring":
            attn = ring_attention(q, k_ctx, v_ctx, positions, read_pos,
                                  read_valid, mesh=mesh,
                                  head_axis=head_axis,
                                  scale=cfg.attn_scale)
        else:
            attn = attend(q, k_ctx, v_ctx,
                          sliding_mask if cfg.layer_sliding(l) else mask,
                          scale=cfg.attn_scale,
                          softcap=cfg.attn_logit_softcap)
        x = _attn_residual(x, jnp.einsum("bthk,hkd->btd", attn, lp["wo"][l]),
                           lp, l, cfg)
        x = _ffn_block(x, lp, l, cfg, mesh=mesh)

    if logits_idx is not None:
        x = jnp.take_along_axis(
            x, logits_idx[:, None, None].astype(jnp.int32), axis=1)  # [B,1,D]
    return _lm_head(x, params, cfg), k_pool, v_pool


def forward_pp(params: Dict[str, Any], cfg: LlamaConfig,
               tokens: jax.Array,        # [M, Bm, T] microbatched token ids
               positions: jax.Array,     # [M, Bm, T]
               k_pool: jax.Array,        # [L, Hkv, n_pages, page, Dh]
               v_pool: jax.Array,
               write_idx: jax.Array,     # [M, Bm, T]
               read_idx: jax.Array,      # [M, Bm, S]
               read_pos: jax.Array,      # [M, Bm, S]
               read_valid: jax.Array,    # [M, Bm, S]
               mesh,                     # must carry a pp axis > 1 (or == 1)
               logits_idx: Optional[jax.Array] = None,  # [M, Bm] positions
               attn_impl: str = "xla",   # "xla" gather | "flash" in-stage
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pipeline-parallel forward: the layer stack is split into ``pp``
    contiguous stages (params AND the KV pools sharded on the layer dim —
    each device materializes only its stage's weights and pages, the memory
    win that fits 70B-class models on small slices). Microbatches enter
    stage 0 one per step; activations hop stages with ``ppermute``; KV
    writes land in each stage's local pool shard. Exact vs. the sequential
    :func:`forward` per microbatch.

    Composes with tensor parallelism: when the mesh carries a tp axis > 1,
    heads/ffn shard over tp WITHIN each stage (manual-SPMD psum after the
    wo/wd contractions — the scaling-book megatron recipe), and the KV pool
    shards over (pp: layers, tp: kv heads).

    Returns (logits [M, Bm, T, V] fp32, k_pool, v_pool); with ``logits_idx``
    ([M, Bm] int32), the LM head runs only at each lane's given chunk
    position and logits are [M, Bm, 1, V] (the prefill fast path). Embedding
    and head run outside the stage loop under GSPMD (they are not
    layer-stacked).

    Reference capability: SURVEY §2.5 pipeline parallelism (the reference
    delegates to vLLM `pipeline_parallel_size`); here the model compute
    path itself is pp-partitioned and engine-served (JaxEngineConfig.pp).
    """
    from ..parallel.mesh import AXIS_EP, AXIS_PP

    M, Bm, T = tokens.shape
    L = cfg.num_layers
    pp = _pp_size(mesh)
    _require_xla_attn(cfg, attn_impl)
    if pp == 1:
        outs = []
        li = None
        for m in range(M):
            if logits_idx is not None:
                li = logits_idx[m]
            lg, k_pool, v_pool = forward(
                params, cfg, tokens[m], positions[m], k_pool, v_pool,
                write_idx[m], read_idx[m], read_pos[m], read_valid[m],
                logits_idx=li)
            outs.append(lg)
        return jnp.stack(outs), k_pool, v_pool
    assert L % pp == 0, f"layers {L} must divide pp {pp}"
    tp_sz = _tp_size(mesh)
    # per-shard GQA grouping must stay integral: with kv heads replicated a
    # shard would silently pair its local q heads with the wrong kv heads
    assert cfg.num_kv_heads % tp_sz == 0, \
        f"pp with tp={tp_sz} needs kv heads divisible (got {cfg.num_kv_heads})"
    # pp x ep (round 5): the stage body computes its LOCAL experts' dense
    # dispatch for the full token set and psums over ep — same math as
    # moe_ffn's sharded formulation, inlined because we're already inside
    # the pp(+tp) shard_map and shard_maps don't nest
    ep_sz = (mesh.shape[AXIS_EP]
             if mesh is not None and AXIS_EP in mesh.axis_names else 1)
    E = cfg.num_experts
    El = E // ep_sz if E else 0
    moe_tp = (tp_sz if E and tp_sz > 1
              and cfg.intermediate_size % tp_sz == 0 else 1)
    page = k_pool.shape[3]
    lp = params["layers"]

    # embed + rope for every microbatch, replicated (cheap, not stacked);
    # rope_tables handles arbitrary leading dims
    x0 = _embed(params, cfg, tokens)                   # [M, Bm, T, D]
    cos, sin = rope_tables(cfg, positions)             # [M, Bm, T, Dh/2]
    if cfg.rope_local_theta is not None:
        cos_sl, sin_sl = rope_tables(cfg, positions, local=True)
    else:
        cos_sl, sin_sl = cos, sin   # unused; keeps the shard_map arity fixed

    perm_fwd = [(i, (i + 1) % pp) for i in range(pp)]

    def local(lp_loc, kp_loc, vp_loc, x0, cos, sin, cos_sl, sin_sl,
              positions, widx, ridx, rpos, rvalid):
        idx = jax.lax.axis_index(AXIS_PP)
        Lloc = L // pp
        cur = jnp.zeros_like(x0[0])
        outs = jnp.zeros_like(x0)

        def apply_stage(carry, mb, live):
            cur, kp, vp = carry
            c_m = jax.lax.dynamic_index_in_dim(cos, mb, keepdims=False)
            s_m = jax.lax.dynamic_index_in_dim(sin, mb, keepdims=False)
            cl_m = jax.lax.dynamic_index_in_dim(cos_sl, mb, keepdims=False)
            sl_m = jax.lax.dynamic_index_in_dim(sin_sl, mb, keepdims=False)
            widx_m = jax.lax.dynamic_index_in_dim(widx, mb, keepdims=False)
            ridx_m = jax.lax.dynamic_index_in_dim(ridx, mb, keepdims=False)
            rpos_m = jax.lax.dynamic_index_in_dim(rpos, mb, keepdims=False)
            rval_m = jax.lax.dynamic_index_in_dim(rvalid, mb, keepdims=False)
            pos_m = jax.lax.dynamic_index_in_dim(positions, mb,
                                                 keepdims=False)
            flat_w = widx_m.reshape(-1)
            # bubble steps write NOTHING: out-of-bounds page index + drop
            # mode gates the scatter itself (a whole-pool select per step
            # would copy the dominant HBM tensor twice each step)
            flat_w = jnp.where(live, flat_w, kp.shape[2] * page)
            wp, wo = flat_w // page, flat_w % page
            rp, ro = ridx_m // page, ridx_m % page
            mask = (rval_m[:, None, :]
                    & (rpos_m[:, None, :] <= pos_m[:, :, None]))
            if cfg.sliding_window is not None:
                sliding_mask = mask & (
                    rpos_m[:, None, :]
                    > pos_m[:, :, None] - cfg.sliding_window)
            # mirrors forward's xla layer body (see the NOTE there);
            # test_forward_pp pins exactness between the two. With tp > 1
            # each shard computes its head/ffn slice; the wo/wd
            # contractions produce partial sums reduced over tp.
            x = cur
            for l in range(Lloc):
                h = rms_norm(x, lp_loc["ln1"][l], cfg.rms_eps, cfg.norm_offset)
                q = jnp.einsum("btd,dhk->bthk", h, lp_loc["wq"][l])
                k = jnp.einsum("btd,dhk->bthk", h, lp_loc["wk"][l])
                v = jnp.einsum("btd,dhk->bthk", h, lp_loc["wv"][l])
                if cfg.attention_bias:
                    q = q + lp_loc["bq"][l]
                    k = k + lp_loc["bk"][l]
                    v = v + lp_loc["bv"][l]
                if cfg.qk_norm:
                    q = rms_norm(q, lp_loc["ln_q"][l], cfg.rms_eps,
                                 cfg.norm_offset)
                    k = rms_norm(k, lp_loc["ln_k"][l], cfg.rms_eps,
                                 cfg.norm_offset)
                if (cfg.rope_local_theta is not None
                        and cfg.sliding_window is not None):
                    # gemma3 dual-base rope: the GLOBAL layer index (traced
                    # stage offset) picks local vs global tables — same
                    # guard as cfg.layer_sliding so pp stays exact vs the
                    # sequential forward when sliding_window is unset
                    sl = (idx * Lloc + l + 1) % cfg.sliding_pattern != 0
                    c_sel = jnp.where(sl, cl_m, c_m)
                    s_sel = jnp.where(sl, sl_m, s_m)
                else:
                    c_sel, s_sel = c_m, s_m
                q = apply_rope(q, c_sel, s_sel)
                k = apply_rope(k, c_sel, s_sel)
                kp = kp.at[l, :, wp, wo].set(
                    k.reshape(-1, *k.shape[2:]), mode="drop")
                vp = vp.at[l, :, wp, wo].set(
                    v.reshape(-1, *v.shape[2:]), mode="drop")
                k_ctx = kp[l, :, rp, ro]
                v_ctx = vp[l, :, rp, ro]
                if attn_impl == "flash":
                    # in-stage Pallas flash: we're already inside manual
                    # SPMD (pp x tp shard_map), so the kernel runs on this
                    # shard's q/kv head slices directly — same per-shard
                    # call shape as forward()'s tp path (removes the
                    # pp-forfeits-kernels restriction, VERDICT r3 weak #5)
                    from ..ops.attention import flash_attention
                    fl = partial(flash_attention, scale=cfg.attn_scale,
                                 softcap=cfg.attn_logit_softcap)
                    if cfg.sliding_window is not None:
                        # sliding-vs-full depends on the GLOBAL layer index
                        # (traced stage offset); window is a static kernel
                        # param — cond picks between the two compiled
                        # variants at run time
                        sl = (idx * Lloc + l + 1) % cfg.sliding_pattern != 0
                        attn = jax.lax.cond(
                            sl,
                            partial(fl, window=cfg.sliding_window),
                            fl, q, k_ctx, v_ctx, pos_m, rpos_m, rval_m)
                    else:
                        attn = fl(q, k_ctx, v_ctx, pos_m, rpos_m, rval_m)
                elif cfg.sliding_window is not None:
                    # the GLOBAL layer index (stage offset + local index)
                    # decides sliding vs full — idx is traced, so select
                    m_l = jnp.where(
                        (idx * Lloc + l + 1) % cfg.sliding_pattern != 0,
                        sliding_mask, mask)
                    attn = attend(q, k_ctx, v_ctx, m_l,
                                  scale=cfg.attn_scale,
                                  softcap=cfg.attn_logit_softcap)
                else:
                    attn = attend(q, k_ctx, v_ctx, mask,
                                  scale=cfg.attn_scale,
                                  softcap=cfg.attn_logit_softcap)
                o = jnp.einsum("bthk,hkd->btd", attn, lp_loc["wo"][l])
                if tp_sz > 1:
                    o = jax.lax.psum(o, AXIS_TP)
                x = _attn_residual(x, o, lp_loc, l, cfg)
                h2 = rms_norm(x, lp_loc["ln2"][l], cfg.rms_eps, cfg.norm_offset)
                if E:
                    # routed MoE: router replicated, experts sharded over
                    # ep (and F over tp when divisible). Dense dispatch —
                    # every local expert sees every token; non-local gate
                    # weights are zero, so the ep psum is exact. Gating and
                    # expert math are moe.py's shared helpers: the pp path
                    # cannot silently diverge from the pp=1 moe_ffn policy.
                    from .moe import dense_gates, expert_ffn, route_topk
                    vals, topi = route_topk(h2, lp_loc["wr"][l],
                                            cfg.experts_per_token)
                    gates = dense_gates(vals, topi, E)     # [B, T, E]
                    if ep_sz > 1:
                        eidx = jax.lax.axis_index(AXIS_EP)
                        gates = jax.lax.dynamic_slice_in_dim(
                            gates, eidx * El, El, axis=2)  # local slice
                    f = expert_ffn(h2, lp_loc["wg"][l], lp_loc["wu"][l],
                                   lp_loc["wd"][l], gates)
                    axes = tuple(ax for ax, n in ((AXIS_EP, ep_sz),
                                                  (AXIS_TP, moe_tp))
                                 if n > 1)
                    if axes:
                        f = jax.lax.psum(f, axes)
                else:
                    g = jnp.einsum("btd,df->btf", h2, lp_loc["wg"][l])
                    u = jnp.einsum("btd,df->btf", h2, lp_loc["wu"][l])
                    f = jnp.einsum("btf,fd->btd", _act(cfg)(g) * u,
                                   lp_loc["wd"][l])
                    if tp_sz > 1:
                        f = jax.lax.psum(f, AXIS_TP)
                if cfg.sandwich_norms:
                    f = rms_norm(f, lp_loc["ln2_post"][l], cfg.rms_eps,
                                 cfg.norm_offset)
                x = x + f
            return x, kp, vp

        for t in range(M + pp - 1):
            if t < M:
                cur = jnp.where(idx == 0, x0[t], cur)
            # the microbatch THIS stage processes at step t entered at
            # t - idx; clamp keeps the index legal during bubble steps
            # (their results are masked out)
            mb = jnp.clip(t - idx, 0, M - 1)
            live = (t - idx >= 0) & (t - idx < M)
            y, kp_loc, vp_loc = apply_stage((cur, kp_loc, vp_loc), mb, live)
            if t >= pp - 1:
                m_out = t - (pp - 1)
                outs = outs.at[m_out].set(
                    jnp.where(idx == pp - 1, y, outs[m_out]))
            cur = jax.lax.ppermute(y, AXIS_PP, perm_fwd)
        outs = jax.lax.psum(
            jnp.where(jax.lax.axis_index(AXIS_PP) == pp - 1, outs, 0.0),
            AXIS_PP)
        return outs, kp_loc, vp_loc

    # per-layer params carry their tp sharding INTO the stage (manual SPMD
    # over both axes); pools shard (pp: layer dim, tp: kv heads). Axis
    # names the mesh doesn't carry (pp-only meshes) are dropped.
    from ..parallel.mesh import filter_spec
    pspec = param_specs(cfg, tp_sz, pp=pp)["layers"]
    pspec = {k: filter_spec(mesh, pspec[k]) for k in lp}
    pool_spec = filter_spec(mesh, kv_cache_spec(cfg, tp_sz, pp=pp))
    rep = P()
    xs, k_pool, v_pool = jax.shard_map(
        local, mesh=mesh,
        in_specs=(pspec, pool_spec, pool_spec, rep, rep, rep, rep, rep,
                  rep, rep, rep, rep, rep),
        out_specs=(rep, pool_spec, pool_spec),
        check_vma=False,
    )(lp, k_pool, v_pool, x0, cos, sin, cos_sl, sin_sl, positions,
      write_idx, read_idx, read_pos, read_valid)

    if logits_idx is not None:
        xs = jnp.take_along_axis(
            xs, logits_idx[:, :, None, None].astype(jnp.int32), axis=2)
    xs = rms_norm(xs, params["final_norm"], cfg.rms_eps, cfg.norm_offset)
    head = params["embed"].T if cfg.tie_embeddings else params["lm_head"]
    logits = jnp.einsum("mbtd,dv->mbtv", xs, head.astype(xs.dtype))
    logits = logits.astype(jnp.float32)
    if cfg.final_logit_softcap:
        cap = cfg.final_logit_softcap
        logits = jnp.tanh(logits / cap) * cap
    return logits, k_pool, v_pool


def forward_decode_pp(params: Dict[str, Any], cfg: LlamaConfig,
                      tokens: jax.Array,        # [B] int32 last sampled
                      k_pool: jax.Array,        # [L, Hkv, n_pages, page, Dh]
                      v_pool: jax.Array,
                      page_tables: jax.Array,   # [B, P] int32
                      lengths: jax.Array,       # [B] tokens incl. current
                      mesh,
                      microbatches: int = 0,    # 0 => pp stages
                      attn_impl: str = "xla",
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-token decode through the pipeline-parallel stage loop.

    Builds the (write, read) pool addressing on device from the page tables
    — exactly :func:`forward_decode`'s XLA path — then microbatches the B
    lanes through :func:`forward_pp` to keep every stage busy. Returns
    (logits [B, 1, vocab] fp32, k_pool, v_pool).
    """
    B = tokens.shape[0]
    page = k_pool.shape[3]
    M = pp_microbatches(B, microbatches or _pp_size(mesh))
    Bm = B // M

    pos = lengths - 1                                       # [B]
    w_page = jnp.take_along_axis(page_tables, (pos // page)[:, None],
                                 axis=1)[:, 0]
    write_idx = w_page * page + pos % page                  # [B]
    S = page_tables.shape[1] * page
    t = jnp.arange(S, dtype=jnp.int32)
    rp = jnp.take_along_axis(
        page_tables, jnp.broadcast_to((t // page)[None], (B, S)), axis=1)
    read_idx = rp * page + (t % page)[None]                 # [B, S]
    read_pos = jnp.broadcast_to(t[None], (B, S))
    read_valid = t[None] < lengths[:, None]                 # [B, S]

    logits, k_pool, v_pool = forward_pp(
        params, cfg,
        tokens.reshape(M, Bm, 1),
        pos.reshape(M, Bm, 1),
        k_pool, v_pool,
        write_idx.reshape(M, Bm, 1),
        read_idx.reshape(M, Bm, S),
        read_pos.reshape(M, Bm, S),
        read_valid.reshape(M, Bm, S),
        mesh,
        logits_idx=jnp.zeros((M, Bm), jnp.int32),
        attn_impl=attn_impl,
    )
    return logits.reshape(B, 1, -1), k_pool, v_pool


def pallas_tp_ok(cfg: LlamaConfig, tp: int) -> bool:
    """Can the Pallas kernels run per-shard at this tp? Each shard needs an
    integral GQA group: Hq/tp divisible by the per-shard kv head count."""
    if tp <= 1:
        return True
    if cfg.num_heads % tp:
        return False
    hq_shard = cfg.num_heads // tp
    hkv_shard = (cfg.num_kv_heads // tp if cfg.num_kv_heads % tp == 0
                 else cfg.num_kv_heads)     # kv replicated when not divisible
    return hq_shard % hkv_shard == 0


def _tp_size(mesh) -> int:
    from ..parallel.mesh import AXIS_TP as _TP
    if mesh is None or _TP not in mesh.axis_names:
        return 1
    return mesh.shape[_TP]


def _pp_size(mesh) -> int:
    from ..parallel.mesh import AXIS_PP as _PP
    if mesh is None or _PP not in mesh.axis_names:
        return 1
    return mesh.shape[_PP]


def pp_microbatches(B: int, pp: int) -> int:
    """Largest microbatch count <= pp that divides B (keeps every pipeline
    stage busy without padding lanes). Shared by the engine's prefill
    program and :func:`forward_decode_pp` so both pipeline identically."""
    M = max(1, min(B, pp))
    while B % M:
        M -= 1
    return M


def forward_decode(params: Dict[str, Any], cfg: LlamaConfig,
                   tokens: jax.Array,        # [B] int32 — last sampled token
                   k_pool: jax.Array,        # [L, Hkv, n_pages, page, Dh]
                   v_pool: jax.Array,
                   page_tables: jax.Array,   # [B, P] int32 (pad rows: page 0)
                   lengths: jax.Array,       # [B] tokens incl. current one
                   attn_impl: str = "xla",   # "xla" gather | "pallas" paged
                   mesh=None,                # for pallas at tp>1 (shard_map)
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Single-token decode step addressed purely by page tables.

    The current token sits at position ``lengths - 1``; its KV is written
    through the page table, then attention covers tokens [0, length). With
    ``attn_impl="pallas"`` the paged-attention kernel reads pages straight
    from the HBM pool (no contiguous-context gather at all).

    Returns (logits [B, 1, vocab] fp32, k_pool, v_pool).
    """
    B = tokens.shape[0]
    page = k_pool.shape[3]
    lp = params["layers"]
    pos = lengths - 1                                  # [B]
    x = _embed(params, cfg, tokens)[:, None]           # [B,1,D]
    cos, sin = rope_tables(cfg, pos[:, None])
    if cfg.rope_local_theta is not None:
        cos_l, sin_l = rope_tables(cfg, pos[:, None], local=True)
    w_page = jnp.take_along_axis(page_tables, (pos // page)[:, None],
                                 axis=1)[:, 0]
    w_off = pos % page
    tp_sz = _tp_size(mesh) if attn_impl == "pallas" else 1
    if attn_impl == "pallas":
        from ..ops.attention import paged_attention as _paged
        _paged_cache: Dict[Optional[int], Any] = {}

        def paged_for(layer: int):
            """Per-layer kernel variant (window on sliding layers; softcap/
            scale always) — static kernel params, so the two layer classes
            compile two variants, built once. At tp>1 the kernel runs per
            tp shard: q sharded over heads, pools over kv heads when
            divisible (replicated otherwise); axes the specs don't mention
            (sp/dp/...) stay replicated."""
            w = cfg.sliding_window if cfg.layer_sliding(layer) else None
            if w not in _paged_cache:
                fn = partial(_paged, scale=cfg.attn_scale,
                             softcap=cfg.attn_logit_softcap, window=w)
                if tp_sz > 1:
                    kv_spec = (P(AXIS_TP, None, None, None)
                               if cfg.num_kv_heads % tp_sz == 0
                               else P(None, None, None, None))
                    fn = jax.shard_map(
                        fn, mesh=mesh,
                        in_specs=(P(None, AXIS_TP, None), kv_spec, kv_spec,
                                  P(None, None), P(None)),
                        out_specs=P(None, AXIS_TP, None),
                        check_vma=False)   # pallas_call can't declare vma
                _paged_cache[w] = fn
            return _paged_cache[w]
    _require_xla_attn(cfg, attn_impl)
    if attn_impl != "pallas":
        S = page_tables.shape[1] * page
        t = jnp.arange(S, dtype=jnp.int32)
        rp = jnp.take_along_axis(
            page_tables, jnp.broadcast_to((t // page)[None], (B, S)), axis=1)
        ro = jnp.broadcast_to((t % page)[None], (B, S))
        # causal == validity here: the query is the last token
        mask = (t[None] < lengths[:, None])[:, None, :]  # [B,1,S]
        if cfg.sliding_window is not None:
            # single-query: the window collapses to a per-lane slot range
            sliding_mask = mask & (
                t[None] > pos[:, None] - cfg.sliding_window)[:, None, :]

    for l in range(cfg.num_layers):
        h = rms_norm(x, lp["ln1"][l], cfg.rms_eps, cfg.norm_offset)
        q = jnp.einsum("btd,dhk->bthk", h, lp["wq"][l])
        k = jnp.einsum("btd,dhk->bthk", h, lp["wk"][l])
        v = jnp.einsum("btd,dhk->bthk", h, lp["wv"][l])
        if cfg.attention_bias:
            q = q + lp["bq"][l]
            k = k + lp["bk"][l]
            v = v + lp["bv"][l]
        if cfg.qk_norm:
            q = rms_norm(q, lp["ln_q"][l], cfg.rms_eps, cfg.norm_offset)
            k = rms_norm(k, lp["ln_k"][l], cfg.rms_eps, cfg.norm_offset)
        if cfg.rope_local_theta is not None and cfg.layer_sliding(l):
            q = apply_rope(q, cos_l, sin_l)
            k = apply_rope(k, cos_l, sin_l)
        else:
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        # [l, :, w_page, w_off] batches over the scalar l too, so the
        # indexed shape is [B, Hkv, Dh] — exactly k[:, 0]
        k_pool = k_pool.at[l, :, w_page, w_off].set(k[:, 0])
        v_pool = v_pool.at[l, :, w_page, w_off].set(v[:, 0])
        if attn_impl == "pallas":
            attn = paged_for(l)(q[:, 0], k_pool[l], v_pool[l],
                                page_tables, lengths)[:, None]
        else:
            k_ctx = k_pool[l, :, rp, ro]               # [B,S,Hkv,Dh]
            v_ctx = v_pool[l, :, rp, ro]
            attn = attend(q, k_ctx, v_ctx,
                          sliding_mask if cfg.layer_sliding(l) else mask,
                          scale=cfg.attn_scale,
                          softcap=cfg.attn_logit_softcap)
        x = _attn_residual(x, jnp.einsum("bthk,hkd->btd", attn, lp["wo"][l]),
                           lp, l, cfg)
        x = _ffn_block(x, lp, l, cfg, mesh=mesh)

    return _lm_head(x, params, cfg), k_pool, v_pool
