"""Host-side multimodal prompt assembly for Gemma3 VLM serving.

The engine keeps its compiled prefill programs token-shaped; images enter
as (a) an embedding override (projected soft tokens replacing the
``<image_soft_token>`` placeholder embeddings) and (b) per-position image
GROUP ids driving the same-image bidirectional attention mask. This module
computes both from the prompt's token ids — pure numpy, no device work.

Reference capability: the VLM prompt merge the reference inherits from its
engines (HF masked_scatter + token_type_ids mask,
transformers modeling_gemma3.py:729-953).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def image_spans(prompt: List[int], image_token_id: int) -> np.ndarray:
    """Per-position image-group ids: 0 for text, k>=1 for the k-th
    contiguous run of ``image_token_id`` placeholders."""
    ids = np.asarray(prompt, np.int64)
    is_img = ids == image_token_id
    starts = is_img & ~np.concatenate(([False], is_img[:-1]))
    groups = np.cumsum(starts)
    return np.where(is_img, groups, 0).astype(np.int32)


def validate_mm_prompt(spans: np.ndarray, n_images: int,
                       mm_tokens_per_image: int,
                       prefill_chunk: int) -> Optional[str]:
    """Returns an error string when the prompt's image layout can't be
    served, None when fine. Checks: placeholder-run count/length matches
    the attached images, and every image fits inside one prefill chunk
    (bidirectional attention must see the whole image in a single
    dispatch — the chunker aligns boundaries, it cannot split a span)."""
    groups = int(spans.max()) if spans.size else 0
    if groups != n_images:
        return (f"prompt has {groups} image placeholder run(s) but "
                f"{n_images} image(s) attached")
    for g in range(1, groups + 1):
        n = int((spans == g).sum())
        if n != mm_tokens_per_image:
            return (f"image {g} placeholder run is {n} tokens; the model "
                    f"expects exactly {mm_tokens_per_image} "
                    f"<image_soft_token>s per image")
        if mm_tokens_per_image > prefill_chunk:
            return (f"mm_tokens_per_image {mm_tokens_per_image} exceeds "
                    f"prefill_chunk {prefill_chunk}: an image span cannot "
                    f"fit one prefill dispatch")
    return None


def chunk_end(spans: np.ndarray, start: int, max_count: int) -> int:
    """Largest count <= max_count such that [start, start+count) does not
    split an image span: bidirectional attention needs every image wholly
    inside one prefill dispatch. The boundary moves BACK to the span start
    (validate_mm_prompt guarantees a span fits a full chunk, so count
    stays > 0)."""
    count = min(len(spans) - start, max_count)
    end = start + count
    if end < len(spans) and spans[end] != 0 and spans[end] == spans[end - 1]:
        g = spans[end]
        span_start = int(np.argmax(spans == g))
        if span_start > start:
            return span_start - start
        # span starts at (or before) this chunk's start and doesn't fit
        # max_count — validate_mm_prompt rejects this layout up front
        raise ValueError("image span longer than the prefill chunk")
    return count


def soft_token_rows(spans: np.ndarray, soft: np.ndarray,
                    start: int, count: int) -> Tuple[np.ndarray, np.ndarray]:
    """(vals [count, D], mask [count]) for prompt window [start,
    start+count): each image position takes its row of that image's
    projected soft tokens, in order (HF masked_scatter semantics —
    flattened image features fill flattened placeholder positions).
    ``soft``: [n_images, mm_tokens, D]."""
    D = soft.shape[-1]
    window = spans[start:start + count]
    vals = np.zeros((count, D), soft.dtype)
    mask = window > 0
    for g in np.unique(window[mask]):
        pos = np.nonzero(spans == g)[0]          # absolute positions
        rows = soft[g - 1]                       # [mm_tokens, D]
        sel = (pos >= start) & (pos < start + count)
        vals[pos[sel] - start] = rows[np.nonzero(sel)[0]]
    return vals, mask


def normalize_image(pixels: np.ndarray, image_size: int) -> np.ndarray:
    """uint8 HWC (or float CHW already normalized) -> float32 CHW in
    [-1, 1], resized to (image_size, image_size). SigLIP preprocessing:
    rescale 1/255 then normalize mean=std=0.5 (HF SiglipImageProcessor
    defaults)."""
    a = np.asarray(pixels)
    # integer HWC (uint8, or int lists off the wire — BackendInput
    # serializes pixels as nested lists, which round-trip as int64)
    if a.ndim == 3 and a.shape[-1] in (1, 3) and a.dtype.kind in "iu":
        from PIL import Image

        a = np.clip(a, 0, 255).astype(np.uint8)
        img = Image.fromarray(a if a.shape[-1] == 3
                              else np.repeat(a, 3, axis=-1))
        img = img.resize((image_size, image_size), Image.BILINEAR)
        a = np.asarray(img, np.float32) / 255.0
        a = (a - 0.5) / 0.5
        return a.transpose(2, 0, 1)
    a = a.astype(np.float32)
    if a.ndim != 3 or a.shape[0] != 3:
        raise ValueError(f"image must be uint8 HWC or float CHW, "
                         f"got shape {a.shape}")
    if a.shape[1] != image_size or a.shape[2] != image_size:
        raise ValueError(f"float CHW image must already be "
                         f"{image_size}x{image_size}, got {a.shape[1:]}")
    return a
