"""Speculative decoding for the JAX engine: pluggable draft proposers.

Decode is memory-bandwidth-bound — every step streams the whole weight set
through HBM to emit one token per lane. Speculative decoding drafts k cheap
candidate tokens per lane and verifies all of them in ONE wider forward pass
(`EngineCore._verify_fn`), so each dispatch can commit up to k+1 tokens
instead of one. This module owns the host side of that subsystem:

- :class:`NgramProposer` — prompt-lookup / self-speculation: the draft for
  the next k tokens is the continuation of the most recent earlier
  occurrence of the current suffix n-gram within the request's own
  prompt+generated tokens. No extra weights; the right default for a
  serving framework (strong on code, JSON, extraction, multi-turn chat).
- :class:`DraftModelProposer` — a second, smaller model loaded alongside
  (sharing the tokenizer) that greedily drafts k tokens against its own
  private paged KV pool. Optional; single-process deployments only.

Acceptance (greedy exact-match; rejection sampling for temperature>0) lives
in :mod:`.sampling` (``spec_verify``/``spec_accept``); the verify program
and scheduling live in :mod:`.engine`. Rejected tokens roll back by simply
never being accounted: pages are reserved ahead, block hashes seal only
over accepted tokens, and the next dispatch overwrites the stale KV slots
(the same write-then-read contract single-token decode already relies on).

Env knobs (all overridable per-engine via ``JaxEngineConfig``):

- ``DYN_SPEC``            "" (off, default) | ``ngram`` | ``draft``
- ``DYN_SPEC_K``          max draft tokens per lane per dispatch (default 4)
- ``DYN_SPEC_K_MIN``      adaptive-k floor (default 1)
- ``DYN_SPEC_ADAPT``      per-lane adaptive k on/off (default 1)
- ``DYN_SPEC_NGRAM_MAX``  longest suffix n-gram to look up (default 3)
- ``DYN_SPEC_NGRAM_MIN``  shortest suffix n-gram to fall back to (default 1)
- ``DYN_SPEC_DRAFT``      draft model: a preset name or checkpoint dir
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("dynamo_tpu.engine.spec")


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        log.warning("invalid %s=%r; using %d", name, os.environ.get(name),
                    default)
        return default


@dataclass
class SpecConfig:
    """Resolved speculative-decoding configuration (spec is ON)."""

    mode: str                   # "ngram" | "draft"
    k_max: int = 4
    k_min: int = 1
    adapt: bool = True
    ngram_max: int = 3
    ngram_min: int = 1
    ngram_window: int = 2048    # lookback tokens the n-gram match scans
    draft: Optional[str] = None  # preset name or checkpoint dir

    def __post_init__(self):
        self.k_max = max(1, int(self.k_max))
        self.k_min = max(1, min(int(self.k_min), self.k_max))
        self.ngram_min = max(1, int(self.ngram_min))
        self.ngram_max = max(self.ngram_min, int(self.ngram_max))
        self.ngram_window = max(self.ngram_max + 1, int(self.ngram_window))
        # dispatch-width buckets: powers of two up to k_max (plus k_max
        # itself) — bounds compiled verify-program count to
        # |k_buckets| x |s_buckets| no matter how adaptive k wanders
        b, out = 1, []
        while b < self.k_max:
            out.append(b)
            b *= 2
        out.append(self.k_max)
        self.k_buckets: List[int] = sorted(set(out))

    def bucket(self, k: int) -> int:
        """Smallest dispatch width covering ``k`` drafts (always >= 1: a
        zero-draft round still verifies one position, which IS a plain
        single-token decode step)."""
        for b in self.k_buckets:
            if k <= b:
                return b
        return self.k_buckets[-1]

    def next_k(self, k: int, accepted: int, proposed: int) -> int:
        """Per-lane adaptive draft length: grow on full acceptance, shrink
        on total rejection, hold otherwise."""
        if not self.adapt:
            return k
        if proposed and accepted >= proposed:
            return min(k * 2, self.k_max)
        if proposed and accepted == 0:
            return max(k // 2, self.k_min)
        return k


def resolve_spec(cfg) -> Optional[SpecConfig]:
    """Build a :class:`SpecConfig` from a ``JaxEngineConfig`` + ``DYN_SPEC*``
    env knobs. Returns None (spec fully off — zero extra compiled programs,
    untouched decode path) unless explicitly enabled."""
    mode = cfg.spec if cfg.spec is not None else os.environ.get("DYN_SPEC", "")
    mode = (mode or "").strip().lower()
    if mode in ("", "0", "off", "none", "false"):
        return None
    if mode not in ("ngram", "draft"):
        raise ValueError(f"spec/DYN_SPEC must be ngram|draft, got {mode!r}")
    return SpecConfig(
        mode=mode,
        k_max=(cfg.spec_k if cfg.spec_k is not None
               else _env_int("DYN_SPEC_K", 4)),
        k_min=_env_int("DYN_SPEC_K_MIN", 1),
        adapt=os.environ.get("DYN_SPEC_ADAPT", "1") not in ("0", "false"),
        ngram_max=_env_int("DYN_SPEC_NGRAM_MAX", 3),
        ngram_min=_env_int("DYN_SPEC_NGRAM_MIN", 1),
        ngram_window=_env_int("DYN_SPEC_NGRAM_WINDOW", 2048),
        draft=(cfg.spec_draft if cfg.spec_draft is not None
               else os.environ.get("DYN_SPEC_DRAFT") or None),
    )


@dataclass
class SeqSpecState:
    """Per-sequence speculation state (host side, engine thread)."""

    tokens: List[int]                    # committed prompt + generated
    k: int                               # current adaptive draft length
    # tokens committed since the last verify dispatch — folded into the
    # on-device penalty counts at the start of the next dispatch
    pending: List[int] = field(default_factory=list)


class NgramProposer:
    """Prompt-lookup decoding: self-speculation from the request's own
    context, no extra weights (vLLM's ``[ngram]`` method / prompt-lookup
    decoding). Looks up the most recent earlier occurrence of the current
    suffix n-gram (longest first) within a bounded lookback window and
    proposes its continuation. The match is numpy-vectorized and window-
    clipped: this runs per lane per verify round ON the engine thread, so
    a pure-Python scan over a 32k context would cost more than the verify
    forward it feeds."""

    def __init__(self, sc: SpecConfig):
        self.sc = sc

    def propose(self, seq_id: str, st: SeqSpecState, k: int) -> List[int]:
        ctx = st.tokens
        arr = np.asarray(ctx[-self.sc.ngram_window:], dtype=np.int32)
        L = arr.size
        for n in range(self.sc.ngram_max, self.sc.ngram_min - 1, -1):
            if L <= n:
                continue
            pat = arr[-n:]
            # candidate starts j in [0, L-n-1] (the suffix itself excluded)
            m = np.ones(L - n, dtype=bool)
            for o in range(n):
                m &= arr[o:o + L - n] == pat[o]
            idx = np.nonzero(m)[0]
            if idx.size:
                j = int(idx[-1]) + n   # most recent occurrence wins
                # j <= L - 1, so there is always at least one continuation
                # token (clipped at the context end)
                return [int(t) for t in arr[j:j + k]]
        return []

    def warmup(self) -> int:
        return 0   # no compiled programs on the lookup path

    def drop(self, seq_id: str) -> None:
        pass


class DraftModelProposer:
    """Greedy drafting from a second, smaller model against its own private
    paged KV pool (one page table per engine slot's sequence).

    The draft pool mirrors the main engine's bookkeeping discipline: pages
    are reserved ahead, only committed tokens are accounted, and drafted
    (uncommitted) KV writes overshoot into reserved pages where the next
    sync chunk simply overwrites them. Two jitted programs, both B=1 (the
    draft model is small; per-lane dispatch keeps shapes trivial):

    - sync: one chunk forward feeding committed tokens into the draft KV
    - propose: a ``lax.scan`` of k greedy single-token steps in ONE dispatch
    """

    def __init__(self, sc: SpecConfig, cfg, s_buckets: List[int],
                 c_buckets: List[int]):
        import jax

        from ..models import llama

        if jax.process_count() > 1:
            raise ValueError(
                "spec='draft' is single-process only for now (the draft "
                "model is not mirrored to followers); use spec='ngram'")
        self.sc = sc
        src = sc.draft or "tiny-byte"
        if os.path.exists(src):
            from ..llm.model_card import ModelDeploymentCard
            card = ModelDeploymentCard.from_local_path(src)
            if not card.model_config:
                raise ValueError(f"draft checkpoint {src} has no config")
            mcfg = llama.LlamaConfig.from_hf_config(card.model_config)
        else:
            mcfg = llama.preset(src)
        self.mcfg = mcfg
        self.page = cfg.page_size
        from .cache import PagePool
        pad = -(-(sc.k_max + 1) // self.page) * self.page
        self.pages_per_seq = -(-(cfg.max_context + pad) // self.page)
        self.pool = PagePool(cfg.max_batch * self.pages_per_seq + 1,
                             self.page)
        self.s_buckets = [min(b, self.pages_per_seq * self.page)
                          for b in s_buckets]
        self.c_buckets = list(c_buckets)
        self.chunk = self.c_buckets[-1]
        if os.path.exists(src):
            from ..parallel.mesh import serving_mesh, sharding as mk_sharding
            from jax.sharding import PartitionSpec as P

            mesh = serving_mesh(1, 1, 1, 1, [jax.devices()[0]])
            specs = llama.param_specs(mcfg, 1, 1)
            shardings = jax.tree.map(
                lambda s: mk_sharding(mesh, *s), specs,
                is_leaf=lambda x: isinstance(x, P))
            from .loader import load_llama_params
            self.params = load_llama_params(src, mcfg, shardings)
        else:
            self.params = llama.init_params(
                mcfg, jax.random.PRNGKey(cfg.seed + 101))
        import jax.numpy as jnp

        pool_shape = (mcfg.num_layers, mcfg.num_kv_heads,
                      self.pool.num_pages, self.page, mcfg.head_dim)
        zeros = jax.jit(lambda: jnp.zeros(pool_shape, mcfg.dtype))
        self.k_pool = zeros()
        self.v_pool = zeros()
        self._sync_fns: Dict[Tuple[int, int], Any] = {}
        self._prop_fns: Dict[int, Any] = {}
        self.synced: Dict[str, int] = {}   # committed tokens in draft KV

    # -- compiled programs ---------------------------------------------
    def _sync_fn(self, C: int, S: int):
        if (C, S) not in self._sync_fns:
            import jax
            import jax.numpy as jnp

            from ..models import llama
            mcfg = self.mcfg

            @partial(jax.jit, donate_argnums=(1, 2))
            def fn(params, k_pool, v_pool, tokens, positions, write_idx,
                   read_idx, read_pos, read_valid, last_i):
                logits, k_pool, v_pool = llama.forward(
                    params, mcfg, tokens, positions, k_pool, v_pool,
                    write_idx, read_idx, read_pos, read_valid,
                    attn_impl="xla", logits_idx=last_i)
                return (jnp.argmax(logits[:, 0], -1).astype(jnp.int32),
                        k_pool, v_pool)

            from ..utils.roofline import instrument_compile, record_compile
            self._sync_fns[(C, S)] = instrument_compile(
                "draft", fn, record_compile)
        return self._sync_fns[(C, S)]

    def _prop_fn(self, S: int):
        if S not in self._prop_fns:
            import jax
            import jax.numpy as jnp

            from ..models import llama
            mcfg = self.mcfg
            n_steps = self.sc.k_max

            @partial(jax.jit, donate_argnums=(1, 2))
            def fn(params, k_pool, v_pool, tok, page_table, length):
                def one(carry, _):
                    tok, length, k_pool, v_pool = carry
                    logits, k_pool, v_pool = llama.forward_decode(
                        params, mcfg, tok, k_pool, v_pool, page_table,
                        length, attn_impl="xla")
                    nxt = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                    return (nxt, length + 1, k_pool, v_pool), nxt

                (_, _, k_pool, v_pool), toks = jax.lax.scan(
                    one, (tok, length, k_pool, v_pool), None, length=n_steps)
                return toks[:, 0], k_pool, v_pool   # [n_steps]

            from ..utils.roofline import instrument_compile, record_compile
            self._prop_fns[S] = instrument_compile(
                "draft", fn, record_compile)
        return self._prop_fns[S]

    @staticmethod
    def _bucket(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    # -- proposal ------------------------------------------------------
    def propose(self, seq_id: str, st: SeqSpecState, k: int) -> List[int]:
        from .cache import OutOfPages

        ctx = st.tokens
        if len(ctx) < 2:
            return []
        if seq_id not in self.synced:
            self.pool.create(seq_id, block_hashing=False)
            self.synced[seq_id] = 0
        try:
            self.pool.ensure_pages(seq_id, len(ctx) + self.sc.k_max)
        except OutOfPages:
            return []   # draft pool pressure: skip speculation this round
        # sync committed tokens (all but the last, which feeds the scan)
        n = self.synced[seq_id]
        while n < len(ctx) - 1:
            count = min(len(ctx) - 1 - n, self.chunk)
            self._sync_chunk(seq_id, ctx, n, count)
            n += count
            # accounted tokens never shrink: num_tokens tracks the sync
            # high-water mark, so re-synced (post-rollback) slots are
            # rewritten in place without re-accounting
            sc = self.pool.seqs[seq_id]
            if n > sc.num_tokens:
                sc.num_tokens = n
        self.synced[seq_id] = n
        # greedy scan from the last committed token
        import jax.numpy as jnp
        S = self._bucket(len(ctx) + self.sc.k_max, self.s_buckets)
        pt = self.pool.page_table_row(seq_id, S // self.page)[None, :]
        fn = self._prop_fn(S)
        toks, self.k_pool, self.v_pool = fn(
            self.params, self.k_pool, self.v_pool,
            jnp.asarray([ctx[-1]], jnp.int32), pt,
            np.asarray([len(ctx)], np.int32))
        # dynalint: ok(host-sync) draft-chain fetch: k drafted tokens in
        # one array per proposal round (the proposer is host-side by design)
        return [int(t) for t in np.asarray(toks)[:k]]

    def _sync_chunk(self, seq_id: str, ctx: List[int], start: int,
                    count: int) -> None:
        import jax.numpy as jnp

        C = self._bucket(count, self.c_buckets)
        S = self._bucket(start + count, self.s_buckets)
        tokens = np.zeros((1, C), np.int32)
        positions = np.zeros((1, C), np.int32)
        write_idx = np.zeros((1, C), np.int32)
        tokens[0, :count] = ctx[start:start + count]
        positions[0, :count] = np.arange(start, start + count)
        write_idx[0, :count] = self.pool.write_slots(seq_id, start, count)
        r_s, r_p, r_v = self.pool.read_slots(seq_id, start + count, S)
        fn = self._sync_fn(C, S)
        _, self.k_pool, self.v_pool = fn(
            self.params, self.k_pool, self.v_pool, tokens, positions,
            write_idx, r_s[None], r_p[None], r_v[None],
            np.asarray([count - 1], np.int32))

    def warmup(self) -> int:
        """Compile every draft sync/propose bucket program on dummy inputs
        (called from ``EngineCore.warmup``): without this, the first
        spec='draft' request to land in a fresh bucket pays a full XLA
        compile mid-serving. All dummy writes target scratch page 0."""
        import jax.numpy as jnp

        n = 0
        for S in sorted(set(self.s_buckets)):
            pt = np.zeros((1, S // self.page), np.int32)
            # argument placement must match propose() exactly (device tok,
            # host tables/lengths): jit cache keys include placement
            _, self.k_pool, self.v_pool = self._prop_fn(S)(
                self.params, self.k_pool, self.v_pool,
                jnp.zeros(1, jnp.int32), pt, np.ones(1, np.int32))
            n += 1
            for C in sorted(set(self.c_buckets)):
                zc = np.zeros((1, C), np.int32)
                _, self.k_pool, self.v_pool = self._sync_fn(C, S)(
                    self.params, self.k_pool, self.v_pool, zc, zc, zc,
                    np.zeros((1, S), np.int32), np.zeros((1, S), np.int32),
                    np.zeros((1, S), bool), np.zeros(1, np.int32))
                n += 1
        return n

    def drop(self, seq_id: str) -> None:
        if seq_id in self.synced:
            self.synced.pop(seq_id, None)
            self.pool.release(seq_id)


def build_proposer(sc: SpecConfig, cfg, s_buckets: List[int],
                   c_buckets: List[int]):
    if sc.mode == "draft":
        return DraftModelProposer(sc, cfg, s_buckets, c_buckets)
    return NgramProposer(sc)
