"""The in-tree JAX engine: continuous batching over a paged KV pool.

Architecture (TPU-first):
- All device work happens in exactly two jitted programs per (bucket) shape:
  ``prefill_mid`` (chunk forward, no LM head) and ``prefill_last``/``decode``
  (forward + sample). Shapes are bucketed so XLA compiles a handful of
  programs once and replays them forever; KV pools are donated so updates are
  in-place in HBM.
- A synchronous :class:`EngineCore` owns all mutable state (slots, page
  tables, sampling vectors) and is driven from one engine thread — the same
  single-owner actor discipline the reference uses for its schedulers.
- :class:`JaxEngine` is the asyncio facade implementing the AsyncEngine
  contract (BackendInput -> stream of EngineOutput).

Reference capability: the role vLLM/TRT-LLM play behind the reference's
adapters (continuous batching, paged KV, streaming detached tokens), per
SURVEY §7 step 3.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import logging
import os
import queue as thread_queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..llm.model_card import ModelDeploymentCard
from ..llm.protocols.common import BackendInput, EngineOutput, FinishReason
from ..models import llama
from ..obs import flightrec as _flightrec
from ..parallel.mesh import AXIS_TP, serving_mesh
from ..runtime.engine import AsyncEngine, Context
from .cache import OutOfPages, PagePool
from .sampling import (STATIC_K, SamplingState, apply_penalties,
                       resume_seed, sample)

log = logging.getLogger("dynamo_tpu.engine")


def _trace_annotation(name: str):
    """Named ``jax.profiler`` scope around a device dispatch (no-op when the
    profiler is unavailable) — lines the XLA timeline up with the host-side
    request spans in captured profiles."""
    try:
        return jax.profiler.TraceAnnotation(name)
    # dynalint: ok(swallowed-exception) profiler unavailable => no-op
    # scope by design; this wraps EVERY device dispatch and must not log
    except Exception:
        return contextlib.nullcontext()


def global_put(host_array, sharding) -> jax.Array:
    """device_put that also works on a multi-process mesh: every process
    contributes only its addressable shards (all processes must call this
    with the same host data)."""
    if all(d.process_index == jax.process_index()
           for d in sharding.device_set):
        # dynalint: ok(flow-accounting) primitive wrapper — callers meter
        # the tree-level flow (cold weight load, swap slab stream)
        return jax.device_put(host_array, sharding)
    return jax.make_array_from_callback(
        host_array.shape, sharding,
        lambda idx: np.asarray(host_array[idx]))


def _buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


@dataclass
class JaxEngineConfig:
    model: llama.LlamaConfig
    tp: int = 1
    sp: int = 1                         # sequence-parallel (ring) axis size
    ep: int = 1                         # expert-parallel axis size (MoE)
    pp: int = 1                         # pipeline-parallel stage count
    page_size: int = 64
    max_batch: int = 8
    max_context: int = 2048
    prefill_chunk: int = 512
    num_pages: Optional[int] = None     # default: max_batch*max_context worth
    decode_steps: int = 8               # decode iterations per XLA dispatch
    prefill_lanes: Optional[int] = None  # sequences per prefill dispatch
    #                                      (None => max_batch: whole wave)
    params_path: Optional[str] = None   # safetensors dir; None => random init
    seed: int = 0
    preset: Optional[str] = None
    # attention backend: "auto" => Pallas kernels on TPU, XLA dense elsewhere.
    # Explicit values: "pallas" | "xla" | "ring" (sequence-parallel prefill
    # over the sp mesh axis; decode stays pallas/xla).
    attn_impl: str = "auto"
    # precompile every (lanes, chunk, context) prefill bucket and every
    # decode context bucket at init — tail latency becomes predictable
    # (the reference engines' startup warmup / CUDA-graph capture role)
    warmup: bool = False
    # KV block manager (SURVEY §2.4): prefix reuse + tiered offload
    enable_prefix_reuse: bool = True
    host_cache_blocks: int = 0          # host-DRAM KV tier capacity (0 = off)
    disk_cache_blocks: int = 0          # mmap spill tier capacity (0 = off)
    disk_cache_path: Optional[str] = None
    # cluster KV sharing (llm/kv_cluster/): mirror every newly sealed
    # block to the host tier write-through, so peers can fetch hot
    # prefixes that never saw device-pool eviction pressure. Requires
    # host_cache_blocks > 0; the worker CLI turns it on with
    # DYN_KV_CLUSTER=1.
    cluster_writethrough: bool = False
    # speculative decoding (engine/spec.py). None => consult the DYN_SPEC*
    # env knobs; "" / "off" force-disables regardless of env. Off by
    # default: zero extra compiled programs, decode path untouched.
    spec: Optional[str] = None          # "ngram" | "draft" | "off"/None
    spec_k: Optional[int] = None        # max drafts/lane (None => DYN_SPEC_K)
    spec_draft: Optional[str] = None    # draft preset/dir (None => env)
    # KV paging (llm/kvpage/): serve contexts beyond max_context with
    # device residency bounded to a page budget — chunked prefill demotes
    # sealed blocks d2h, decode streams the cold tail back through staged
    # uploads. None => consult the DYN_KVPAGE_* env knobs; 0 disables.
    # Requires host_cache_blocks > 0 and composes with neither spec
    # decoding nor pp/sp/multi-host (validated at construction).
    kvpage_budget: Optional[int] = None      # device pages for the lane
    kvpage_seg_pages: Optional[int] = None   # blocks per staging segment
    kvpage_prefetch: Optional[int] = None    # segments prefetched ahead
    kvpage_max_context: Optional[int] = None  # paged context ceiling
    kvpage_batch: Optional[int] = None       # concurrent decode lanes

    @classmethod
    def from_card(cls, card: ModelDeploymentCard, tensor_parallel: int = 1,
                  **extra) -> "JaxEngineConfig":
        if card.model_config:
            mcfg = llama.LlamaConfig.from_hf_config(card.model_config)
        elif extra.get("preset"):
            mcfg = llama.preset(extra["preset"])
        elif card.path and (gpath := _gguf_file(card.path)):
            # GGUF cards carry no HF config dict — the model shape lives in
            # the container metadata; sizing from a preset here would build
            # sampler state (penalty counts) at the wrong vocab width
            from ..llm.gguf import read_gguf
            g = read_gguf(gpath)
            try:
                mcfg = g.llama_config()
            finally:
                g.close()
        else:
            mcfg = llama.preset("tiny-byte")
        kw = dict(
            model=mcfg,
            tp=tensor_parallel,
            page_size=card.kv_block_size,
            params_path=card.path,
        )
        # every config field is overridable from extra args; unknown keys
        # raise instead of being silently dropped (a typo'd or unplumbed
        # key — e.g. page_size once — must not ship a different engine
        # than the config asked for)
        managed = {"model", "params_path"}
        for k, v in extra.items():
            if k == "preset":
                continue
            if k in cls.__dataclass_fields__ and k not in managed:
                kw[k] = v
            else:
                raise ValueError(f"unknown engine arg {k!r}")
        cfg = cls(**kw)
        cfg.max_context = min(cfg.max_context, card.context_length)
        return cfg


@dataclass
class _Slot:
    seq_id: str
    request: BackendInput
    prompt: List[int]
    prefill_done: int = 0           # prompt tokens already in cache
    generated: int = 0
    last_token: int = 0
    cum_logprob: float = 0.0
    cancelled: bool = False
    # physical tokens written after every ENQUEUED decode dispatch executes
    # (runs ahead of `generated`, which advances when results are fetched)
    sched_len: int = 0
    # VLM: per-position image-group ids + projected soft tokens
    # [n_images, mm_tokens, D] (None for text-only requests)
    mm_spans: Optional[np.ndarray] = None
    mm_soft: Optional[np.ndarray] = None


@dataclass
class StepOutput:
    seq_id: str
    token: int
    logprob: float                  # cumulative over the sequence
    finish: Optional[FinishReason] = None
    prompt_tokens: int = 0
    error: Optional[str] = None     # cause when finish == ERROR
    # this token's own logprob (not re-derivable from the cumulative without
    # float cancellation)
    token_logprob: float = 0.0
    # typed-error fields (meaningful only with finish == ERROR): the
    # http-ish status + stage/reason triple the uniform error body exposes,
    # so an engine-side rejection (over-length prompt -> 400) survives to
    # the frontend instead of collapsing into a generic 500
    error_code: int = 500
    error_stage: Optional[str] = None
    error_reason: Optional[str] = None
    # admission's sealed-prefix restore length, set on a sequence's FIRST
    # output only (None elsewhere) — rides to EngineOutput.
    # kv_prefix_hit_tokens
    prefix_hit: Optional[int] = None


class EngineCore:
    """Synchronous continuous-batching core. Single-threaded by contract."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.cfg = cfg
        m = cfg.model
        llama.validate_tp(m, cfg.tp, cfg.ep)
        llama.validate_pp(m, cfg.pp, cfg.tp)
        if cfg.pp > 1 and cfg.sp > 1:
            # ring prefill shards the sequence axis the pp stage loop
            # microbatches — the two prefill schedules don't compose (the
            # reference's vLLM pp has the same envelope); pp x tp x ep all
            # compose (round 5)
            raise ValueError("pp > 1 composes with tp/ep (sp must be 1)")
        self.mesh = serving_mesh(cfg.tp, cfg.sp, cfg.ep, cfg.pp, devices)
        from ..utils.prometheus import stage_metrics

        self.stage = stage_metrics()   # cached: observe() runs per harvest
        self.page_size = cfg.page_size
        # speculative decoding: resolved up front because the page-pad and
        # bucket sizing below must cover the verify program's k+1 positions
        from .spec import resolve_spec
        self.spec = resolve_spec(cfg)
        if self.spec is not None and cfg.pp > 1:
            raise ValueError("speculative decoding does not compose with "
                             "pp > 1 yet (the staged decode path takes no "
                             "multi-position verify inputs)")
        # every sequence may overshoot up to 2*decode_steps speculative
        # tokens (one dispatch in flight plus one chained behind it) — or,
        # under spec decode, k_max drafts + 1 bonus token per verify round
        overshoot = 2 * cfg.decode_steps
        if self.spec is not None:
            overshoot = max(overshoot, self.spec.k_max + 1)
        self._spec_pad = -(-overshoot // cfg.page_size) * cfg.page_size
        # ceil: a seq at max_context with the speculative pad must always fit
        self.max_pages_per_seq = -(-(cfg.max_context + self._spec_pad)
                                   // cfg.page_size)
        num_pages = cfg.num_pages or (cfg.max_batch * self.max_pages_per_seq + 1)
        self.pool = PagePool(num_pages, cfg.page_size)

        # --- params ---------------------------------------------------
        # sharding() drops spec axes the mesh doesn't carry (e.g. the ep
        # axis of MoE expert weights on an ep=1 mesh)
        from ..parallel.mesh import sharding as mk_sharding

        specs = llama.param_specs(m, cfg.tp, cfg.pp)
        shardings = jax.tree.map(
            lambda s: mk_sharding(self.mesh, *s), specs,
            is_leaf=lambda x: isinstance(x, P))
        if cfg.params_path and _has_safetensors(cfg.params_path):
            from .loader import load_llama_params
            self.params = load_llama_params(cfg.params_path, m, shardings)
        elif cfg.params_path and (gguf := _gguf_file(cfg.params_path)):
            from ..llm.gguf import load_llama_params_gguf
            _, self.params = load_llama_params_gguf(
                gguf, cfg=m, shardings=shardings, dtype=m.dtype)
        else:
            params = llama.init_params(m, jax.random.PRNGKey(cfg.seed))
            self.params = jax.tree.map(
                # dynalint: ok(flow-accounting) random-init placement (no
                # checkpoint): init_params already materialized on device,
                # the put is a resharding — checkpoint loads meter in the
                # loader
                lambda a, s: global_put(a, s), params, shardings)

        # --- vision tower (Gemma3 VLM) --------------------------------
        # replicated params (the tower is tiny next to the LM; sharding it
        # would only add collectives to a once-per-request encode)
        self.vision_cfg = None
        if m.vision is not None:
            from ..models import siglip as _siglip

            self.vision_cfg = _siglip.SiglipVisionConfig.from_hf_config(
                m.vision, dtype=m.dtype)
            vt = None
            if cfg.params_path and _has_safetensors(cfg.params_path):
                from .loader import _get, _open_all

                tensors = _open_all(cfg.params_path)
                vnames = [k for k in tensors
                          if "vision_tower" in k
                          or "multi_modal_projector" in k]
                if vnames:
                    strip = ("model." if any(
                        k.startswith("model.vision_tower") for k in vnames)
                        else "")
                    vt = {k[len(strip):]: _get(tensors, k) for k in vnames}
            if vt is not None:
                self.vision_params = _siglip.params_from_hf(
                    vt, self.vision_cfg)
                self.proj_params = _siglip.projector_from_hf(
                    vt, self.vision_cfg)
            elif cfg.params_path and _has_safetensors(cfg.params_path):
                # a real checkpoint WITHOUT vision tensors must not fall
                # back to random tower weights: images would get
                # confidently wrong completions
                raise ValueError(
                    f"model config declares a vision tower but "
                    f"{cfg.params_path} has no vision_tower/"
                    f"multi_modal_projector tensors; serve the text-only "
                    f"config instead")
            else:
                # no checkpoint at all: random init (tests/benching)
                kv1, kv2 = jax.random.split(jax.random.PRNGKey(cfg.seed + 1))
                self.vision_params = _siglip.init_params(self.vision_cfg, kv1)
                self.proj_params = _siglip.init_projector_params(
                    self.vision_cfg, m.hidden_size, kv2)

            def _encode(px):
                feats = _siglip.forward(self.vision_params, self.vision_cfg,
                                        px)
                return _siglip.project(self.proj_params, self.vision_cfg,
                                       feats, m.mm_tokens_per_image)

            # jit caches per image-count; image requests are rare relative
            # to decode steps, so lazy compile is fine
            self._encode_images = jax.jit(_encode)

        # --- attention backend ---------------------------------------
        impl = cfg.attn_impl
        if impl == "auto":
            import os
            impl = os.environ.get("DYNAMO_TPU_ATTN", "auto")
        if m.attn_logit_softcap or m.sliding_window is not None:
            # Gemma2/3: the Pallas flash/paged kernels take softcap +
            # sliding windows natively (round 5); only ring attention
            # still lacks them (cross-shard windows don't compose with
            # the ring schedule)
            if impl == "ring":
                raise ValueError(
                    "attn_impl='ring' does not support softcapping/"
                    "sliding-window models (Gemma2/3); use 'pallas' or "
                    "'xla'")
        if cfg.pp > 1 and impl == "ring":
            # ring rides the sp axis; pp stages the layer stack — the two
            # prefill shardings don't compose
            raise ValueError("attn_impl='ring' is not supported with pp")
        if impl == "auto":
            # Pallas kernels on TPU (shard_map-wrapped per tp shard); XLA
            # dense elsewhere or when the model's GQA grouping can't split
            impl = ("pallas" if jax.default_backend() == "tpu"
                    and llama.pallas_tp_ok(m, cfg.tp) else "xla")
            if impl == "pallas" and not _pallas_probe_ok(m, cfg):
                # auto must never take the engine down: a Mosaic lowering
                # regression (chip generation, shape corner) degrades to the
                # dense XLA path instead of failing every request
                log.warning("pallas kernel probe failed; auto falling back "
                            "to attn_impl='xla'")
                impl = "xla"
        if impl not in ("pallas", "xla", "ring"):
            raise ValueError(
                f"attn_impl must be auto|pallas|xla|ring, got {impl!r}")
        if impl == "pallas" and not llama.pallas_tp_ok(m, cfg.tp):
            raise ValueError(
                f"attn_impl='pallas' needs an integral per-shard GQA group: "
                f"Hq={m.num_heads}/tp={cfg.tp} per shard must divide by the "
                f"per-shard kv heads")
        if impl == "ring" and cfg.sp < 2:
            raise ValueError("attn_impl='ring' needs sp >= 2")
        self.attn_impl = impl
        # decode is single-token — the ring (prefill) axis does not apply;
        # decode attention runs pallas on TPU, dense XLA elsewhere
        if impl == "ring":
            self.decode_attn_impl = ("pallas"
                                     if jax.default_backend() == "tpu"
                                     and llama.pallas_tp_ok(m, cfg.tp)
                                     else "xla")
        else:
            self.decode_attn_impl = impl

        # --- KV pools (head-major: [L, Hkv, n_pages, page, Dh] so that
        # pool[l] is directly the TPU paged-attention kernel layout) ----
        kv_spec = llama.kv_cache_spec(m, cfg.tp, cfg.pp)
        self.kv_sharding = NamedSharding(self.mesh, kv_spec)
        pool_shape = (m.num_layers, m.num_kv_heads, num_pages,
                      cfg.page_size, m.head_dim)
        # jitted zeros with explicit out_sharding: allocates straight into
        # the (possibly multi-process) sharded layout, no host staging
        zeros = jax.jit(lambda: jnp.zeros(pool_shape, m.dtype),
                        out_shardings=self.kv_sharding)
        self.k_pool = zeros()
        self.v_pool = zeros()

        # --- KV block manager: tiered offload + prefix reuse ----------
        from ..llm.kvbm.transfer import CopyStream
        self.copy_stream = CopyStream()
        self.tiered = None
        if cfg.host_cache_blocks > 0:
            from ..llm.kvbm.tiers import (DiskKvTier, HostKvTier,
                                          TieredKvCache)
            blk_shape = (m.num_layers, m.num_kv_heads, cfg.page_size,
                         m.head_dim)
            # ml_dtypes gives numpy a real bfloat16, so the host tier stores
            # KV at device precision
            # dynalint: ok(host-sync) init-time dtype probe of a 0-d
            # scalar, once per engine construction — never on a request
            np_dtype = np.asarray(jnp.zeros((), m.dtype)).dtype
            host = HostKvTier(cfg.host_cache_blocks, blk_shape, np_dtype)
            disk = None
            if cfg.disk_cache_blocks > 0:
                import os
                # default path is per-process: two engines on one host
                # (e.g. prefill + decode workers) must not memmap the same
                # spill files in w+ mode and corrupt each other's blocks
                path = (cfg.disk_cache_path
                        or f"/tmp/dynamo_tpu_kv_spill.{os.getpid()}")
                disk = DiskKvTier(cfg.disk_cache_blocks, blk_shape,
                                  np_dtype, path)
            self.tiered = TieredKvCache(host, disk)
        self._evict_buf: List[Tuple[int, int]] = []
        self.pool.on_block_evicted = self._offload_evicted
        # cluster write-through: newly sealed blocks queue for a host-tier
        # mirror copy. A block SEALS before the dispatch that writes its
        # KV is issued (extend/account run pre-dispatch), so entries
        # ratchet through two step boundaries (pending -> armed -> buf)
        # before the d2h: by then the writing dispatch has been issued and
        # JAX sequences the copy after it by data dependency.
        self._writethrough_buf: List[Tuple[int, int]] = []
        self._writethrough_armed: List[Tuple[int, int]] = []
        self._writethrough_pending: List[Tuple[int, int]] = []
        if self.tiered is not None and cfg.cluster_writethrough:
            self.pool.add_seal_hook(self._writethrough_sealed)

        # prefix-cache accounting (feeds ForwardPassMetrics + disagg router)
        self.last_prefix_hit = 0
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0

        # --- slots / scheduler ---------------------------------------
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_batch
        self.by_seq: Dict[str, _Slot] = {}
        self.waiting: Deque[Tuple[str, BackendInput]] = collections.deque()
        self.sampling = SamplingState.host_init(cfg.max_batch)
        # commit to a canonical replicated sharding: program cache keys
        # include argument shardings, so an uncommitted key would recompile
        # every bucket once more after the first on-device key update
        self._rep_sharding = NamedSharding(self.mesh, P())
        self.sampling.key = jax.jit(
            lambda: jax.random.split(jax.random.key(0), cfg.max_batch),
            out_shardings=self._rep_sharding)()
        # generated-token occurrence counts per lane (frequency/presence
        # penalties): persistent device state threaded through every decode
        # dispatch like the KV pools; lanes reset in-program when a new
        # sequence enters decode (multi-host lockstep holds — the resets
        # ride the mirrored dispatch, never a side op)
        self.gen_counts = jax.jit(
            lambda: jnp.zeros((cfg.max_batch, m.vocab_size), jnp.int32),
            out_shardings=self._rep_sharding)()
        self._decode_seen: Dict[int, str] = {}

        # --- goodput accounting (utils/roofline.py) -------------------
        # analytic FLOPs/bytes per dispatch over measured dispatch wall
        # time, against the platform peak (whole-mesh: per-chip table
        # peaks scale by device count; the calibrated CPU fallback is
        # already host-wide, virtual devices share one memory bus)
        from ..utils import roofline

        dev0 = next(iter(self.mesh.devices.flat))
        peaks = roofline.detect_peaks(dev0.device_kind, dev0.platform)
        if peaks.source.startswith("table"):
            n_dev = int(self.mesh.devices.size)
            peaks = roofline.Peaks(peaks.flops * n_dev,
                                   peaks.hbm_bytes * n_dev, peaks.source)
        weight_bytes = float(sum(
            int(a.size) * np.dtype(a.dtype).itemsize
            for a in jax.tree.leaves(self.params)))
        self.costs = roofline.model_costs(m, weight_bytes=weight_bytes)
        self.goodput = roofline.GoodputMeter(self.costs, peaks)
        # set by the compile-instrumentation wrapper when a dispatch's
        # first call just XLA-compiled: that dispatch's wall time is
        # compile, not compute, and must not poison the MFU window
        self._just_compiled = False

        # --- compiled programs ---------------------------------------
        # decode reads are indexed through page tables of width S/page_size:
        # every S bucket MUST be a page multiple or the final partial page
        # would clamp out of bounds and silently read/write the wrong page
        pg = cfg.page_size
        raw = _buckets(min(256, cfg.max_context), cfg.max_context + self._spec_pad)
        self.s_buckets = sorted({-(-b // pg) * pg for b in raw})
        self.c_buckets = _buckets(min(32, cfg.prefill_chunk), cfg.prefill_chunk)
        # prefill lane budget: the whole admission wave prefills in one
        # dispatch by default — splitting a 32-request wave into 8-lane
        # dispatches quadruples the per-dispatch host round-trips, which
        # dominate TTFT when the host link is slow
        lanes = cfg.prefill_lanes or cfg.max_batch
        self.b_buckets = _buckets(1, max(1, min(lanes, cfg.max_batch)))
        self._decode_fns: Dict[int, Any] = {}
        self._prefill_batch_fns: Dict[Tuple[int, int, int], Any] = {}
        # verify programs, keyed (S, K): compiled lazily, and ONLY when spec
        # decoding is enabled — spec off costs zero extra programs
        self._verify_fns: Dict[Tuple[int, int], Any] = {}
        self.proposer = None
        self._spec_states: Dict[str, Any] = {}
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_dispatch_total = 0
        if self.spec is not None:
            from .spec import build_proposer
            self.proposer = build_proposer(self.spec, cfg, self.s_buckets,
                                           self.c_buckets)

        # --- in-flight decode dispatches (device-chained) -------------
        # Each record is a dispatch whose results have not been fetched yet.
        # Chaining feeds the previous dispatch's on-device token/key arrays
        # straight into the next one, so the host fetch (one full tunnel
        # round-trip) overlaps device execution instead of gating it.
        self._inflight: Deque[Dict[str, Any]] = collections.deque()
        self._deferred_release: List[str] = []
        self._pending_seeds: List[Tuple[int, int]] = []
        # seq_id -> admission's prefix-restore length, consumed by step()'s
        # tagging post-pass on the sequence's first output
        self._pending_prefix_hit: Dict[str, int] = {}
        # --- layer-streamed KV injection (disagg receive path) --------
        # seq_id -> in-flight stream-inject state: pool pages are leased
        # at begin (unsealed, unregistered — invisible to attention and
        # prefix matching), per-layer scatters enqueue as layers arrive,
        # and only finish seals/publishes the blocks. Abort releases the
        # pages untouched-by-anyone: a torn stream can never leave a
        # half-written block reachable.
        self._stream_injects: Dict[str, Dict[str, Any]] = {}
        # --- placement-driven h2d prefetch staging --------------------
        # seq_hash -> (k_dev, v_dev) device blocks uploaded by
        # stage_prefetch (asyncio thread) while the request queues at the
        # slot gate; admission's restore consumes them with a d2d scatter
        # instead of a critical-path h2d. Bounded FIFO (insertion-ordered
        # dict), guarded by _h2d_stage_lock (two-thread access).
        self._h2d_stage: Dict[int, Tuple[Any, Any]] = {}
        self._h2d_stage_lock = threading.Lock()
        # hashes a prefetch was REQUESTED for: admission counts a host
        # upload on one of these as a prefetch stall (vs a plain miss)
        self._h2d_requested: set = set()
        self._last_final_tok = None   # device [B] from the last decode
        # multi-host lockstep: called with (kind, meta, arrays) right before
        # every device dispatch so follower processes can replay it
        self.dispatch_hook: Optional[Any] = None

        # --- KV paging lane (llm/kvpage/) -----------------------------
        # long-context requests the pool/max_context would reject are
        # served with bounded device residency: chunked prefill demotes
        # sealed blocks to the host tier, decode streams them back per
        # layer through staged uploads (docs/long_context.md)
        self.kvpager = None
        from ..llm.kvpage.runner import PagedConfig
        pcfg = PagedConfig.resolve(cfg)
        if pcfg is not None:
            from ..llm.kvpage.programs import PagedPrograms
            from ..llm.kvpage.runner import PagedEngine
            why = PagedPrograms.validate(cfg)
            if why is not None:
                raise ValueError(f"KV paging does not support {why}")
            if self.tiered is None:
                raise ValueError("KV paging needs a host tier to demote "
                                 "into (set host_cache_blocks > 0)")
            if self.spec is not None:
                raise ValueError("KV paging does not compose with "
                                 "speculative decoding")
            self.kvpager = PagedEngine(self, pcfg)

        if cfg.warmup:
            self.warmup()

    # ------------------------------------------------------------------
    def warmup(self) -> None:
        """Compile every bucket program up front on dummy inputs.

        Without this, the first request that lands in a fresh (lanes,
        chunk, context) bucket pays a full XLA compile mid-serving — a
        multi-second TTFT outlier on CPU, tens of seconds on TPU. All
        dummy writes go to scratch page 0 (what padded lanes use), so
        engine state is untouched. Runs identically on multi-host leader
        and followers (same ctor, same dummy data — lockstep holds).
        """
        cfg = self.cfg
        t0 = time.monotonic()
        n = 0
        s = self.sampling
        B = cfg.max_batch
        # argument TYPES must match serving exactly (host numpy for tables/
        # lengths/sampling vectors, device arrays for keys/chained tokens):
        # jit cache keys include arg placement, so a device-array warmup
        # would compile a different program than the serving dispatch uses
        zb = np.zeros(B, np.int32)
        zf = np.zeros(B, np.float32)
        ones = np.ones(B, np.int32)
        fresh = np.zeros(B, bool)
        act = np.zeros(B, bool)
        for S in self.s_buckets:
            fn = self._decode_fn(S)
            pt = np.zeros((B, S // self.page_size), np.int32)
            # non-chained (host tokens) ...
            (_, final_tok, key2, self.k_pool, self.v_pool,
             self.gen_counts) = fn(
                self.params, zb, self.k_pool, self.v_pool, pt, ones,
                s.temperature, s.top_p, s.top_k, s.key,
                self.gen_counts, fresh, act, s.freq_pen, s.pres_pen)
            # ... and chained (previous dispatch's on-device tokens/key)
            (_, _, _, self.k_pool, self.v_pool, self.gen_counts) = fn(
                self.params, final_tok, self.k_pool, self.v_pool, pt, ones,
                s.temperature, s.top_p, s.top_k, key2,
                self.gen_counts, fresh, act, s.freq_pen, s.pres_pen)
            n += 2
            if self.spec is not None:
                # spec enabled: also pre-compile every (S, K-bucket) verify
                # program (spec off compiles zero of these)
                U = self.spec.k_max + 1
                for K in self.spec.k_buckets:
                    vfn = self._verify_fn(S, K)
                    (_, _, self.k_pool, self.v_pool, self.gen_counts) = vfn(
                        self.params, np.zeros((B, K + 1), np.int32),
                        self.k_pool, self.v_pool, pt, ones,
                        s.temperature, s.top_p, s.top_k, s.key,
                        self.gen_counts, fresh, act, s.freq_pen, s.pres_pen,
                        np.zeros((B, U), np.int32), np.zeros((B, U), bool))
                    n += 1
        for Bp in self.b_buckets:
            for C in self.c_buckets:
                for S in self.s_buckets:
                    fn = self._prefill_fn(Bp, C, S)
                    zt = np.zeros((Bp, C), np.int32)
                    keys = s.key[jnp.asarray(np.zeros(Bp, np.int32))]
                    _, _, _, self.k_pool, self.v_pool = fn(
                        self.params, zt, zt, self.k_pool, self.v_pool,
                        zt, np.zeros((Bp, S), np.int32),
                        np.zeros((Bp, S), np.int32),
                        np.zeros((Bp, S), bool),
                        np.zeros(Bp, np.int32), np.zeros(Bp, np.float32),
                        np.ones(Bp, np.float32), np.zeros(Bp, np.int32),
                        keys)
                    n += 1
        if self.proposer is not None:
            n += self.proposer.warmup()   # draft model's own bucket set
        # dynalint: ok(host-sync) warmup barrier: block ONCE at startup so
        # every bucket compile lands before serving, not on a request
        jax.block_until_ready(self.k_pool)
        # warmup's own compiles are counted; the first SERVING dispatch
        # must not be skipped by the goodput meter on their account
        self._just_compiled = False
        log.info("warmup compiled %d bucket programs in %.1fs",
                 n, time.monotonic() - t0)

    # ------------------------------------------------------------------
    # compiled program builders
    # ------------------------------------------------------------------
    def _record_compile(self, kind: str, seconds: float) -> None:
        """A fresh bucket program's first call just traced+XLA-compiled:
        count it (compile plane) and flag the enclosing dispatch so the
        goodput meter skips its wall time."""
        from ..utils.roofline import record_compile

        record_compile(kind, seconds)
        self._just_compiled = True

    def _take_compiled_flag(self) -> bool:
        flag = self._just_compiled
        self._just_compiled = False
        return flag

    def _decode_fn(self, S: int):
        """Multi-step decode: N autoregressive iterations inside one jitted
        lax.scan — indices computed on device from page tables, sampled token
        fed straight back in. Lanes that hit a finish condition mid-scan
        overshoot harmlessly into their own pre-allocated pages; the host
        trims afterwards.

        Returns (packed [N, B, 2] f32 (token, logprob) — ONE host fetch per
        dispatch — plus the final token [B] i32, key, pools, all of which
        stay on device so the next dispatch can chain off them without a
        host round-trip)."""
        if S not in self._decode_fns:
            cfg = self.cfg
            N = cfg.decode_steps
            impl = self.decode_attn_impl
            mesh = self.mesh
            rep, kv = self._rep_sharding, self.kv_sharding

            # out_shardings pinned so the pools keep the canonical kv
            # sharding across programs: without this, XLA may emit an
            # equivalent-but-differently-spec'd sharding and every *other*
            # bucket program compiles a second variant against it
            B = self.cfg.max_batch

            @partial(jax.jit, donate_argnums=(2, 3, 10),
                     out_shardings=(rep, rep, rep, kv, kv, rep))
            def step(params, tokens, k_pool, v_pool, page_tables, lengths,
                     temp, top_p, top_k, key, counts, fresh, active,
                     freq_pen, pres_pen):
                # lanes whose sequence just entered decode restart their
                # generated-token counts at one-hot(first generated token);
                # chained dispatches pass fresh all-False
                lane = jnp.arange(B)
                counts = jnp.where(
                    fresh[:, None],
                    jnp.zeros_like(counts).at[lane, tokens].add(1),
                    counts)
                act = active.astype(jnp.int32)

                def one(carry, _):
                    tokens, lengths, k_pool, v_pool, key, counts = carry
                    if cfg.pp > 1:
                        # in-stage kernels: flash per pp×tp shard (the
                        # paged kernel would need page tables threaded
                        # into the stage loop — flash covers T=1 decode)
                        logits, k_pool, v_pool = llama.forward_decode_pp(
                            params, cfg.model, tokens, k_pool, v_pool,
                            page_tables, lengths, mesh=mesh,
                            attn_impl=("flash" if impl == "pallas"
                                       else "xla"))
                    else:
                        logits, k_pool, v_pool = llama.forward_decode(
                            params, cfg.model, tokens, k_pool, v_pool,
                            page_tables, lengths, attn_impl=impl, mesh=mesh)
                    lg = apply_penalties(logits[:, 0], counts, freq_pen,
                                         pres_pen)
                    tok, logp, new_key = sample(lg, temp, top_p, top_k, key)
                    # only lanes ACTIVE in this dispatch count their sample:
                    # a deferred (pool-pressure) lane's garbage tokens must
                    # not poison its penalties when it resumes
                    counts = counts.at[lane, tok].add(act)
                    return ((tok, lengths + 1, k_pool, v_pool, new_key,
                             counts), (tok, logp))

                carry = (tokens, lengths, k_pool, v_pool, key, counts)
                (tok, lengths, k_pool, v_pool, key, counts), (toks, logps) \
                    = jax.lax.scan(one, carry, None, length=N)
                # token ids < 2^24 are exact in f32, so one packed array
                # (one host fetch) carries both streams losslessly
                packed = jnp.stack([toks.astype(jnp.float32), logps], -1)
                return packed, tok, key, k_pool, v_pool, counts

            from ..utils.roofline import instrument_compile
            self._decode_fns[S] = instrument_compile(
                "decode", step, self._record_compile)
        return self._decode_fns[S]

    def _prefill_fn(self, Bp: int, C: int, S: int, mm: bool = False):
        """Batched prefill: Bp sequence chunks advance in ONE dispatch (the
        whole admission wave prefills together instead of one dispatch — and
        one host round-trip — per sequence). Every lane computes the LM head
        only at its own last chunk position (``logits_idx``) and samples; the
        host keeps results only for lanes whose prompt completed. Padded
        lanes write to scratch page 0 with nothing valid to read."""
        if (Bp, C, S, mm) not in self._prefill_batch_fns:
            cfg = self.cfg
            impl = {"pallas": "flash", "ring": "ring"}.get(
                self.attn_impl, "xla")
            mesh = self.mesh
            rep, kv = self._rep_sharding, self.kv_sharding

            # pp microbatching: shared rule with forward_decode_pp
            M = llama.pp_microbatches(Bp, cfg.pp)

            @partial(jax.jit, donate_argnums=(3, 4),
                     out_shardings=(rep, rep, rep, kv, kv))
            def fn(params, tokens, positions, k_pool, v_pool, write_idx,
                   read_idx, read_pos, read_valid, last_i, temp, top_p,
                   top_k, keys, ov_vals=None, ov_mask=None, q_span=None,
                   read_span=None):
                if cfg.pp > 1:
                    def mb(a):
                        return a.reshape(M, Bp // M, *a.shape[1:])
                    logits, k_pool, v_pool = llama.forward_pp(
                        params, cfg.model, mb(tokens), mb(positions),
                        k_pool, v_pool, mb(write_idx), mb(read_idx),
                        mb(read_pos), mb(read_valid), mesh,
                        logits_idx=mb(last_i),
                        attn_impl=("flash" if impl == "flash" else "xla"))
                    logits = logits.reshape(Bp, 1, -1)
                else:
                    # image waves run the xla attention path: the span
                    # or-mask has no Pallas kernel input (text waves keep
                    # the fast path — mm programs compile separately)
                    logits, k_pool, v_pool = llama.forward(
                        params, cfg.model, tokens, positions, k_pool, v_pool,
                        write_idx, read_idx, read_pos, read_valid,
                        attn_impl="xla" if mm else impl, mesh=mesh,
                        logits_idx=last_i,
                        embed_override=((ov_vals, ov_mask) if mm else None),
                        attn_spans=((q_span, read_span) if mm else None))
                tok, logp, new_keys = sample(
                    logits[:, 0], temp, top_p, top_k, keys)
                packed = jnp.stack([tok.astype(jnp.float32), logp], -1)
                return packed, tok, new_keys, k_pool, v_pool

            from ..utils.roofline import instrument_compile
            self._prefill_batch_fns[(Bp, C, S, mm)] = instrument_compile(
                "prefill", fn, self._record_compile)
        return self._prefill_batch_fns[(Bp, C, S, mm)]

    def _verify_fn(self, S: int, K: int):
        """Speculative-decoding verify program: ONE forward over K+1
        positions per lane against the paged pool (the prefill machinery —
        device-computed write/read indices off the page tables — at decode
        membership), then in-program verify sampling. Column 0 of
        ``tokens`` is each lane's last committed token (whose KV this
        dispatch writes, exactly like single-token decode); columns 1..K
        are draft tokens. The host accepts/rejects afterwards; rejected
        tokens are never accounted, so their stale KV slots are overwritten
        by the next dispatch (the standard decode write-then-read
        contract). ``upd_tok``/``upd_mask`` fold the PREVIOUS round's
        committed tokens into the penalty counts; ``fresh`` lanes restart
        their counts first (same mechanic as the decode scan)."""
        if (S, K) not in self._verify_fns:
            from .sampling import spec_verify

            cfg = self.cfg
            impl = "flash" if self.decode_attn_impl == "pallas" else "xla"
            mesh = self.mesh
            rep, kv = self._rep_sharding, self.kv_sharding
            B = cfg.max_batch
            T = K + 1
            page = self.page_size

            # upd_tok/upd_mask width is k_max+1 (the most one round can
            # commit), NOT T: a lane can emit more tokens under a wide
            # bucket than the next round's narrower bucket could carry
            @partial(jax.jit, donate_argnums=(2, 3, 10),
                     out_shardings=(rep, rep, kv, kv, rep))
            def fn(params, tokens, k_pool, v_pool, page_tables, lengths,
                   temp, top_p, top_k, key, counts, fresh, active,
                   freq_pen, pres_pen, upd_tok, upd_mask):
                lane = jnp.arange(B)
                counts = jnp.where(fresh[:, None],
                                   jnp.zeros_like(counts), counts)
                counts = counts.at[lane[:, None], upd_tok].add(
                    (upd_mask & active[:, None]).astype(jnp.int32))
                pos = (lengths - 1)[:, None] + jnp.arange(T)[None, :]
                write_idx = (jnp.take_along_axis(page_tables, pos // page,
                                                 axis=1) * page + pos % page)
                t = jnp.arange(S, dtype=jnp.int32)
                rp = jnp.take_along_axis(
                    page_tables,
                    jnp.broadcast_to((t // page)[None], (B, S)), axis=1)
                read_idx = rp * page + (t % page)[None]
                read_pos = jnp.broadcast_to(t[None], (B, S))
                # causality (read_pos <= position) masks the not-yet-written
                # tail per query; validity only needs the max coverage
                read_valid = t[None] < (lengths[:, None] + K)
                logits, k_pool, v_pool = llama.forward(
                    params, cfg.model, tokens, pos, k_pool, v_pool,
                    write_idx, read_idx, read_pos, read_valid,
                    attn_impl=impl, mesh=mesh)          # [B, T, V]
                cf = counts.astype(jnp.float32)[:, None, :]
                lg = (logits - freq_pen[:, None, None] * cf
                      - pres_pen[:, None, None]
                      * (cf > 0).astype(jnp.float32))
                packed, new_key = spec_verify(lg, tokens[:, 1:], temp,
                                              top_p, top_k, key)
                return packed, new_key, k_pool, v_pool, counts

            from ..utils.roofline import instrument_compile
            self._verify_fns[(S, K)] = instrument_compile(
                "verify", fn, self._record_compile)
        return self._verify_fns[(S, K)]

    @staticmethod
    def _bucket(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    # ------------------------------------------------------------------
    # public API (engine thread)
    # ------------------------------------------------------------------
    def close(self) -> None:
        """Release host-side cache resources (the disk tier's spill
        memmaps + files, the pager's prefetch thread). Idempotent; called
        from JaxEngine.shutdown."""
        if self.kvpager is not None:
            self.kvpager.close()
        if self.tiered is not None:
            self.tiered.close()

    def submit(self, seq_id: str, request: BackendInput) -> None:
        self.waiting.append((seq_id, request))

    def cancel(self, seq_id: str) -> None:
        slot = self.by_seq.get(seq_id)
        if slot is not None:
            slot.cancelled = True
        else:
            self.waiting = collections.deque(
                (s, r) for s, r in self.waiting if s != seq_id)
            if self.kvpager is not None:
                self.kvpager.cancel(seq_id)
            if seq_id in self._stream_injects:
                # mid-stream cancel: release the half-written pages
                self.abort_stream_inject(seq_id)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.by_seq or self._inflight
                    or (self.kvpager is not None
                        and self.kvpager.has_work))

    @property
    def active(self) -> int:
        return len(self.by_seq)

    def utilization(self) -> Dict[str, float]:
        total = self.pool.num_pages - 1
        hit_rate = (self.prefix_hit_tokens / self.prefix_query_tokens
                    if self.prefix_query_tokens else 0.0)
        goodput = self.goodput.snapshot()
        # byte-honest residency: device pool bytes in use plus the paged
        # lane's pinned host working set, against device + host-tier
        # capacity — the router's bytes-pressure scoring input (a 128k
        # request shows up here at its true size, not as one slot)
        blk_bytes = float(llama.kv_block_bytes(self.cfg.model,
                                               self.cfg.page_size))
        resident = float(total - self.pool.free_pages) * blk_bytes
        capacity = float(total) * blk_bytes
        if self.tiered is not None:
            capacity += float(self.tiered.host.num_blocks) * blk_bytes
        if self.kvpager is not None:
            # the lane's device pages are already counted in-pool; its
            # pinned host working set is the part slots cannot see
            resident += self.kvpager.resident_bytes()[1]
        return {
            "request_active_slots": float(self.active),
            "request_total_slots": float(self.cfg.max_batch),
            "kv_active_blocks": float(total - self.pool.free_pages),
            "kv_total_blocks": float(total),
            "num_requests_waiting": float(len(self.waiting)),
            "gpu_prefix_cache_hit_rate": hit_rate,
            # speculative decoding: drafted-token acceptance rate (0 when
            # spec is off or nothing proposed yet) — surfaced through
            # ForwardPassMetrics so the planner/router/tracectl can see it
            "spec_accept_rate": (
                self.spec_accepted_total / self.spec_proposed_total
                if self.spec_proposed_total else 0.0),
            # goodput plane: windowed device-efficiency rates (0 when the
            # engine has been idle for the whole window)
            "mfu": goodput["mfu"],
            "mbu": goodput["mbu"],
            "hbm_gbps": goodput["hbm_gbps"],
            "kv_resident_bytes": resident,
            "kv_capacity_bytes": capacity,
        }

    # ------------------------------------------------------------------
    # KV export/import (disaggregated prefill -> decode transfer)
    # ------------------------------------------------------------------
    def extract_kv(self, seq_id: str, layer: Optional[int] = None,
                   count: Optional[int] = None):
        """Gather a sequence's KV out of the pool -> host numpy arrays.
        With ``layer`` set, returns that layer only ([T,Hkv,Dh] k, v) for
        layer-pipelined transfer; otherwise all layers ([L,T,Hkv,Dh]).
        ``count`` limits extraction to the first N tokens (e.g. the prompt)."""
        sc = self.pool.seqs[seq_id]
        n = sc.num_tokens if count is None else min(count, sc.num_tokens)
        slots = jnp.asarray(self.pool.write_slots(seq_id, 0, n))
        if layer is None:
            # dynalint: ok(host-sync) the KV export IS the transfer: disagg
            # prefill->decode ships blocks host-staged, once per sequence
            k = np.asarray(self._kv_gather(self.k_pool, slots))
            # dynalint: ok(host-sync) second half of the same export
            v = np.asarray(self._kv_gather(self.v_pool, slots))
        else:
            # dynalint: ok(host-sync) layer-pipelined variant of the same
            # once-per-sequence disagg KV export
            k = np.asarray(self._kv_gather_layer(self.k_pool, slots, layer))
            # dynalint: ok(host-sync) second half of the same export
            v = np.asarray(self._kv_gather_layer(self.v_pool, slots, layer))
        return k, v

    def _kv_gather(self, pool, slots):
        # pool [L, Hkv, n_pages, page, Dh], flat slots [n] -> [L, n, Hkv, Dh]
        # (adjacent advanced indices stay in place: [L, Hkv, n, Dh])
        if not hasattr(self, "_gather_fn"):
            pg = self.page_size
            self._gather_fn = jax.jit(
                lambda p, s: jnp.transpose(p[:, :, s // pg, s % pg],
                                           (0, 2, 1, 3)))
        return self._gather_fn(pool, slots)

    def _kv_gather_layer(self, pool, slots, layer: int):
        if not hasattr(self, "_gather_layer_fn"):
            pg = self.page_size
            self._gather_layer_fn = jax.jit(
                lambda p, s, l: jnp.transpose(p[l][:, s // pg, s % pg],
                                              (1, 0, 2)), static_argnums=2)
        return self._gather_layer_fn(pool, slots, layer)

    def prefill_extract(self, seq_id: str, request: BackendInput
                        ) -> Tuple[np.ndarray, np.ndarray, int, float]:
        """Prefill-worker path: run the full (chunked) prefill for a request,
        sample its first token, gather the prompt KV to host, release the
        slot. Returns (k [L,T,Hkv,Dh], v, first_token, first_logprob).
        The caller owns queue/transfer; this runs on the engine thread."""
        from dataclasses import replace

        prompt = list(request.token_ids)
        if len(prompt) + 1 >= self.cfg.max_context:
            # typed 400 (not a bare ValueError): the disagg frontend's
            # error body names the configured limit and the stage that
            # rejected, end to end over the wire
            from ..runtime.engine import EngineError
            raise EngineError(
                f"prompt of {len(prompt)} tokens exceeds the configured "
                f"max_context of {self.cfg.max_context}", 400,
                stage="prefill", reason="context_exceeded")
        if request.images:
            raise ValueError("disaggregated prefill does not take image "
                             "requests yet; serve VLM prompts aggregated")
        if None not in self.slots:
            raise RuntimeError("no free slot for prefill job")
        # the first sampled token must never finish the slot (we need the KV
        # before release) — neutralize stop conditions for the prefill pass
        req = replace(request, stop=replace(
            request.stop, max_tokens=None, stop_token_ids=[],
            min_tokens=None, ignore_eos=True))
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, req, prompt)
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self.pool.create(seq_id, lora_id=getattr(req, "lora_id", 0))
        self._load_sampling(slot_idx, req)
        out: List[StepOutput] = []
        try:
            while slot.prefill_done < len(prompt):
                self._prefill_dispatch([(slot_idx, slot)], out)
                if out and out[-1].finish == FinishReason.ERROR:
                    raise OutOfPages("prefill ran out of KV pages")
            so = out[-1]
            k, v = self.extract_kv(seq_id, count=len(prompt))
        finally:
            self._free_slot(slot_idx)
        return k, v, so.token, so.logprob

    def inject_prefilled(self, seq_id: str, request: BackendInput,
                         k: np.ndarray, v: np.ndarray,
                         first_token: int,
                         first_logprob: float = 0.0) -> StepOutput:
        """Receive a remotely-prefilled sequence: write its prompt KV into
        this pool and enter it straight into decode (prefill_done=len).
        ``k``/``v``: [L, T, Hkv, Dh] for the prompt tokens."""
        if None not in self.slots:
            raise RuntimeError("no free slot for injected sequence")
        prompt = list(request.token_ids)
        T = k.shape[1]
        if T != len(prompt):
            raise ValueError(f"KV covers {T} tokens, prompt is {len(prompt)}")
        self.pool.create(seq_id, lora_id=getattr(request, "lora_id", 0))
        self.pool.extend(seq_id, prompt)
        self._flush_evictions()
        slots = jnp.asarray(self.pool.write_slots(seq_id, 0, T))
        if not hasattr(self, "_scatter_fn"):
            pg = self.page_size
            # vals [L, T, Hkv, Dh] -> pool indexed shape [L, Hkv, T, Dh]
            self._scatter_fn = jax.jit(
                lambda p, s, vals: p.at[:, :, s // pg, s % pg].set(
                    jnp.transpose(vals, (0, 2, 1, 3))), donate_argnums=0)
        self.k_pool = self._scatter_fn(self.k_pool, slots,
                                       k.astype(self.cfg.model.dtype))
        self.v_pool = self._scatter_fn(self.v_pool, slots,
                                       v.astype(self.cfg.model.dtype))
        return self._enter_injected(seq_id, request, prompt, first_token,
                                    first_logprob)

    def _enter_injected(self, seq_id: str, request: BackendInput,
                        prompt: List[int], first_token: int,
                        first_logprob: float) -> StepOutput:
        """Shared tail of the two KV-import paths (bulk inject / layer
        stream): claim a slot straight into decode, seed bookkeeping, and
        emit the prefill-worker-sampled first token."""
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, request, prompt, prefill_done=len(prompt))
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self._load_sampling(slot_idx, request)
        self._apply_pending_seeds()
        if request.sampling.seed is not None:
            # the prefill worker consumed one key step sampling the first
            # token; advance the freshly-seeded key the same way so token 2
            # onward matches a local prefill of the same seeded request
            s = self.sampling
            s.key = s.key.at[slot_idx].set(
                jax.random.split(s.key[slot_idx], 2)[0])
        self._append_generated(slot, int(first_token))
        slot.cum_logprob = float(first_logprob)
        fin = self._finish_reason(slot, int(first_token))
        so = StepOutput(seq_id, int(first_token), slot.cum_logprob, fin,
                        prompt_tokens=len(prompt),
                        token_logprob=float(first_logprob))
        if fin is not None:
            self._free_slot(slot_idx)
        return so

    # ------------------------------------------------------------------
    # layer-streamed KV injection (disagg receive; engine thread)
    # ------------------------------------------------------------------
    def begin_stream_inject(self, seq_id: str,
                            request: BackendInput) -> None:
        """Lease pool pages for a remotely-prefilled prompt whose KV is
        still on the wire. The pages stay UNSEALED (no hash registration,
        no stored events, no write-through) until :meth:`
        finish_stream_inject` — a torn stream releases them with nothing
        ever having referenced them."""
        prompt = list(request.token_ids)
        if None not in self.slots:
            raise RuntimeError("no free slot for streamed sequence")
        self.pool.create(seq_id, lora_id=getattr(request, "lora_id", 0))
        try:
            self.pool.ensure_pages(seq_id, len(prompt))
        except Exception:
            self.pool.release(seq_id)
            raise
        # leasing may have evicted reusable pages: their offload d2h must
        # be enqueued before our scatters overwrite them
        self._flush_evictions()
        slots = jnp.asarray(self.pool.write_slots(seq_id, 0, len(prompt)))
        if not hasattr(self, "_stream_scatter_fns"):
            # grouped per-arrival scatter, keyed by group size G:
            # [G] layer ids + [G, T, Hkv, Dh] values land in one donated
            # dispatch (ls[:,None] broadcasts with the [T] slot indices
            # to a [G, T] advanced subspace, placed leading — the wire
            # layout lands without a host-side transpose). Grouping
            # bounds the per-transfer dispatch count: one jit call per
            # arriving layer would spend more host time on dispatch
            # overhead than the scatters it hides.
            self._stream_scatter_fns: Dict[int, Any] = {}
        self._stream_injects[seq_id] = {
            "request": request, "prompt": prompt, "slots": slots,
            "layers_done": 0, "buf": [], "buf_l0": 0,
            # flush granularity: ~4 scatter dispatches per pool per
            # transfer, never coarser than half the model
            "group": max(1, min(4, self.cfg.model.num_layers)),
        }

    def _stream_scatter(self, G: int):
        fn = self._stream_scatter_fns.get(G)
        if fn is None:
            pg = self.page_size
            fn = jax.jit(
                lambda p, ls, s, vals: p.at[
                    ls[:, None], :, s // pg, s % pg].set(vals),
                donate_argnums=0)
            self._stream_scatter_fns[G] = fn
        return fn

    def _flush_stream_buf(self, st) -> None:
        buf = st["buf"]
        if not buf:
            return
        dt = self.cfg.model.dtype
        G = len(buf)
        fn = self._stream_scatter(G)
        ls = jnp.arange(st["buf_l0"], st["buf_l0"] + G)
        k_vals = jnp.asarray(np.stack([b[0] for b in buf]), dt)
        v_vals = jnp.asarray(np.stack([b[1] for b in buf]), dt)
        self.k_pool = fn(self.k_pool, ls, st["slots"], k_vals)
        self.v_pool = fn(self.v_pool, ls, st["slots"], v_vals)
        st["buf_l0"] += G
        st["buf"] = []

    def stream_inject_layer(self, seq_id: str, layer: int,
                            k: np.ndarray, v: np.ndarray) -> None:
        """Accept ONE arriving layer ([T,Hkv,Dh] each) and enqueue its
        group's device scatter while later layers are still in flight.
        Donated, async: the engine keeps dispatching other sequences'
        work in between."""
        st = self._stream_injects[seq_id]
        st["buf"].append((k, v))
        st["layers_done"] = layer + 1
        if len(st["buf"]) >= st["group"]:
            self._flush_stream_buf(st)

    def finish_stream_inject(self, seq_id: str, first_token: int,
                             first_logprob: float) -> StepOutput:
        """All scatters enqueued: seal+register the blocks (stored events
        and write-through fire only now, for fully-arrived KV) and enter
        the sequence straight into decode."""
        st = self._stream_injects.pop(seq_id)
        prompt = st["prompt"]
        if st["layers_done"] != self.cfg.model.num_layers:
            self.pool.release(seq_id)
            raise ValueError(
                f"stream inject for {seq_id} finished at layer "
                f"{st['layers_done']}/{self.cfg.model.num_layers}")
        if None not in self.slots:
            self.pool.release(seq_id)
            raise RuntimeError("no free slot for streamed sequence")
        self._flush_stream_buf(st)         # tail group (< group layers)
        self.pool.account_tokens(seq_id, prompt)
        return self._enter_injected(seq_id, st["request"], prompt,
                                    first_token, first_logprob)

    def abort_stream_inject(self, seq_id: str) -> None:
        """Torn stream: drop the ingest state and release the leased
        pages. They were never sealed/registered, so nothing — attention,
        prefix match, write-through, peers — can have observed the
        partial writes; the pages return to the free list."""
        if self._stream_injects.pop(seq_id, None) is not None:
            self.pool.release(seq_id)

    # ------------------------------------------------------------------
    def step(self) -> List[StepOutput]:
        """One engine iteration (see :meth:`_step`), plus the prefix-hit
        tagging post-pass: a sequence's FIRST output carries admission's
        sealed-prefix restore length (``StepOutput.prefix_hit``), the
        client-observable proof of the KV re-attach path on resumes."""
        out = self._step()
        if self._pending_prefix_hit:
            for so in out:
                hit = self._pending_prefix_hit.pop(so.seq_id, None)
                if hit is not None:
                    so.prefix_hit = hit
        return out

    def _step(self) -> List[StepOutput]:
        """Run one engine iteration.

        Steady-state decode is PIPELINED: a dispatch's sampled tokens are
        fetched one iteration later, while the next dispatch (chained off
        the previous one's on-device token/key arrays) already executes.
        The host fetch round-trip therefore overlaps device compute instead
        of serializing with it. Membership changes (admission, prefill,
        cancel, finish) are sync points: the in-flight window drains first.

        Prefill advances every mid-prefill sequence and admits as many
        waiting requests as fit, batched into ONE dispatch (up to 8 lanes);
        fresh first tokens are flushed to callers immediately rather than
        held through a decode dispatch (TTFT)."""
        out: List[StepOutput] = []
        self._advance_writethrough()
        out.extend(self._reap_cancelled())
        n_reaped = len(out)     # paged outputs below don't change slots
        if self.kvpager is not None and self.kvpager.has_work:
            # one unit of paged long-context work (a prefill chunk or a
            # decode token) interleaves with every normal engine step
            out.extend(self.kvpager.advance())

        prefill_work = any(s is not None and s.prefill_done < len(s.prompt)
                           for s in self.slots)
        admit_possible = bool(self.waiting) and None in self.slots
        sync_needed = prefill_work or admit_possible or n_reaped > 0

        if self.spec is not None:
            # speculative mode is synchronous per round (acceptance needs
            # the fetch), so there is never an in-flight decode window
            self._apply_deferred_release()
            if prefill_work or admit_possible:
                self._prefill_round(out)
            if any(s is not None and s.prefill_done >= len(s.prompt)
                   for s in self.slots):
                self._spec_round(out)
            return out

        if self._inflight:
            if not sync_needed and self._can_chain():
                self._dispatch_decode()
            out.extend(self._process_oldest_inflight())
            while not self.by_seq and self._inflight:
                # every live sequence finished: drain the stale window so
                # its pages release instead of idling in limbo
                out.extend(self._process_oldest_inflight())
            if not self._inflight:
                self._apply_deferred_release()
            return out

        self._apply_deferred_release()
        if prefill_work or admit_possible:
            self._prefill_round(out)
            # if no prefill progress was possible (e.g. pool full), fall
            # through to decode so the engine never stalls
        if any(s is not None and s.prefill_done >= len(s.prompt)
               for s in self.slots):
            # non-blocking enqueue — even right after a prefill round, so
            # decode keeps advancing between chunks of a long prompt; the
            # results are fetched on a later iteration
            self._dispatch_decode(out)
        return out

    # ------------------------------------------------------------------
    def _reap_cancelled(self) -> List[StepOutput]:
        outs = []
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.cancelled:
                outs.append(StepOutput(slot.seq_id, slot.last_token, 0.0,
                                       FinishReason.CANCELLED))
                self._free_slot(i)
        return outs

    def _free_slot(self, i: int) -> None:
        slot = self.slots[i]
        if slot is None:
            return
        # a queued-but-unapplied seed for this slot must die with it, or a
        # later occupant of the slot could get two key writes at one index
        # (implementation-defined winner)
        self._pending_seeds = [(ix, sd) for ix, sd in self._pending_seeds
                               if ix != i]
        self._decode_seen.pop(i, None)
        self._spec_states.pop(slot.seq_id, None)
        if self.proposer is not None:
            self.proposer.drop(slot.seq_id)
        if self._inflight:
            # an enqueued decode dispatch may still write into this
            # sequence's pages; hold the release until the window drains so
            # the pages cannot be reallocated under the in-flight program
            self._deferred_release.append(slot.seq_id)
        else:
            self.pool.release(slot.seq_id)
        self.by_seq.pop(slot.seq_id, None)
        self.slots[i] = None

    def _apply_deferred_release(self) -> None:
        if self._deferred_release and not self._inflight:
            for seq_id in self._deferred_release:
                self.pool.release(seq_id)
            self._deferred_release.clear()

    def _offload_evicted(self, seq_hash: int, page: int) -> None:
        """Eviction hook: queue the page for host-tier offload. The data
        stays valid until the page's new owner WRITES (the next device
        dispatch), so :meth:`_flush_evictions` batches the copies out right
        before any dispatch that could overwrite pool pages."""
        if self.tiered is None:
            return
        # an evicted page's slot can be rewritten by the very next
        # dispatch: deferred write-through entries for it would mirror the
        # new owner's data under the old hash. Drop them — this eviction
        # entry offloads the same block with still-valid data.
        entry = (seq_hash, page)
        for buf in (self._writethrough_buf, self._writethrough_armed,
                    self._writethrough_pending):
            if entry in buf:
                buf.remove(entry)
        self._evict_buf.append(entry)

    def _writethrough_sealed(self, seq_id: str, block, page: int,
                             lora_id: int) -> None:
        """Seal hook (cluster sharing): mirror the block to the host tier
        so peers can fetch it while it is still hot on device. The KV for
        a freshly sealed block is NOT on device yet — see the ratchet in
        :meth:`_advance_writethrough`. Host-tier restores also seal
        (``fire_stored``) — those blocks came FROM the tier, so mirroring
        them back would be a wasted d2h exactly on the cluster-warm path."""
        if block.sequence_hash in self.tiered:
            return
        self._writethrough_pending.append((block.sequence_hash, page))

    def _advance_writethrough(self) -> None:
        """Step-boundary ratchet for cluster write-through mirrors: a
        block sealed during step N has its KV written by a dispatch issued
        no later than step N+1 (pipelined decode chains one step behind
        the seal), so entries become d2h-safe at the top of step N+2 —
        the copy then reads the post-dispatch pool binding. Also drains
        the ready batch on decode-only steps, which never hit the
        extend-path flush sites."""
        if (not self._writethrough_pending and not self._writethrough_armed
                and not self._writethrough_buf):
            return
        self._writethrough_buf.extend(self._writethrough_armed)
        self._writethrough_armed = self._writethrough_pending
        self._writethrough_pending = []
        if self._writethrough_buf:
            self._flush_evictions()

    def _flush_evictions(self) -> None:
        if not self._evict_buf and not self._writethrough_buf:
            return
        # evictions + write-through mirrors share one batched d2h; dedupe
        # (a written-through block can also be in the eviction batch)
        buf = list(dict.fromkeys(self._evict_buf + self._writethrough_buf))
        self._evict_buf, self._writethrough_buf = [], []
        pages = [p for _, p in buf]
        t0 = time.perf_counter()
        k, v = self.copy_stream.d2h_pages(self.k_pool, self.v_pool, pages,
                                          pipeline=len(pages) > 4)
        from ..obs.flows import record_flow
        record_flow("d2h_writethrough", k.nbytes + v.nbytes,
                    time.perf_counter() - t0)
        for i, (seq_hash, _) in enumerate(buf):
            self.tiered.offload(seq_hash, k[i], v[i])

    # ------------------------------------------------------------------
    # placement-driven h2d prefetch (asyncio thread -> admission restore)
    # ------------------------------------------------------------------
    def stage_prefetch(self, token_ids, lora_id: int = 0) -> int:
        """Upload matched host/disk-tier prefix blocks to the device
        STAGING buffer while the request still queues at the slot gate
        (asyncio thread; the engine thread keeps dispatching). Admission's
        restore then consumes them with a d2d scatter instead of paying
        the h2d on first prefill's critical path. Returns blocks staged.

        Safe concurrently with the engine thread: the tier is internally
        locked, staged arrays are fresh device buffers nothing else
        references, and the stage dict is lock-guarded."""
        from ..llm.tokens import compute_seq_hashes
        from ..utils.knobs import env_float

        cap = int(env_float("DYN_H2D_PREFETCH_BLOCKS", 32, minimum=0.0))
        if cap <= 0 or self.tiered is None:
            return 0
        dt = self.cfg.model.dtype
        staged = 0
        nbytes = 0
        t0 = time.perf_counter()
        for h in compute_seq_hashes(list(token_ids), self.page_size,
                                    lora_id=lora_id):
            if self.pool.blocks.contains(h):
                continue            # device-resident: nothing to move
            with self._h2d_stage_lock:
                if h in self._h2d_stage:
                    continue
            kv = self.tiered.peek(h)   # copies; no LRU perturbation
            if kv is None:
                break               # consecutive-prefix property
            # enqueue the h2d now — by admission time the copy has been
            # overlapping the queue wait instead of gating first prefill
            k_dev = jnp.asarray(kv[0], dt)
            v_dev = jnp.asarray(kv[1], dt)
            nbytes += kv[0].nbytes + kv[1].nbytes
            with self._h2d_stage_lock:
                while len(self._h2d_stage) >= cap:
                    self._h2d_stage.pop(next(iter(self._h2d_stage)))
                self._h2d_stage[h] = (k_dev, v_dev)
                self._h2d_requested.add(h)
                if len(self._h2d_requested) > 4 * cap:
                    # cancelled/never-admitted requests must not grow the
                    # stall-attribution set forever
                    self._h2d_requested.clear()
            staged += 1
            if staged >= cap:
                break
        if staged:
            from ..obs.flows import record_flow
            record_flow("h2d_prefetch", nbytes,
                        time.perf_counter() - t0)
        return staged

    def _restore_prefix(self, seq_id: str, prompt: List[int]) -> int:
        """Prefix reuse at admission: claim matching device blocks,
        consume prefetch-staged device blocks (d2d), and upload the
        remaining matching host-tier blocks; returns tokens satisfied
        from cache (always < len(prompt) so the last token still
        computes logits)."""
        host_lookup = None
        fetched: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        staged: Dict[int, Tuple[Any, Any]] = {}
        if self.tiered is not None:
            def host_lookup(h):
                with self._h2d_stage_lock:
                    dev = self._h2d_stage.pop(h, None)
                    self._h2d_requested.discard(h)
                if dev is not None:
                    staged[h] = dev
                    return True
                # fetch (and copy) eagerly: leasing the upload page can evict
                # a device block whose offload lands in — and LRU-drops from —
                # the very host tier we matched against
                kv = self.tiered.lookup(h)
                if kv is None:
                    return False
                fetched[h] = (kv[0].copy(), kv[1].copy())
                return True
        matched, uploads = self.pool.match_prefix(
            seq_id, prompt, len(prompt) - 1, host_lookup)
        if uploads:
            self._flush_evictions()
            from ..utils.prometheus import stage_metrics
            stage = stage_metrics()
            host_up = [(h, p) for h, p in uploads if h not in staged]
            dev_up = [(h, p) for h, p in uploads if h in staged]
            if host_up:
                pages = [p for _, p in host_up]
                ks = np.stack([fetched[h][0] for h, _ in host_up])
                vs = np.stack([fetched[h][1] for h, _ in host_up])
                t0 = time.perf_counter()
                self.k_pool, self.v_pool = self.copy_stream.h2d_pages(
                    self.k_pool, self.v_pool, pages, ks, vs)
                from ..obs.flows import record_flow
                record_flow("h2d_prefetch", ks.nbytes + vs.nbytes,
                            time.perf_counter() - t0, trace_id=seq_id)
                stalls = 0
                with self._h2d_stage_lock:
                    for h, _ in host_up:
                        if h in self._h2d_requested:
                            self._h2d_requested.discard(h)
                            stalls += 1
                if stalls:
                    stage.prefetch_h2d_stalls.inc(amount=float(stalls))
            if dev_up:
                self.k_pool, self.v_pool = self.copy_stream.scatter_blocks(
                    self.k_pool, self.v_pool, [p for _, p in dev_up],
                    [staged[h][0] for h, _ in dev_up],
                    [staged[h][1] for h, _ in dev_up])
                stage.prefetch_h2d_hits.inc(amount=float(len(dev_up)))
        return matched

    def _prepare_mm(self, req: BackendInput, prompt: List[int]):
        """Validate + encode a VLM request. Returns (spans, soft, digest)
        or an error string. Vision encode happens here (admission, engine
        thread) so the prefill dispatch itself stays token-shaped."""
        import hashlib

        from . import multimodal as mm

        m = self.cfg.model
        if self.vision_cfg is None:
            return ("this model has no vision tower; images are not "
                    "servable (text-only deployment)")
        if self.cfg.pp > 1:
            return ("image requests are not supported on pipeline-parallel "
                    "engines yet (the staged prefill takes no span inputs)")
        if m.image_token_id is None:
            return "model config has no image_token_id"
        spans = mm.image_spans(prompt, m.image_token_id)
        err = mm.validate_mm_prompt(spans, len(req.images),
                                    m.mm_tokens_per_image,
                                    self.cfg.prefill_chunk)
        if err:
            return err
        try:
            px = np.stack([mm.normalize_image(im, self.vision_cfg.image_size)
                           for im in req.images])
        except ValueError as e:
            return str(e)
        digest = 0
        if not getattr(req, "kv_salt", 0):
            # only needed when the frontend didn't already salt the request
            # (preprocessor.image_kv_salt): hashing the full normalized
            # pixel stack on the engine thread is pure waste otherwise
            digest = int.from_bytes(
                hashlib.blake2b(px.tobytes(), digest_size=8).digest(),
                "little")
        # dynalint: ok(host-sync) vision-tower fetch: one soft-token array
        # per image batch at admission, reused for every prefill chunk
        soft = np.asarray(self._encode_images(jnp.asarray(px)))
        return spans, soft, digest

    def _admit_one(self, out: List[StepOutput]):
        """Admit the head-of-line request into a free slot (no prefill yet).
        Returns (slot_idx, slot), "rejected" (popped with an error emitted),
        or "blocked" (no KV capacity right now)."""
        seq_id, req = self.waiting[0]
        prompt = list(req.token_ids)
        over_ctx = len(prompt) >= self.cfg.max_context
        over_pool = (self.pool.pages_needed(len(prompt) + 1)
                     > self.pool.num_pages - 1)
        if over_ctx or over_pool:
            # beyond the dense path's reach. With KV paging enabled this
            # is exactly the long-context lane's workload; without it,
            # reject with the typed 400 body naming the configured limit
            # (can NEVER fit, even with an empty pool: don't starve)
            self.waiting.popleft()
            if self.kvpager is not None:
                so = self.kvpager.try_route(seq_id, req)
                if so is None:
                    return "paged"
                out.append(so)
                return "rejected"
            if over_ctx:
                msg = (f"prompt of {len(prompt)} tokens exceeds the "
                       f"configured max_context of {self.cfg.max_context}")
            else:
                msg = (f"prompt of {len(prompt)} tokens cannot fit in the "
                       f"KV pool ({self.pool.num_pages - 1} pages)")
            out.append(StepOutput(
                seq_id, 0, 0.0, FinishReason.ERROR, error=msg,
                error_code=400, error_stage="engine_admission",
                error_reason="context_exceeded"))
            return "rejected"
        if not self.pool.can_admit(len(prompt) + 1):
            return "blocked"  # decode will free KV space eventually
        mm_spans = mm_soft = None
        chain_salt = getattr(req, "lora_id", 0)
        if req.images:
            err = self._prepare_mm(req, prompt)
            if isinstance(err, str):
                self.waiting.popleft()
                out.append(StepOutput(seq_id, 0, 0.0, FinishReason.ERROR,
                                      error=err))
                return "rejected"
            mm_spans, mm_soft, img_digest = err
            # salt the block-hash chain with the image content: identical
            # (prompt, images) requests still prefix-match, but the same
            # placeholder ids with DIFFERENT images can never alias — in
            # local reuse or the router index. When the FRONTEND already
            # computed a salt (BackendInput.kv_salt, preprocessor digest),
            # use it verbatim: the router's prefix-overlap scoring hashes
            # with that same salt, so published VLM blocks stay routable
            chain_salt = (getattr(req, "kv_salt", 0)
                          or (chain_salt ^ img_digest) & ((1 << 63) - 1))
        self.waiting.popleft()
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, req, prompt)
        slot.mm_spans, slot.mm_soft = mm_spans, mm_soft
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self.pool.create(seq_id, lora_id=chain_salt)
        matched = 0
        if self.cfg.enable_prefix_reuse:
            matched = self._restore_prefix(seq_id, prompt)
            slot.prefill_done = matched
        self.last_prefix_hit = matched
        self.prefix_hit_tokens += matched
        # surfaced on this sequence's FIRST StepOutput (step()'s tagging
        # post-pass) -> EngineOutput.kv_prefix_hit_tokens at the facade
        self._pending_prefix_hit[seq_id] = matched
        self.prefix_query_tokens += len(prompt)
        if getattr(req, "resume_pos", 0):
            # mid-stream resume: the restored prefix IS the KV re-attach —
            # everything past `matched` (including the dead worker's
            # emitted tail) is teacher-forced prefill recompute. Counted
            # in blocks so the soak can assert the re-attach path (not
            # full re-prefill) was taken in the donor-alive arm.
            from ..utils.prometheus import stage_metrics

            stage_metrics().resume_kv_reattach_blocks.inc(
                amount=matched // self.pool.page_size)
        self._load_sampling(slot_idx, req)
        return slot_idx, slot

    def _prefill_round(self, out: List[StepOutput]) -> bool:
        """Advance every mid-prefill slot by one chunk and admit as many
        waiting requests as fit, all in ONE batched dispatch (up to the
        prefill lane budget). Returns True if a dispatch ran."""
        max_lanes = self.b_buckets[-1]
        chunks = [(i, s) for i, s in enumerate(self.slots)
                  if s is not None and s.prefill_done < len(s.prompt)]
        while (self.waiting and None in self.slots
               and len(chunks) < max_lanes):
            admitted = self._admit_one(out)
            if admitted == "blocked":
                break
            if admitted in ("rejected", "paged"):
                continue
            # fully satisfied by prefix reuse still needs its last token
            # computed, so every admission lands in the chunk list
            chunks.append(admitted)
        chunks = chunks[:max_lanes]
        if not chunks:
            return False
        return self._prefill_dispatch(chunks, out)

    def _load_sampling(self, slot_idx: int, req: BackendInput) -> None:
        s = self.sampling
        s.temperature[slot_idx] = float(req.sampling.temperature or 0.0)
        s.top_p[slot_idx] = float(req.sampling.top_p
                                  if req.sampling.top_p is not None else 1.0)
        s.top_k[slot_idx] = int(min(req.sampling.top_k or 0, STATIC_K))
        s.freq_pen[slot_idx] = float(req.sampling.frequency_penalty or 0.0)
        s.pres_pen[slot_idx] = float(req.sampling.presence_penalty or 0.0)
        if req.sampling.seed is not None:
            # deferred to the next prefill dispatch: keeps EVERY device op
            # at a mirrorable dispatch point (multi-host lockstep) and
            # batches the key writes. A resumed request folds its resume
            # position into the seed: the emitted prefix is replayed
            # verbatim (forced tokens, no draws), and the continuation
            # gets a fresh deterministic stream instead of re-issuing the
            # dead worker's already-consumed draws.
            self._pending_seeds.append((slot_idx, resume_seed(
                int(req.sampling.seed),
                int(getattr(req, "resume_pos", 0) or 0))))

    def _apply_pending_seeds(self) -> List[Tuple[int, int]]:
        applied, self._pending_seeds = self._pending_seeds, []
        if applied:
            s = self.sampling
            idx = jnp.asarray([i for i, _ in applied])
            keys = jax.vmap(jax.random.key)(
                jnp.asarray([seed for _, seed in applied]))
            s.key = s.key.at[idx].set(keys)
        return applied

    def _run_prefill_program(self, Bp, C, S, tokens, positions, write_idx,
                             read_idx, read_pos, read_valid, last_i, temp,
                             top_p, top_k, idxs, last_lanes,
                             mm_arrays=None):
        """Execute the batched prefill program + key bookkeeping. The SAME
        code path runs on the leader (from _prefill_dispatch) and on
        followers (from mirror_dispatch) so device state stays in lockstep."""
        s = self.sampling
        keys = s.key[jnp.asarray(idxs)]
        fn = self._prefill_fn(Bp, C, S, mm=mm_arrays is not None)
        with _trace_annotation(f"dynamo.prefill[B{Bp},C{C},S{S}]"):
            if mm_arrays is not None:
                packed, _tok, new_keys, self.k_pool, self.v_pool = fn(
                    self.params, tokens, positions, self.k_pool, self.v_pool,
                    write_idx, read_idx, read_pos, read_valid, last_i,
                    temp, top_p, top_k, keys, mm_arrays["ov_vals"],
                    mm_arrays["ov_mask"], mm_arrays["q_span"],
                    mm_arrays["read_span"])
            else:
                packed, _tok, new_keys, self.k_pool, self.v_pool = fn(
                    self.params, tokens, positions, self.k_pool, self.v_pool,
                    write_idx, read_idx, read_pos, read_valid, last_i,
                    temp, top_p, top_k, keys)
        # persist advanced PRNG keys only for lanes that really sampled
        if last_lanes:
            la = jnp.asarray([int(idxs[l]) for l in last_lanes])
            s.key = s.key.at[la].set(new_keys[jnp.asarray(last_lanes)])
        return packed

    def _prefill_dispatch(self, chunks: List[Tuple[int, _Slot]],
                          out: List[StepOutput]) -> bool:
        """Advance each (slot_idx, slot) by one prompt chunk in a single
        batched dispatch; fetch all lanes' sampled tokens with ONE host
        round-trip and keep results only for lanes whose prompt completed.
        Returns True if a dispatch ran."""
        cfg = self.cfg
        work = []  # (slot_idx, slot, start, count, is_last)
        for i, slot in chunks:
            prompt = slot.prompt
            start = slot.prefill_done
            if slot.mm_spans is not None:
                # never split an image span across chunks: its queries need
                # every span key written in the same dispatch
                from .multimodal import chunk_end
                count = chunk_end(slot.mm_spans, start, cfg.prefill_chunk)
            else:
                count = min(len(prompt) - start, cfg.prefill_chunk)
            try:
                self.pool.extend(slot.seq_id, prompt[start:start + count])
            except OutOfPages:
                out.append(StepOutput(slot.seq_id, 0, 0.0,
                                      FinishReason.ERROR,
                                      error="out of KV pages during prefill"))
                self._free_slot(i)
                continue
            work.append((i, slot, start, count,
                         start + count == len(prompt)))
        if not work:
            return False
        self._flush_evictions()   # extend() may have evicted pages

        Bp = self._bucket(len(work), self.b_buckets)
        C = self._bucket(max(w[3] for w in work), self.c_buckets)
        S = self._bucket(max(w[2] + w[3] for w in work), self.s_buckets)
        s = self.sampling
        tokens = np.zeros((Bp, C), np.int32)
        positions = np.zeros((Bp, C), np.int32)
        write_idx = np.zeros((Bp, C), np.int32)   # pad -> scratch page 0
        read_idx = np.zeros((Bp, S), np.int32)
        read_pos = np.zeros((Bp, S), np.int32)
        read_valid = np.zeros((Bp, S), bool)
        last_i = np.zeros(Bp, np.int32)
        temp = np.zeros(Bp, np.float32)
        top_p = np.ones(Bp, np.float32)
        top_k = np.zeros(Bp, np.int32)
        idxs = np.zeros(Bp, np.int32)
        mm = any(w[1].mm_spans is not None for w in work)
        mm_arrays = None
        if mm:
            from .multimodal import soft_token_rows
            D = cfg.model.hidden_size
            ov_vals = np.zeros((Bp, C, D), np.float32)
            ov_mask = np.zeros((Bp, C), bool)
            q_span = np.zeros((Bp, C), np.int32)
            read_span = np.zeros((Bp, S), np.int32)
        for lane, (i, slot, start, count, _) in enumerate(work):
            tokens[lane, :count] = slot.prompt[start:start + count]
            positions[lane, :count] = np.arange(start, start + count)
            write_idx[lane, :count] = self.pool.write_slots(
                slot.seq_id, start, count)
            r_s, r_p, r_v = self.pool.read_slots(slot.seq_id,
                                                 start + count, S)
            read_idx[lane], read_pos[lane], read_valid[lane] = r_s, r_p, r_v
            last_i[lane] = count - 1
            temp[lane] = s.temperature[i]
            top_p[lane] = s.top_p[i]
            top_k[lane] = s.top_k[i]
            idxs[lane] = i
            if mm and slot.mm_spans is not None:
                vals, maskv = soft_token_rows(slot.mm_spans, slot.mm_soft,
                                              start, count)
                ov_vals[lane, :count] = vals
                ov_mask[lane, :count] = maskv
                q_span[lane, :count] = slot.mm_spans[start:start + count]
                # context slots map position -> image group (0 past prompt)
                sp = np.zeros(S, np.int32)
                n = min(len(slot.mm_spans), S)
                sp[:n] = slot.mm_spans[:n]
                read_span[lane] = np.where(r_v, sp[np.minimum(r_p, S - 1)],
                                           0)
        if mm:
            mm_arrays = {"ov_vals": ov_vals, "ov_mask": ov_mask,
                         "q_span": q_span, "read_span": read_span}
        seeds = self._apply_pending_seeds()
        last_lanes = [lane for lane, w in enumerate(work) if w[4]]
        if self.dispatch_hook is not None:
            arrays = {"tokens": tokens, "positions": positions,
                      "write_idx": write_idx, "read_idx": read_idx,
                      "read_pos": read_pos, "read_valid": read_valid,
                      "last_i": last_i, "temp": temp, "top_p": top_p,
                      "top_k": top_k, "idxs": idxs}
            if mm_arrays:
                arrays.update(mm_arrays)
            self.dispatch_hook("prefill", {
                "Bp": Bp, "C": C, "S": S, "seeds": seeds,
                "last_lanes": last_lanes, "mm": bool(mm_arrays),
            }, arrays)
        t_disp = time.perf_counter()
        packed = self._run_prefill_program(
            Bp, C, S, tokens, positions, write_idx, read_idx, read_pos,
            read_valid, last_i, temp, top_p, top_k, idxs, last_lanes,
            mm_arrays=mm_arrays)

        # dynalint: ok(host-sync) THE designed prefill fetch: one packed
        # [Bp,2] (token,logprob) array per dispatch, batched across lanes
        packed_np = np.asarray(packed)            # ONE host fetch
        if not self._take_compiled_flag():
            from ..utils.roofline import prefill_cost

            fl, by, tk = prefill_cost(
                self.costs, [(w[2], w[3]) for w in work])
            self.goodput.account(fl, by, time.perf_counter() - t_disp, tk)
        for lane, (i, slot, start, count, is_last) in enumerate(work):
            slot.prefill_done = start + count
            if not is_last:
                continue
            t = int(packed_np[lane, 0])
            lp = float(packed_np[lane, 1])
            try:
                self._append_generated(slot, t)
            except OutOfPages:
                out.append(StepOutput(slot.seq_id, t, lp,
                                      FinishReason.ERROR,
                                      error="out of KV pages appending the "
                                            "first generated token"))
                self._free_slot(i)
                continue
            slot.cum_logprob += lp
            fin = self._finish_reason(slot, t)
            out.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin,
                                  prompt_tokens=len(slot.prompt),
                                  token_logprob=lp))
            if fin is not None:
                self._free_slot(i)
        return True

    def _append_generated(self, slot: _Slot, token: int) -> None:
        slot.generated += 1
        slot.last_token = token
        self.pool.extend(slot.seq_id, [token])

    def _finish_reason(self, slot: _Slot, token: int) -> Optional[FinishReason]:
        req = slot.request
        if not req.stop.ignore_eos:
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            if token in eos and slot.generated >= (req.stop.min_tokens or 0):
                return FinishReason.EOS
        if req.stop.max_tokens and slot.generated >= req.stop.max_tokens:
            return FinishReason.LENGTH
        if len(slot.prompt) + slot.generated >= self.cfg.max_context:
            return FinishReason.LENGTH
        return None

    # ------------------------------------------------------------------
    def _decode_eligible(self, lookahead: Optional[int] = None):
        """(slot_idx, slot, phys_len) for every decode-ready slot whose next
        dispatch's pages could be reserved; deferred = ready but no pages.
        ``lookahead`` is the page reservation beyond phys (default: the
        chained decode window; the spec path passes its verify window)."""
        N = self.cfg.decode_steps if lookahead is None else lookahead
        active, deferred = [], []
        for i, slot in enumerate(self.slots):
            if slot is None or slot.prefill_done < len(slot.prompt):
                continue
            phys = slot.sched_len or (len(slot.prompt) + slot.generated)
            try:
                # reserve room for N speculative tokens up front
                self.pool.ensure_pages(slot.seq_id, phys + N)
            except OutOfPages:
                # pool pressure: defer this slot — batchmates finishing will
                # free pages — rather than killing a healthy request
                deferred.append((i, slot))
                continue
            active.append((i, slot, phys))
        return active, deferred

    def _can_chain(self) -> bool:
        """True if the next decode dispatch can be enqueued straight off the
        in-flight one's on-device outputs: same membership, pages available
        for every lane, and only one dispatch currently outstanding."""
        if len(self._inflight) != 1:
            return False
        rec = self._inflight[-1]
        # the chained dispatch feeds the previous dispatch's on-device
        # final_tok to EVERY lane, so the decode-ready set must be EXACTLY
        # the lanes that were active in that dispatch: a newly injected or
        # newly eligible slot (inject_prefilled, deferred slot unblocking)
        # has a real last_token the device array does not contain
        ready_now = {i for i, s in enumerate(self.slots)
                     if s is not None and s.prefill_done >= len(s.prompt)}
        rec_lanes = {i for i, _, _ in rec["active"]}
        if ready_now != rec_lanes:
            return False
        for i, slot, _ in rec["active"]:
            if self.slots[i] is not slot:
                return False   # membership changed (cancel) -> sync
        N = self.cfg.decode_steps
        for i, slot, _ in rec["active"]:
            try:
                self.pool.ensure_pages(slot.seq_id, slot.sched_len + N)
            except OutOfPages:
                return False
        return True

    def _evict_largest_deferred(self, deferred, out: List[StepOutput]) -> None:
        """No decode-ready lane can be dispatched and every deferred lane
        is blocked on KV capacity: evict the largest consumer so the rest
        of the system unblocks (capacity error). Shared by the chained
        decode path and the speculative verify path."""
        i, slot = max(deferred,
                      key=lambda t: len(self.pool.seqs[t[1].seq_id].pages))
        out.append(StepOutput(
            slot.seq_id, slot.last_token, slot.cum_logprob,
            FinishReason.ERROR,
            error="evicted under KV pool pressure (no capacity to "
                  "continue decoding)"))
        self._free_slot(i)

    def _dispatch_decode(self, out: Optional[List[StepOutput]] = None) -> None:
        """Enqueue one multi-step decode dispatch WITHOUT fetching results.
        If a dispatch is already in flight, chain off its on-device token
        and key arrays (no host data dependency)."""
        B = self.cfg.max_batch
        N = self.cfg.decode_steps
        chain = bool(self._inflight)
        active, deferred = self._decode_eligible()
        if not active:
            if deferred and not chain and out is not None:
                self._evict_largest_deferred(deferred, out)
            return
        self._flush_evictions()   # ensure_pages() may have evicted pages
        S = self._bucket(max(phys for _, _, phys in active) + N,
                         self.s_buckets)
        P = S // self.page_size

        lengths = np.ones(B, np.int32)    # inactive lanes write into page 0
        page_tables = np.zeros((B, P), np.int32)
        for i, slot, phys in active:
            lengths[i] = phys
            page_tables[i] = self.pool.page_table_row(slot.seq_id, P)
            slot.sched_len = phys + N
        if chain:
            tokens = None   # resolved to the previous dispatch's device toks
        else:
            tokens = np.zeros(B, np.int32)
            for i, slot, _ in active:
                tokens[i] = slot.last_token

        # lanes whose SEQUENCE changed since their last decode dispatch
        # restart their penalty counts in-program (a chained dispatch has
        # identical membership by _can_chain, so fresh is all-False there)
        fresh = np.zeros(B, bool)
        if not chain:
            for i, slot, _ in active:
                if self._decode_seen.get(i) != slot.seq_id:
                    fresh[i] = True
                    self._decode_seen[i] = slot.seq_id
        active_mask = np.zeros(B, bool)
        for i, _, _ in active:
            active_mask[i] = True

        s = self.sampling
        if self.dispatch_hook is not None:
            payload = {"page_tables": page_tables, "lengths": lengths,
                       "temp": s.temperature, "top_p": s.top_p,
                       "top_k": s.top_k, "fresh": fresh,
                       "active_mask": active_mask,
                       "freq_pen": s.freq_pen, "pres_pen": s.pres_pen}
            if tokens is not None:
                payload["tokens"] = tokens
            self.dispatch_hook("decode", {"S": S, "chain": chain}, payload)
        packed, final_tok = self._run_decode_program(
            S, tokens, page_tables, lengths, fresh, active_mask)
        self._inflight.append({"packed": packed, "final_tok": final_tok,
                               "active": active,
                               "lengths": [phys for _, _, phys in active],
                               "compiled": self._take_compiled_flag(),
                               "dispatched_at": time.perf_counter()})
        # flight recorder: the hang watchdog judges "a dispatch in flight
        # with no fetch completing for N x the EWMA step time" off this
        _flightrec.hb_begin("engine.decode", stall="decode")
        _flightrec.note_event("engine.dispatch", depth=len(self._inflight),
                              batch=len(active), steps=S)

    def _run_decode_program(self, S: int, tokens, page_tables, lengths,
                            fresh, active_mask):
        """Execute the multi-step decode program. ``tokens=None`` chains off
        the previous dispatch's on-device final tokens. The SAME code path
        runs on the leader and on follower mirrors (multi-host lockstep)."""
        if tokens is None:
            tokens = self._last_final_tok
        s = self.sampling
        fn = self._decode_fn(S)
        with _trace_annotation(f"dynamo.decode[S{S}]"):
            (packed, final_tok, new_key, self.k_pool, self.v_pool,
             self.gen_counts) = fn(
                self.params, tokens, self.k_pool, self.v_pool,
                page_tables, lengths, s.temperature, s.top_p, s.top_k, s.key,
                self.gen_counts, fresh, active_mask, s.freq_pen, s.pres_pen)
        s.key = new_key
        self._last_final_tok = final_tok
        return packed, final_tok

    # ------------------------------------------------------------------
    # speculative decoding (engine/spec.py owns proposers + acceptance)
    # ------------------------------------------------------------------
    def _run_verify_program(self, S: int, K: int, tokens, page_tables,
                            lengths, fresh, active_mask, upd_tok, upd_mask):
        """Execute the verify program. The SAME code path runs on the
        leader and on follower mirrors (multi-host lockstep)."""
        s = self.sampling
        fn = self._verify_fn(S, K)
        with _trace_annotation(f"dynamo.verify[S{S},K{K}]"):
            (packed, new_key, self.k_pool, self.v_pool,
             self.gen_counts) = fn(
                self.params, tokens, self.k_pool, self.v_pool, page_tables,
                lengths, s.temperature, s.top_p, s.top_k, s.key,
                self.gen_counts, fresh, active_mask, s.freq_pen, s.pres_pen,
                upd_tok, upd_mask)
        s.key = new_key
        return packed

    @staticmethod
    def _spec_opt_out(req: BackendInput) -> bool:
        """Lanes that must not speculate (they still ride the verify
        dispatch with zero drafts, which IS a plain single-token decode
        step): per-request opt-out, and penalty requests — the verify
        program applies penalty counts per-dispatch, which is only exact
        when each dispatch commits one token."""
        if getattr(req, "no_spec", False):
            return True
        sp = req.sampling
        return bool(sp.frequency_penalty or sp.presence_penalty)

    def _spec_seq_state(self, slot: _Slot):
        from .spec import SeqSpecState

        st = self._spec_states.get(slot.seq_id)
        if st is None:
            # created at first decode entry: exactly one generated token
            # exists (the prefill- or injection-sampled first token)
            st = SeqSpecState(
                tokens=list(slot.prompt) + [int(slot.last_token)],
                k=self.spec.k_max,
                pending=[int(slot.last_token)])
            self._spec_states[slot.seq_id] = st
        return st

    def _spec_round(self, out: List[StepOutput]) -> None:
        """One synchronous speculative-decoding round: propose k drafts per
        lane, verify all of them in ONE wider forward, accept host-side,
        commit only accepted tokens. Unlike the chained decode path this is
        a sync point every round (acceptance needs the fetch), but each
        dispatch can commit up to k+1 tokens instead of one."""
        from .sampling import spec_accept, spec_unpack

        cfg, sp = self.cfg, self.spec
        B = cfg.max_batch
        # reserve the whole verify window (k drafts + bonus) up front:
        # rollback is then pure bookkeeping, never data movement
        active, deferred = self._decode_eligible(lookahead=sp.k_max + 1)
        if not active:
            if deferred:
                self._evict_largest_deferred(deferred, out)
            return
        self._flush_evictions()   # ensure_pages() may have evicted pages

        drafts: Dict[int, List[int]] = {}
        for i, slot, phys in active:
            st = self._spec_seq_state(slot)
            d: List[int] = []
            if not self._spec_opt_out(slot.request):
                d = self.proposer.propose(slot.seq_id, st, st.k)[:st.k]
            drafts[i] = [int(x) for x in d]
            self.spec_proposed_total += len(d)
            if d:
                self.stage.spec_proposed.inc(amount=float(len(d)))

        K = sp.bucket(max(len(d) for d in drafts.values()))
        T = K + 1
        S = self._bucket(max(phys for _, _, phys in active) + K,
                         self.s_buckets)
        P = S // self.page_size
        U = sp.k_max + 1
        tokens = np.zeros((B, T), np.int32)
        lengths = np.ones(B, np.int32)     # inactive lanes write to page 0
        page_tables = np.zeros((B, P), np.int32)
        upd_tok = np.zeros((B, U), np.int32)
        upd_mask = np.zeros((B, U), bool)
        fresh = np.zeros(B, bool)
        active_mask = np.zeros(B, bool)
        for i, slot, phys in active:
            st = self._spec_states[slot.seq_id]
            d = drafts[i]
            tokens[i, 0] = slot.last_token
            tokens[i, 1:1 + len(d)] = d
            lengths[i] = phys
            page_tables[i] = self.pool.page_table_row(slot.seq_id, P)
            upd = st.pending[-U:]
            upd_tok[i, :len(upd)] = upd
            upd_mask[i, :len(upd)] = True
            active_mask[i] = True
            if self._decode_seen.get(i) != slot.seq_id:
                fresh[i] = True
                self._decode_seen[i] = slot.seq_id

        s = self.sampling
        if self.dispatch_hook is not None:
            self.dispatch_hook("verify", {"S": S, "K": K}, {
                "tokens": tokens, "page_tables": page_tables,
                "lengths": lengths, "fresh": fresh,
                "active_mask": active_mask, "upd_tok": upd_tok,
                "upd_mask": upd_mask, "temp": s.temperature,
                "top_p": s.top_p, "top_k": s.top_k,
                "freq_pen": s.freq_pen, "pres_pen": s.pres_pen})
        t0 = time.perf_counter()
        packed = self._run_verify_program(
            S, K, tokens, page_tables, lengths, fresh, active_mask,
            upd_tok, upd_mask)
        # dynalint: ok(host-sync) THE designed verify fetch: one packed
        # array per verify dispatch covers k+1 positions for every lane
        r = spec_unpack(np.asarray(packed), K)      # ONE host fetch
        if not self._take_compiled_flag():
            from ..utils.roofline import verify_cost

            fl, by, tk = verify_cost(
                self.costs, [phys for _, _, phys in active], T)
            self.goodput.account(fl, by, time.perf_counter() - t0, tk)
        n_emitted = 0
        self.spec_dispatch_total += 1               # one verify dispatch
        for i, slot, phys in active:
            st = self._spec_states[slot.seq_id]
            d = drafts[i]
            lane = {k: v[i] for k, v in r.items()}
            greedy = float(s.temperature[i]) <= 0.0
            toks, lps, acc = spec_accept(d, greedy, lane)
            self.spec_accepted_total += acc
            if d:
                self.stage.spec_accepted.inc(amount=float(acc))
                self.stage.spec_per_dispatch.observe(value=float(acc))
            st.pending = []
            for tok, lp in zip(toks, lps):
                self.pool.account_tokens(slot.seq_id, [tok])
                slot.generated += 1
                slot.last_token = tok
                slot.cum_logprob += lp
                st.tokens.append(tok)
                st.pending.append(tok)
                n_emitted += 1
                fin = self._finish_reason(slot, tok)
                out.append(StepOutput(slot.seq_id, tok, slot.cum_logprob,
                                      fin, token_logprob=lp))
                if fin is not None:
                    self._free_slot(i)
                    break
            if d and self.slots[i] is slot:
                st.k = sp.next_k(st.k, acc, len(d))
        if n_emitted:
            self.stage.decode_step.observe(
                value=(time.perf_counter() - t0) / n_emitted)

    def mirror_dispatch(self, kind: str, meta: Dict[str, Any],
                        arrs: Dict[str, np.ndarray]) -> None:
        """Follower-side replay of a leader dispatch (multi-host mode): runs
        the identical jitted program with the identical inputs so every
        process's sharded params/KV/key state advances in lockstep. Results
        are not fetched — only the leader streams tokens to clients."""
        if kind == "prefill":
            for slot_idx, seed in meta.get("seeds", []):
                self._pending_seeds.append((int(slot_idx), int(seed)))
            self._apply_pending_seeds()
            mm_arrays = ({k: arrs[k] for k in ("ov_vals", "ov_mask",
                                               "q_span", "read_span")}
                         if meta.get("mm") else None)
            self._run_prefill_program(
                meta["Bp"], meta["C"], meta["S"], arrs["tokens"],
                arrs["positions"], arrs["write_idx"], arrs["read_idx"],
                arrs["read_pos"], arrs["read_valid"], arrs["last_i"],
                arrs["temp"], arrs["top_p"], arrs["top_k"], arrs["idxs"],
                [int(x) for x in meta.get("last_lanes", [])],
                mm_arrays=mm_arrays)
        elif kind == "decode":
            s = self.sampling
            s.temperature = arrs["temp"]
            s.top_p = arrs["top_p"]
            s.top_k = arrs["top_k"]
            s.freq_pen = arrs["freq_pen"]
            s.pres_pen = arrs["pres_pen"]
            self._run_decode_program(
                meta["S"], arrs.get("tokens"), arrs["page_tables"],
                arrs["lengths"], arrs["fresh"], arrs["active_mask"])
        elif kind == "verify":
            s = self.sampling
            s.temperature = arrs["temp"]
            s.top_p = arrs["top_p"]
            s.top_k = arrs["top_k"]
            s.freq_pen = arrs["freq_pen"]
            s.pres_pen = arrs["pres_pen"]
            self._run_verify_program(
                meta["S"], meta["K"], arrs["tokens"], arrs["page_tables"],
                arrs["lengths"], arrs["fresh"], arrs["active_mask"],
                arrs["upd_tok"], arrs["upd_mask"])
        else:
            raise ValueError(f"unknown dispatch kind {kind!r}")

    def _process_oldest_inflight(self) -> List[StepOutput]:
        """Fetch (blocking) and account the oldest in-flight dispatch."""
        rec = self._inflight.popleft()
        # dynalint: ok(host-sync) THE designed decode fetch: one [N,B,2]
        # array per N-step dispatch — 1/N host round-trips per token, and
        # the pipelined next dispatch is already running when we block here
        packed_np = np.asarray(rec["packed"])     # [N, B, 2] — ONE fetch
        N = packed_np.shape[0]
        if N and "dispatched_at" in rec:
            # effective per-token decode latency: dispatch -> results on
            # host, amortized over the dispatch's N steps (pipelined
            # dispatches overlap compute, which this deliberately reflects)
            elapsed = time.perf_counter() - rec["dispatched_at"]
            self.stage.decode_step.observe(value=elapsed / N)
            # after the blocking fetch (a wedged device shows up THERE):
            # feed the watchdog's step-time EWMA and balance hb_begin
            _flightrec.hb_done("engine.decode", elapsed / N)
            _flightrec.note_event("engine.step", s=round(elapsed, 6), n=N,
                                  compiled=bool(rec.get("compiled")))
            if not rec.get("compiled"):
                from ..utils.roofline import decode_cost

                fl, by, tk = decode_cost(self.costs, rec["lengths"], N)
                self.goodput.account(fl, by, elapsed, tk)
        else:
            _flightrec.hb_done("engine.decode")
        outs: List[StepOutput] = []
        for i, slot, _ in rec["active"]:
            if self.slots[i] is not slot:
                continue   # freed since dispatch (finish/cancel): discard
            for j in range(N):
                t = int(packed_np[j, i, 0])
                self.pool.account_tokens(slot.seq_id, [t])
                slot.generated += 1
                slot.last_token = t
                tok_lp = float(packed_np[j, i, 1])
                slot.cum_logprob += tok_lp
                fin = self._finish_reason(slot, t)
                outs.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin,
                                       token_logprob=tok_lp))
                if fin is not None:
                    # overshoot tokens beyond the finish are discarded; their
                    # page-pool writes are inside this seq's own pages, which
                    # stay held until the in-flight window drains
                    self._free_slot(i)
                    break
        return outs


def _set_result(fut, res) -> None:
    if not fut.done():
        fut.set_result(res)


def _set_exception(fut, exc) -> None:
    if not fut.done():
        fut.set_exception(exc)


def _pallas_probe_ok(m, cfg) -> bool:
    """Compile+run both Pallas kernels once at engine shapes (tiny batch).
    Cheap insurance on the auto path: seconds at init versus every request
    erroring if a kernel fails to lower on this chip."""
    try:
        from ..ops.attention import flash_attention, paged_attention

        # probe the PER-SHARD instantiation the shard_map wrappers actually
        # run at this tp — full-model head counts would validate a kernel
        # that never executes at tp>1
        tp = max(1, cfg.tp)
        Hq = m.num_heads // tp
        Hkv = (m.num_kv_heads // tp if m.num_kv_heads % tp == 0
               else m.num_kv_heads)
        Dh = m.head_dim
        page = cfg.page_size
        q = jnp.zeros((2, Hq, Dh), m.dtype)
        kp = jnp.zeros((Hkv, 3, page, Dh), m.dtype)
        pt = jnp.zeros((2, 1), jnp.int32)
        ln = jnp.ones((2,), jnp.int32)
        # probe the exact kernel variants this model will run: softcap and
        # (on sliding models) the windowed variant are distinct Mosaic
        # lowerings from the plain causal one
        kw = dict(scale=m.attn_scale, softcap=m.attn_logit_softcap)
        windows = ([None, m.sliding_window] if m.sliding_window is not None
                   else [None])
        for w in windows:
            paged_attention(q, kp, kp, pt, ln, interpret=False,
                            window=w, **kw).block_until_ready()
        T = max(8, min(128, cfg.prefill_chunk))
        qf = jnp.zeros((2, T, Hq, Dh), m.dtype)
        kf = jnp.zeros((2, T, Hkv, Dh), m.dtype)
        pos = jnp.zeros((2, T), jnp.int32)
        for w in windows:
            flash_attention(qf, kf, kf, pos, pos, pos < 1, interpret=False,
                            window=w, **kw).block_until_ready()
        return True
    except Exception:  # noqa: BLE001 - any lowering failure means fall back
        log.exception("pallas probe failure detail")
        return False


def _has_safetensors(path: str) -> bool:
    import glob
    import os

    return bool(glob.glob(os.path.join(path, "*.safetensors")))


def _gguf_file(path: str) -> Optional[str]:
    """The GGUF weights file for ``path``: the file itself or the first
    *.gguf inside the directory."""
    import glob
    import os

    if os.path.isfile(path) and path.endswith(".gguf"):
        return path
    hits = sorted(glob.glob(os.path.join(path, "*.gguf")))
    return hits[0] if hits else None


# ---------------------------------------------------------------------------
# Async facade
# ---------------------------------------------------------------------------

class JaxEngine(AsyncEngine[BackendInput, EngineOutput]):
    """AsyncEngine facade: one background engine thread runs EngineCore."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.core = EngineCore(cfg, devices)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: Dict[str, asyncio.Queue] = {}
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._run, name="jax-engine",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        from ..utils.prometheus import stage_metrics

        stage = stage_metrics()
        # DYN_PROFILE_DIR: capture an XLA profile of the first
        # DYN_PROFILE_STEPS (default 32) working engine iterations — the
        # TraceAnnotation scopes around prefill/decode dispatches name the
        # device timeline so it lines up with host-side request spans.
        profile_dir = os.environ.get("DYN_PROFILE_DIR")
        try:
            profile_steps = int(os.environ.get("DYN_PROFILE_STEPS", "32"))
        except ValueError:
            # a typo'd env var must not kill the engine thread
            log.warning("invalid DYN_PROFILE_STEPS=%r; using 32",
                        os.environ.get("DYN_PROFILE_STEPS"))
            profile_steps = 32
        profiling = False
        last_gauges = 0.0
        last_disp = 0
        while self._running:
            moved = False
            while True:
                try:
                    kind, seq_id, payload = self._inbox.get_nowait()
                except thread_queue.Empty:
                    break
                moved = True
                if kind == "submit":
                    self.core.submit(seq_id, payload)
                elif kind == "cancel":
                    self.core.cancel(seq_id)
                elif kind == "inject":
                    try:
                        so = self.core.inject_prefilled(seq_id, *payload)
                    except Exception as e:  # noqa: BLE001
                        log.exception("KV injection failed")
                        so = StepOutput(seq_id, 0, 0.0, FinishReason.ERROR,
                                        error=f"KV injection failed: {e}")
                    self._deliver(so)
                elif kind == "ingest_begin":
                    try:
                        self.core.begin_stream_inject(seq_id, payload)
                    except Exception as e:  # noqa: BLE001
                        log.exception("stream-inject begin failed")
                        self._ingest_fail(seq_id, e)
                elif kind == "ingest_layer":
                    # a begin/earlier-layer failure already dropped the
                    # state and delivered the error: later commands no-op
                    if seq_id in self.core._stream_injects:
                        try:
                            self.core.stream_inject_layer(seq_id, *payload)
                        except Exception as e:  # noqa: BLE001
                            log.exception("stream-inject layer failed")
                            self._ingest_fail(seq_id, e)
                elif kind == "ingest_finish":
                    if seq_id in self.core._stream_injects:
                        try:
                            self._deliver(self.core.finish_stream_inject(
                                seq_id, *payload))
                        except Exception as e:  # noqa: BLE001
                            log.exception("stream-inject finish failed")
                            self._ingest_fail(seq_id, e)
                elif kind == "ingest_abort":
                    self.core.abort_stream_inject(seq_id)
                elif kind == "prefill_extract":
                    request, loop, fut = payload
                    try:
                        res = self.core.prefill_extract(seq_id, request)
                        loop.call_soon_threadsafe(_set_result, fut, res)
                    except Exception as e:
                        log.exception("prefill_extract failed")
                        loop.call_soon_threadsafe(_set_exception, fut, e)
                elif kind == "swap":
                    # model-mobility hot-swap: runs on the engine thread
                    # (single-threaded core contract) post-drain; typed
                    # SwapError propagates to the agent's fallback path
                    host_params, new_cfg, loop, fut = payload
                    from ..fleet.mobility.swap import hot_swap
                    try:
                        res = hot_swap(self.core, host_params, new_cfg)
                        loop.call_soon_threadsafe(_set_result, fut, res)
                    except Exception as e:
                        log.exception("weight hot-swap failed")
                        loop.call_soon_threadsafe(_set_exception, fut, e)
            if not self.core.has_work:
                # idle: keep the windowed goodput gauges honest (they
                # decay to 0 as the last burst ages out of the window)
                now = time.monotonic()
                if now - last_gauges >= 5.0:
                    last_gauges = now
                    self._set_goodput_gauges(stage)
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            if profile_dir and not profiling and profile_steps > 0:
                try:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                    log.info("XLA profile capture started -> %s",
                             profile_dir)
                except Exception:
                    log.exception("DYN_PROFILE_DIR capture failed to start")
                    profile_dir = None
            try:
                outs = self.core.step()
            except Exception as e:  # engine must never die silently
                log.exception("engine step failed")
                outs = [StepOutput(sid, 0, 0.0, FinishReason.ERROR,
                                   error=f"engine step failed: {e}")
                        for sid in list(self.core.by_seq)]
                for sid in list(self.core.by_seq):
                    self.core.cancel(sid)
                self.core._reap_cancelled()
            stage.batch_occupancy.set(str(os.getpid()),
                                      value=self.core.active)
            # goodput gauges: refresh once dispatches have actually been
            # accounted — throttled mid-burst, and ALWAYS at the end of a
            # burst (has_work just drained) so a short request's MFU is
            # visible on /metrics instead of a frozen pre-burst zero
            disp = self.core.goodput.dispatches
            now = time.monotonic()
            if disp != last_disp and (now - last_gauges >= 0.5
                                      or not self.core.has_work):
                last_gauges, last_disp = now, disp
                self._set_goodput_gauges(stage)
            if profiling:
                profile_steps -= 1
                if profile_steps <= 0:
                    try:
                        jax.profiler.stop_trace()
                        log.info("XLA profile capture written to %s",
                                 profile_dir)
                    except Exception:
                        log.exception("stopping XLA profile failed")
                    profiling = False
                    profile_dir = None
            for so in outs:
                try:
                    self._deliver(so)
                except Exception:  # closed loop etc. must not kill the thread
                    log.exception("failed to deliver step output")
            if not outs and not self.core.by_seq:
                # waiting requests that can't be admitted yet: don't busy-spin
                self._wake.wait(timeout=0.02)
                self._wake.clear()
        if profiling:
            # shutdown before DYN_PROFILE_STEPS working iterations: JAX only
            # writes trace files on stop_trace, so finalize the short capture
            try:
                jax.profiler.stop_trace()
                log.info("XLA profile capture written to %s", profile_dir)
            except Exception:
                log.exception("stopping XLA profile failed")

    def _set_goodput_gauges(self, stage) -> None:
        pid = str(os.getpid())
        snap = self.core.goodput.snapshot()
        stage.mfu.set(pid, value=snap["mfu"])
        stage.mbu.set(pid, value=snap["mbu"])
        stage.hbm_gbps.set(pid, value=snap["hbm_gbps"])

    def _ingest_fail(self, seq_id: str, e: Exception) -> None:
        """Engine-thread cleanup of a failed stream inject: release the
        pages (never sealed, never seen) and deliver ONE typed error the
        consumer turns into a local-prefill fallback."""
        self.core.abort_stream_inject(seq_id)
        self._deliver(StepOutput(
            seq_id, 0, 0.0, FinishReason.ERROR,
            error=f"KV stream inject failed: {e}",
            error_stage="kv_ingest", error_reason="ingest_failed"))

    def _deliver(self, so: StepOutput) -> None:
        loop = self._loop
        if loop is None:
            return
        q = self._queues.get(so.seq_id)
        if q is not None:
            loop.call_soon_threadsafe(q.put_nowait, so)

    # ------------------------------------------------------------------
    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        async for out in self._generate(("submit", request), context):
            yield out

    async def prefill_extract(self, request: BackendInput, context: Context
                              ) -> Tuple[np.ndarray, np.ndarray, int, float]:
        """Prefill-worker entry: compute prompt KV + first token on the
        engine thread, await the result. Returns (k, v, token, logprob)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("prefill_extract", context.id,
                         (request, loop, fut)))
        self._wake.set()
        return await fut

    async def swap_weights(self, host_params, new_cfg):
        """Model-mobility hot-swap: post the in-place weight overwrite to
        the engine thread and await its :class:`~dynamo_tpu.fleet.
        mobility.swap.SwapOutcome`. The caller must have drained first
        (``has_work`` False); a typed ``SwapError`` propagates here when
        the sibling's shape signature does not match."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("swap", "", (host_params, new_cfg, loop, fut)))
        self._wake.set()
        return await fut

    async def generate_prefilled(self, request: BackendInput, context: Context,
                                 k, v, first_token: int,
                                 first_logprob: float = 0.0
                                 ) -> AsyncIterator[EngineOutput]:
        """Stream a request whose prompt KV (and first token) arrived from a
        remote prefill worker — enters decode directly."""
        payload = (request, k, v, first_token, first_logprob)
        async for out in self._generate(("inject", payload), context):
            yield out

    # ------------------------------------------------------------------
    # layer-streamed KV ingest (disagg receive path)
    # ------------------------------------------------------------------
    def kv_ingest(self, request: BackendInput, seq_id: str) -> "KvIngest":
        """An asyncio-side handle the :class:`~..llm.kv_transfer.
        KvReceiver` drives to scatter a remote prefill's KV layer-by-
        layer as it arrives. Register it with ``receiver.expect(...,
        ingest=handle)``; consume the entered sequence with
        :meth:`generate_streamed` once the awaited future resolves to
        the handle."""
        return KvIngest(self, request, seq_id)

    async def generate_streamed(self, request: BackendInput,
                                context: Context, ingest: "KvIngest"
                                ) -> AsyncIterator[EngineOutput]:
        """Stream a request whose KV was ingested layer-streamed — the
        inject commands are already queued; this only consumes the output
        queue the ingest registered. Raises
        :class:`~..llm.kv_transfer.RemotePrefillError` (before yielding
        anything) if the engine-side ingest failed, so the caller can
        fall back to local prefill."""
        async for out in self._consume(context.id, context,
                                       ingest_fallback=True):
            yield out

    async def _generate(self, work, context: Context
                        ) -> AsyncIterator[EngineOutput]:
        kind, payload = work
        self._loop = asyncio.get_running_loop()
        seq_id = context.id
        self._queues[seq_id] = asyncio.Queue()
        self._inbox.put((kind, seq_id, payload))
        self._wake.set()
        async for out in self._consume(seq_id, context):
            yield out

    async def _consume(self, seq_id: str, context: Context,
                       ingest_fallback: bool = False
                       ) -> AsyncIterator[EngineOutput]:
        q = self._queues[seq_id]

        async def watch_cancel():
            await context.stopped()
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

        cancel_task = asyncio.ensure_future(watch_cancel())
        try:
            while True:
                so: StepOutput = await q.get()
                if so.finish == FinishReason.ERROR:
                    if ingest_fallback and so.error_stage == "kv_ingest":
                        # torn/failed stream inject: the pages are
                        # released; hand control back so the caller
                        # prefills locally instead of erroring the user
                        from ..llm.kv_transfer import RemotePrefillError
                        raise RemotePrefillError(so.error or "kv ingest "
                                                             "failed")
                    yield EngineOutput(token_ids=[],
                                       finish_reason=FinishReason.ERROR,
                                       error=so.error or "engine error",
                                       error_code=so.error_code,
                                       error_stage=so.error_stage,
                                       error_reason=so.error_reason)
                    return
                ingest_fallback = False   # tokens flowed: no fallback
                yield EngineOutput(
                    token_ids=[so.token],
                    cum_log_prob=so.logprob,
                    logprobs=[{str(so.token): so.token_logprob}],
                    finish_reason=so.finish,
                    # first output only: admission's sealed-prefix restore
                    # length (a resumed stream's re-attach proof)
                    kv_prefix_hit_tokens=so.prefix_hit,
                )
                if so.finish is not None:
                    return
        finally:
            cancel_task.cancel()
            self._queues.pop(seq_id, None)
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

    # ------------------------------------------------------------------
    # placement-driven prefetch (asyncio thread)
    # ------------------------------------------------------------------
    def prefetch_tiers(self, request: BackendInput) -> int:
        """Start h2d upload of the request's matched host/disk-tier
        prefix (and touch draft-model state when spec is on) while it
        waits in the slot-gate queue — admission consumes the staged
        device blocks d2d instead of stalling first prefill on the
        upload. Best-effort: any failure just means the legacy
        synchronous restore path."""
        if getattr(request, "images", None) \
                and not getattr(request, "kv_salt", 0):
            # admission will salt this VLM request's chain with the image
            # digest it computes itself; prefetching under the unsalted
            # chain would stage blocks admission never matches (and evict
            # other requests' genuinely matching staged blocks)
            return 0
        try:
            n = self.core.stage_prefetch(
                request.token_ids,
                lora_id=getattr(request, "kv_salt", 0)
                or getattr(request, "lora_id", 0))
        except Exception:  # noqa: BLE001 - prefetch must never fail a req
            log.exception("h2d prefetch failed; admission restores "
                          "synchronously")
            return 0
        prop = self.core.proposer
        if prop is not None and hasattr(prop, "prefetch"):
            # draft-model weight prefetch hook (spec decode): today's
            # proposers load at init, so this is the seam for lazily-
            # loaded drafts, not a transfer
            try:
                prop.prefetch()
            except Exception:  # noqa: BLE001
                log.debug("draft prefetch hook failed", exc_info=True)
        return n

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._running = False
        self._wake.set()
        self._thread.join(timeout=5)
        # disk-tier spill files are scratch state: flush + unlink them
        # with the engine (next to the metrics-key cleanup) instead of
        # leaking two pool-sized memmaps per engine lifetime
        self.core.close()
        # the engine's per-worker gauge series must die with it: a process
        # that outlives its engine (model remove/re-add, shared-runtime
        # tests) would otherwise export ghost occupancy/MFU forever
        from ..utils.prometheus import stage_metrics

        stage_metrics().clear_worker(str(os.getpid()))

class KvIngest:
    """Asyncio-side handle for one layer-streamed KV injection.

    Created by :meth:`JaxEngine.kv_ingest` before the request parks on
    the prefill queue; the :class:`~..llm.kv_transfer.KvReceiver` drives
    it from the ``kv_receive`` handler: :meth:`begin` validates the wire
    geometry against the engine and registers the output queue,
    :meth:`layer` posts one arrived layer's device scatter to the engine
    thread (enqueued while later layers are still on the wire),
    :meth:`finish` posts the finalize (seal + enter decode + first
    token), :meth:`abort` tears everything down with the pool pages
    released unseen. All methods are cheap posts — no device syncs."""

    def __init__(self, engine: JaxEngine, request: BackendInput,
                 seq_id: str):
        self.engine = engine
        self.request = request
        self.seq_id = seq_id
        self.began = False
        self.finished = False

    def _post(self, kind: str, payload) -> None:
        self.engine._inbox.put((kind, self.seq_id, payload))
        self.engine._wake.set()

    def begin(self, meta: dict) -> bool:
        """Validate the stream's geometry and arm the ingest. False =
        decline (mismatched model geometry / tokens): the receiver falls
        back to buffered assembly, which surfaces the mismatch through
        the legacy import path."""
        m = self.engine.core.cfg.model
        if (int(meta.get("layers", -1)) != m.num_layers
                or int(meta.get("kv_heads", -1)) != m.num_kv_heads
                or int(meta.get("head_dim", -1)) != m.head_dim
                or int(meta.get("tokens", -1))
                != len(self.request.token_ids)):
            log.warning("kv stream geometry %s does not match engine "
                        "(%d layers, %d kv heads, %d head_dim); buffering",
                        {k: meta.get(k) for k in
                         ("layers", "kv_heads", "head_dim", "tokens")},
                        m.num_layers, m.num_kv_heads, m.head_dim)
            return False
        self.engine._loop = asyncio.get_running_loop()
        self.engine._queues[self.seq_id] = asyncio.Queue()
        self._post("ingest_begin", self.request)
        self.began = True
        return True

    def layer(self, layer: int, k: np.ndarray, v: np.ndarray) -> None:
        self._post("ingest_layer", (layer, k, v))

    def finish(self, first_token: int, first_logprob: float) -> None:
        self.finished = True
        self._post("ingest_finish", (int(first_token),
                                     float(first_logprob)))

    def abort(self) -> None:
        """Idempotent, and a no-op once :meth:`finish` posted: the waiter
        consumes the finished sequence's queue, so a late abandon (the
        ``await_remote_kv`` finally) must not tear it down. For an
        UNfinished ingest the abort posts through the same FIFO inbox the
        begin rode, so a local-prefill resubmit of the same seq_id is
        processed strictly after the pool pages were released."""
        if self.began and not self.finished:
            self._post("ingest_abort", None)
            self.engine._queues.pop(self.seq_id, None)
            self.began = False

    def discard(self) -> None:
        """The waiter gave up AFTER the ingest finished (its sequence is
        already decoding) and will never consume the outputs: cancel the
        orphaned sequence and drop its queue so the slot and the dict
        entry don't leak until max_tokens."""
        if self.finished:
            self._post("cancel", None)
            self.engine._queues.pop(self.seq_id, None)
            self.finished = False
            self.began = False
        else:
            self.abort()
