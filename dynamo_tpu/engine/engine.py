"""The in-tree JAX engine: continuous batching over a paged KV pool.

Architecture (TPU-first):
- All device work happens in exactly two jitted programs per (bucket) shape:
  ``prefill_mid`` (chunk forward, no LM head) and ``prefill_last``/``decode``
  (forward + sample). Shapes are bucketed so XLA compiles a handful of
  programs once and replays them forever; KV pools are donated so updates are
  in-place in HBM.
- A synchronous :class:`EngineCore` owns all mutable state (slots, page
  tables, sampling vectors) and is driven from one engine thread — the same
  single-owner actor discipline the reference uses for its schedulers.
- :class:`JaxEngine` is the asyncio facade implementing the AsyncEngine
  contract (BackendInput -> stream of EngineOutput).

Reference capability: the role vLLM/TRT-LLM play behind the reference's
adapters (continuous batching, paged KV, streaming detached tokens), per
SURVEY §7 step 3.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import queue as thread_queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..llm.model_card import ModelDeploymentCard
from ..llm.protocols.common import BackendInput, EngineOutput, FinishReason
from ..models import llama
from ..parallel.mesh import AXIS_TP, tp_mesh
from ..runtime.engine import AsyncEngine, Context
from .cache import OutOfPages, PagePool
from .sampling import STATIC_K, SamplingState, sample

log = logging.getLogger("dynamo_tpu.engine")


def _buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


@dataclass
class JaxEngineConfig:
    model: llama.LlamaConfig
    tp: int = 1
    page_size: int = 64
    max_batch: int = 8
    max_context: int = 2048
    prefill_chunk: int = 512
    num_pages: Optional[int] = None     # default: max_batch*max_context worth
    params_path: Optional[str] = None   # safetensors dir; None => random init
    seed: int = 0
    preset: Optional[str] = None

    @classmethod
    def from_card(cls, card: ModelDeploymentCard, tensor_parallel: int = 1,
                  **extra) -> "JaxEngineConfig":
        if card.model_config:
            mcfg = llama.LlamaConfig.from_hf_config(card.model_config)
        elif extra.get("preset"):
            mcfg = llama.preset(extra["preset"])
        else:
            mcfg = llama.preset("tiny-byte")
        kw = dict(
            model=mcfg,
            tp=tensor_parallel,
            page_size=card.kv_block_size,
            params_path=card.path,
        )
        for k in ("max_batch", "max_context", "prefill_chunk", "num_pages",
                  "seed", "preset"):
            if k in extra:
                kw[k] = extra[k]
        cfg = cls(**kw)
        cfg.max_context = min(cfg.max_context, card.context_length)
        return cfg


@dataclass
class _Slot:
    seq_id: str
    request: BackendInput
    prompt: List[int]
    prefill_done: int = 0           # prompt tokens already in cache
    generated: int = 0
    last_token: int = 0
    cum_logprob: float = 0.0
    cancelled: bool = False


@dataclass
class StepOutput:
    seq_id: str
    token: int
    logprob: float
    finish: Optional[FinishReason] = None
    prompt_tokens: int = 0


class EngineCore:
    """Synchronous continuous-batching core. Single-threaded by contract."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.cfg = cfg
        m = cfg.model
        llama.validate_tp(m, cfg.tp)
        self.mesh = tp_mesh(cfg.tp, devices)
        self.page_size = cfg.page_size
        self.max_pages_per_seq = cfg.max_context // cfg.page_size
        num_pages = cfg.num_pages or (cfg.max_batch * self.max_pages_per_seq + 1)
        self.pool = PagePool(num_pages, cfg.page_size)

        # --- params ---------------------------------------------------
        specs = llama.param_specs(m, cfg.tp)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        if cfg.params_path and _has_safetensors(cfg.params_path):
            from .loader import load_llama_params
            self.params = load_llama_params(cfg.params_path, m, shardings)
        else:
            params = llama.init_params(m, jax.random.PRNGKey(cfg.seed))
            self.params = jax.tree.map(
                lambda a, s: jax.device_put(a, s), params, shardings)

        # --- KV pools -------------------------------------------------
        kv_spec = llama.kv_cache_spec(m, cfg.tp)
        self.kv_sharding = NamedSharding(self.mesh, kv_spec)
        pool_tokens = num_pages * cfg.page_size
        self.k_pool = jax.device_put(
            jnp.zeros((m.num_layers, pool_tokens, m.num_kv_heads, m.head_dim),
                      m.dtype), self.kv_sharding)
        self.v_pool = jax.device_put(
            jnp.zeros_like(self.k_pool), self.kv_sharding)

        # --- slots / scheduler ---------------------------------------
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_batch
        self.by_seq: Dict[str, _Slot] = {}
        self.waiting: Deque[Tuple[str, BackendInput]] = collections.deque()
        self.sampling = SamplingState.host_init(cfg.max_batch)
        self.sampling.key = jax.device_put(self.sampling.key)

        # --- compiled programs ---------------------------------------
        self.s_buckets = _buckets(min(256, cfg.max_context), cfg.max_context)
        self.c_buckets = _buckets(min(32, cfg.prefill_chunk), cfg.prefill_chunk)
        self._decode_fns: Dict[int, Any] = {}
        self._prefill_mid_fns: Dict[Tuple[int, int], Any] = {}
        self._prefill_last_fns: Dict[Tuple[int, int], Any] = {}
        self._decoded_last = False   # prefill/decode alternation flag

    # ------------------------------------------------------------------
    # compiled program builders
    # ------------------------------------------------------------------
    def _decode_fn(self, S: int):
        if S not in self._decode_fns:
            cfg = self.cfg

            @partial(jax.jit, donate_argnums=(3, 4))
            def step(params, tokens, positions, k_pool, v_pool, write_idx,
                     read_idx, read_pos, read_valid, temp, top_p, top_k, key):
                logits, k_pool, v_pool = llama.forward(
                    params, cfg.model, tokens[:, None], positions[:, None],
                    k_pool, v_pool, write_idx[:, None],
                    read_idx, read_pos, read_valid)
                tok, logp, new_key = sample(
                    logits[:, 0], temp, top_p, top_k, key)
                return tok, logp, new_key, k_pool, v_pool

            self._decode_fns[S] = step
        return self._decode_fns[S]

    def _prefill_fns(self, C: int, S: int, last: bool):
        cache = self._prefill_last_fns if last else self._prefill_mid_fns
        if (C, S) not in cache:
            cfg = self.cfg

            if last:
                @partial(jax.jit, donate_argnums=(3, 4), static_argnums=(13,))
                def fn(params, tokens, positions, k_pool, v_pool, write_idx,
                       read_idx, read_pos, read_valid, temp, top_p, top_k,
                       key, last_i):
                    logits, k_pool, v_pool = llama.forward(
                        params, cfg.model, tokens, positions, k_pool, v_pool,
                        write_idx, read_idx, read_pos, read_valid)
                    tok, logp, new_key = sample(
                        logits[:, last_i], temp, top_p, top_k, key)
                    return tok, logp, new_key, k_pool, v_pool
            else:
                @partial(jax.jit, donate_argnums=(3, 4))
                def fn(params, tokens, positions, k_pool, v_pool, write_idx,
                       read_idx, read_pos, read_valid):
                    # mid-prefill chunks skip the LM head entirely
                    _, k_pool, v_pool = llama.forward(
                        params, cfg.model, tokens, positions, k_pool, v_pool,
                        write_idx, read_idx, read_pos, read_valid)
                    return k_pool, v_pool
            cache[(C, S)] = fn
        return cache[(C, S)]

    @staticmethod
    def _bucket(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    # ------------------------------------------------------------------
    # public API (engine thread)
    # ------------------------------------------------------------------
    def submit(self, seq_id: str, request: BackendInput) -> None:
        self.waiting.append((seq_id, request))

    def cancel(self, seq_id: str) -> None:
        slot = self.by_seq.get(seq_id)
        if slot is not None:
            slot.cancelled = True
        else:
            self.waiting = collections.deque(
                (s, r) for s, r in self.waiting if s != seq_id)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.by_seq)

    @property
    def active(self) -> int:
        return len(self.by_seq)

    def utilization(self) -> Dict[str, float]:
        total = self.pool.num_pages - 1
        return {
            "request_active_slots": float(self.active),
            "request_total_slots": float(self.cfg.max_batch),
            "kv_active_blocks": float(total - self.pool.free_pages),
            "kv_total_blocks": float(total),
            "num_requests_waiting": float(len(self.waiting)),
        }

    # ------------------------------------------------------------------
    def step(self) -> List[StepOutput]:
        """Run one engine iteration: at most ONE prefill chunk OR one decode
        batch per call, alternating when both kinds of work exist so ongoing
        decodes keep streaming while a long prompt prefills chunk by chunk."""
        out: List[StepOutput] = []
        out.extend(self._reap_cancelled())
        midfill = [(i, s) for i, s in enumerate(self.slots)
                   if s is not None and s.prefill_done < len(s.prompt)]
        decodable = any(s is not None and s.prefill_done >= len(s.prompt)
                        for s in self.slots)
        want_prefill = bool(midfill) or (self.waiting and None in self.slots)
        if want_prefill and (not decodable or not self._decoded_last):
            if midfill:
                i, slot = midfill[0]
                self._prefill_chunk(i, slot, out)
                self._decoded_last = True  # alternate back to decode
                return out
            if self._admit_and_prefill(out):
                self._decoded_last = True
                return out
        if decodable:
            out.extend(self._decode_step())
            self._decoded_last = False
        return out

    # ------------------------------------------------------------------
    def _reap_cancelled(self) -> List[StepOutput]:
        outs = []
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.cancelled:
                outs.append(StepOutput(slot.seq_id, slot.last_token, 0.0,
                                       FinishReason.CANCELLED))
                self._free_slot(i)
        return outs

    def _free_slot(self, i: int) -> None:
        slot = self.slots[i]
        if slot is None:
            return
        self.pool.release(slot.seq_id)
        self.by_seq.pop(slot.seq_id, None)
        self.slots[i] = None

    def _admit_and_prefill(self, out: List[StepOutput]) -> bool:
        """Admit the head-of-line request and run ONE prefill chunk (possibly
        finishing the prompt). Returns True if an XLA step ran."""
        seq_id, req = self.waiting[0]
        prompt = list(req.token_ids)
        if len(prompt) >= self.cfg.max_context:
            self.waiting.popleft()
            out.append(StepOutput(seq_id, 0, 0.0, FinishReason.ERROR))
            return False
        if self.pool.pages_needed(len(prompt) + 1) > self.pool.num_pages - 1:
            # can NEVER fit, even with an empty pool: reject, don't starve
            self.waiting.popleft()
            out.append(StepOutput(seq_id, 0, 0.0, FinishReason.ERROR))
            return False
        if not self.pool.can_admit(len(prompt) + 1):
            return False  # no KV space yet; decode will free some eventually
        self.waiting.popleft()
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, req, prompt)
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self.pool.create(seq_id)
        s = self.sampling
        s.temperature[slot_idx] = float(req.sampling.temperature or 0.0)
        s.top_p[slot_idx] = float(req.sampling.top_p
                                  if req.sampling.top_p is not None else 1.0)
        s.top_k[slot_idx] = int(min(req.sampling.top_k or 0, STATIC_K))
        if req.sampling.seed is not None:
            s.key = s.key.at[slot_idx].set(
                jax.random.key(req.sampling.seed))
        return self._prefill_chunk(slot_idx, slot, out)

    def _prefill_chunk(self, slot_idx: int, slot: _Slot,
                       out: List[StepOutput]) -> bool:
        prompt = slot.prompt
        start = slot.prefill_done
        count = min(len(prompt) - start, self.cfg.prefill_chunk)
        is_last = start + count == len(prompt)
        C = self._bucket(count, self.c_buckets)
        S = self._bucket(start + count, self.s_buckets)

        try:
            self.pool.extend(slot.seq_id, prompt[start:start + count])
        except OutOfPages:
            out.append(StepOutput(slot.seq_id, 0, 0.0, FinishReason.ERROR))
            self._free_slot(slot_idx)
            return False

        tokens = np.zeros((1, C), np.int32)
        tokens[0, :count] = prompt[start:start + count]
        positions = np.zeros((1, C), np.int32)
        positions[0, :count] = np.arange(start, start + count)
        write_idx = np.zeros((1, C), np.int32)  # pad writes -> scratch page 0
        write_idx[0, :count] = self.pool.write_slots(slot.seq_id, start, count)
        r_slots, r_pos, r_valid = self.pool.read_slots(
            slot.seq_id, start + count, S)
        args = (self.params, tokens, positions, self.k_pool, self.v_pool,
                write_idx, r_slots[None], r_pos[None], r_valid[None])
        if is_last:
            s = self.sampling
            fn = self._prefill_fns(C, S, last=True)
            tok, logp, new_key, self.k_pool, self.v_pool = fn(
                *args, s.temperature[slot_idx:slot_idx + 1],
                s.top_p[slot_idx:slot_idx + 1],
                s.top_k[slot_idx:slot_idx + 1],
                s.key[slot_idx:slot_idx + 1], count - 1)
            s.key = s.key.at[slot_idx].set(new_key[0])
            slot.prefill_done += count
            t = int(tok[0])
            try:
                self._append_generated(slot, t)
            except OutOfPages:
                out.append(StepOutput(slot.seq_id, t, float(logp[0]),
                                      FinishReason.ERROR))
                self._free_slot(slot_idx)
                return True
            slot.cum_logprob += float(logp[0])
            fin = self._finish_reason(slot, t)
            out.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin,
                                  prompt_tokens=len(prompt)))
            if fin is not None:
                self._free_slot(slot_idx)
        else:
            fn = self._prefill_fns(C, S, last=False)
            self.k_pool, self.v_pool = fn(*args)
            slot.prefill_done += count
        return True

    def _append_generated(self, slot: _Slot, token: int) -> None:
        slot.generated += 1
        slot.last_token = token
        self.pool.extend(slot.seq_id, [token])

    def _finish_reason(self, slot: _Slot, token: int) -> Optional[FinishReason]:
        req = slot.request
        if not req.stop.ignore_eos:
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            if token in eos and slot.generated >= (req.stop.min_tokens or 0):
                return FinishReason.EOS
        if req.stop.max_tokens and slot.generated >= req.stop.max_tokens:
            return FinishReason.LENGTH
        if len(slot.prompt) + slot.generated >= self.cfg.max_context:
            return FinishReason.LENGTH
        return None

    # ------------------------------------------------------------------
    def _decode_step(self) -> List[StepOutput]:
        B = self.cfg.max_batch
        # only fully-prefilled slots decode; mid-prefill slots keep their
        # lanes masked (scratch writes) until their prompt is in cache
        active = [(i, s) for i, s in enumerate(self.slots)
                  if s is not None and s.prefill_done >= len(s.prompt)]
        if not active:
            return []
        max_len = max(len(s.prompt) + s.generated for _, s in active)
        S = self._bucket(max_len, self.s_buckets)

        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        write_idx = np.zeros(B, np.int32)   # inactive lanes -> scratch page 0
        read_idx = np.zeros((B, S), np.int32)
        read_pos = np.zeros((B, S), np.int32)
        read_valid = np.zeros((B, S), bool)

        # The input token this step is slot.last_token at position n-1 (its KV
        # was accounted by _append_generated but not yet written to the pool —
        # the write happens inside this step's forward).
        for i, slot in active:
            n = len(slot.prompt) + slot.generated
            tokens[i] = slot.last_token
            positions[i] = n - 1
            write_idx[i] = self.pool.write_slots(slot.seq_id, n - 1, 1)[0]
            r_s, r_p, r_v = self.pool.read_slots(slot.seq_id, n, S)
            read_idx[i], read_pos[i], read_valid[i] = r_s, r_p, r_v

        s = self.sampling
        fn = self._decode_fn(S)
        tok, logp, new_key, self.k_pool, self.v_pool = fn(
            self.params, tokens, positions, self.k_pool, self.v_pool,
            write_idx, read_idx, read_pos, read_valid,
            s.temperature, s.top_p, s.top_k, s.key)
        s.key = new_key
        tok_np = np.asarray(tok)
        logp_np = np.asarray(logp)

        outs: List[StepOutput] = []
        for i, slot in active:
            t = int(tok_np[i])
            try:
                self._append_generated(slot, t)
            except OutOfPages:
                # capacity failure is an ERROR, not a length finish — the
                # client must be able to tell truncation from completion
                outs.append(StepOutput(slot.seq_id, t, slot.cum_logprob,
                                       FinishReason.ERROR))
                self._free_slot(i)
                continue
            slot.cum_logprob += float(logp_np[i])
            fin = self._finish_reason(slot, t)
            outs.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin))
            if fin is not None:
                self._free_slot(i)
        return outs


def _has_safetensors(path: str) -> bool:
    import glob
    import os

    return bool(glob.glob(os.path.join(path, "*.safetensors")))


# ---------------------------------------------------------------------------
# Async facade
# ---------------------------------------------------------------------------

class JaxEngine(AsyncEngine[BackendInput, EngineOutput]):
    """AsyncEngine facade: one background engine thread runs EngineCore."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.core = EngineCore(cfg, devices)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: Dict[str, asyncio.Queue] = {}
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._run, name="jax-engine",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while self._running:
            moved = False
            while True:
                try:
                    kind, seq_id, payload = self._inbox.get_nowait()
                except thread_queue.Empty:
                    break
                moved = True
                if kind == "submit":
                    self.core.submit(seq_id, payload)
                elif kind == "cancel":
                    self.core.cancel(seq_id)
            if not self.core.has_work:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                outs = self.core.step()
            except Exception:  # engine must never die silently
                log.exception("engine step failed")
                outs = [StepOutput(sid, 0, 0.0, FinishReason.ERROR)
                        for sid in list(self.core.by_seq)]
                for sid in list(self.core.by_seq):
                    self.core.cancel(sid)
                self.core._reap_cancelled()
            for so in outs:
                try:
                    self._deliver(so)
                except Exception:  # closed loop etc. must not kill the thread
                    log.exception("failed to deliver step output")
            if not outs and not self.core.by_seq:
                # waiting requests that can't be admitted yet: don't busy-spin
                self._wake.wait(timeout=0.02)
                self._wake.clear()

    def _deliver(self, so: StepOutput) -> None:
        loop = self._loop
        if loop is None:
            return
        q = self._queues.get(so.seq_id)
        if q is not None:
            loop.call_soon_threadsafe(q.put_nowait, so)

    # ------------------------------------------------------------------
    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        self._loop = asyncio.get_running_loop()
        seq_id = context.id
        q: asyncio.Queue = asyncio.Queue()
        self._queues[seq_id] = q
        self._inbox.put(("submit", seq_id, request))
        self._wake.set()

        async def watch_cancel():
            await context.stopped()
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

        cancel_task = asyncio.ensure_future(watch_cancel())
        try:
            while True:
                so: StepOutput = await q.get()
                if so.finish == FinishReason.ERROR:
                    yield EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR)
                    return
                yield EngineOutput(
                    token_ids=[so.token],
                    cum_log_prob=so.logprob,
                    finish_reason=so.finish,
                )
                if so.finish is not None:
                    return
        finally:
            cancel_task.cancel()
            self._queues.pop(seq_id, None)
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._running = False
        self._wake.set()
        self._thread.join(timeout=5)
