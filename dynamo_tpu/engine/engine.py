"""The in-tree JAX engine: continuous batching over a paged KV pool.

Architecture (TPU-first):
- All device work happens in exactly two jitted programs per (bucket) shape:
  ``prefill_mid`` (chunk forward, no LM head) and ``prefill_last``/``decode``
  (forward + sample). Shapes are bucketed so XLA compiles a handful of
  programs once and replays them forever; KV pools are donated so updates are
  in-place in HBM.
- A synchronous :class:`EngineCore` owns all mutable state (slots, page
  tables, sampling vectors) and is driven from one engine thread — the same
  single-owner actor discipline the reference uses for its schedulers.
- :class:`JaxEngine` is the asyncio facade implementing the AsyncEngine
  contract (BackendInput -> stream of EngineOutput).

Reference capability: the role vLLM/TRT-LLM play behind the reference's
adapters (continuous batching, paged KV, streaming detached tokens), per
SURVEY §7 step 3.
"""

from __future__ import annotations

import asyncio
import collections
import logging
import queue as thread_queue
import threading
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, AsyncIterator, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..llm.model_card import ModelDeploymentCard
from ..llm.protocols.common import BackendInput, EngineOutput, FinishReason
from ..models import llama
from ..parallel.mesh import AXIS_TP, tp_mesh
from ..runtime.engine import AsyncEngine, Context
from .cache import OutOfPages, PagePool
from .sampling import STATIC_K, SamplingState, sample

log = logging.getLogger("dynamo_tpu.engine")


def _buckets(lo: int, hi: int) -> List[int]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= 2
    out.append(hi)
    return out


@dataclass
class JaxEngineConfig:
    model: llama.LlamaConfig
    tp: int = 1
    page_size: int = 64
    max_batch: int = 8
    max_context: int = 2048
    prefill_chunk: int = 512
    num_pages: Optional[int] = None     # default: max_batch*max_context worth
    decode_steps: int = 8               # decode iterations per XLA dispatch
    params_path: Optional[str] = None   # safetensors dir; None => random init
    seed: int = 0
    preset: Optional[str] = None
    # attention backend: "auto" => Pallas kernels on TPU, XLA dense elsewhere.
    # Explicit values: "pallas" | "xla".
    attn_impl: str = "auto"
    # KV block manager (SURVEY §2.4): prefix reuse + tiered offload
    enable_prefix_reuse: bool = True
    host_cache_blocks: int = 0          # host-DRAM KV tier capacity (0 = off)
    disk_cache_blocks: int = 0          # mmap spill tier capacity (0 = off)
    disk_cache_path: Optional[str] = None

    @classmethod
    def from_card(cls, card: ModelDeploymentCard, tensor_parallel: int = 1,
                  **extra) -> "JaxEngineConfig":
        if card.model_config:
            mcfg = llama.LlamaConfig.from_hf_config(card.model_config)
        elif extra.get("preset"):
            mcfg = llama.preset(extra["preset"])
        else:
            mcfg = llama.preset("tiny-byte")
        kw = dict(
            model=mcfg,
            tp=tensor_parallel,
            page_size=card.kv_block_size,
            params_path=card.path,
        )
        for k in ("max_batch", "max_context", "prefill_chunk", "num_pages",
                  "decode_steps", "seed", "preset", "attn_impl",
                  "enable_prefix_reuse", "host_cache_blocks",
                  "disk_cache_blocks", "disk_cache_path"):
            if k in extra:
                kw[k] = extra[k]
        cfg = cls(**kw)
        cfg.max_context = min(cfg.max_context, card.context_length)
        return cfg


@dataclass
class _Slot:
    seq_id: str
    request: BackendInput
    prompt: List[int]
    prefill_done: int = 0           # prompt tokens already in cache
    generated: int = 0
    last_token: int = 0
    cum_logprob: float = 0.0
    cancelled: bool = False


@dataclass
class StepOutput:
    seq_id: str
    token: int
    logprob: float
    finish: Optional[FinishReason] = None
    prompt_tokens: int = 0


class EngineCore:
    """Synchronous continuous-batching core. Single-threaded by contract."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.cfg = cfg
        m = cfg.model
        llama.validate_tp(m, cfg.tp)
        self.mesh = tp_mesh(cfg.tp, devices)
        self.page_size = cfg.page_size
        # every sequence may overshoot up to decode_steps speculative tokens
        self._spec_pad = -(-cfg.decode_steps // cfg.page_size) * cfg.page_size
        # ceil: a seq at max_context with the speculative pad must always fit
        self.max_pages_per_seq = -(-(cfg.max_context + self._spec_pad)
                                   // cfg.page_size)
        num_pages = cfg.num_pages or (cfg.max_batch * self.max_pages_per_seq + 1)
        self.pool = PagePool(num_pages, cfg.page_size)

        # --- params ---------------------------------------------------
        specs = llama.param_specs(m, cfg.tp)
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))
        if cfg.params_path and _has_safetensors(cfg.params_path):
            from .loader import load_llama_params
            self.params = load_llama_params(cfg.params_path, m, shardings)
        else:
            params = llama.init_params(m, jax.random.PRNGKey(cfg.seed))
            self.params = jax.tree.map(
                lambda a, s: jax.device_put(a, s), params, shardings)

        # --- attention backend ---------------------------------------
        impl = cfg.attn_impl
        if impl == "auto":
            import os
            impl = os.environ.get("DYNAMO_TPU_ATTN", "auto")
        if impl == "auto":
            # Pallas kernels on TPU; they run per-shard, so tp>1 needs the
            # shard_map wrap (ring-attention work) — fall back to XLA there.
            impl = ("pallas" if jax.default_backend() == "tpu"
                    and cfg.tp == 1 else "xla")
        if impl not in ("pallas", "xla"):
            raise ValueError(f"attn_impl must be auto|pallas|xla, got {impl!r}")
        if impl == "pallas" and cfg.tp > 1:
            raise ValueError("attn_impl='pallas' requires tp=1 (the kernels "
                             "run per-shard; tp>1 uses the XLA path)")
        self.attn_impl = impl

        # --- KV pools (page-major: [L, n_pages, Hkv, page, Dh]) -------
        kv_spec = llama.kv_cache_spec(m, cfg.tp)
        self.kv_sharding = NamedSharding(self.mesh, kv_spec)
        self.k_pool = jax.device_put(
            jnp.zeros((m.num_layers, num_pages, m.num_kv_heads,
                       cfg.page_size, m.head_dim), m.dtype), self.kv_sharding)
        self.v_pool = jax.device_put(
            jnp.zeros_like(self.k_pool), self.kv_sharding)

        # --- KV block manager: tiered offload + prefix reuse ----------
        from ..llm.kvbm.transfer import CopyStream
        self.copy_stream = CopyStream()
        self.tiered = None
        if cfg.host_cache_blocks > 0:
            from ..llm.kvbm.tiers import (DiskKvTier, HostKvTier,
                                          TieredKvCache)
            blk_shape = (m.num_layers, m.num_kv_heads, cfg.page_size,
                         m.head_dim)
            # ml_dtypes gives numpy a real bfloat16, so the host tier stores
            # KV at device precision
            np_dtype = np.asarray(jnp.zeros((), m.dtype)).dtype
            host = HostKvTier(cfg.host_cache_blocks, blk_shape, np_dtype)
            disk = None
            if cfg.disk_cache_blocks > 0:
                path = cfg.disk_cache_path or "/tmp/dynamo_tpu_kv_spill"
                disk = DiskKvTier(cfg.disk_cache_blocks, blk_shape,
                                  np_dtype, path)
            self.tiered = TieredKvCache(host, disk)
        self._evict_buf: List[Tuple[int, int]] = []
        self.pool.on_block_evicted = self._offload_evicted

        # prefix-cache accounting (feeds ForwardPassMetrics + disagg router)
        self.last_prefix_hit = 0
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0

        # --- slots / scheduler ---------------------------------------
        self.slots: List[Optional[_Slot]] = [None] * cfg.max_batch
        self.by_seq: Dict[str, _Slot] = {}
        self.waiting: Deque[Tuple[str, BackendInput]] = collections.deque()
        self.sampling = SamplingState.host_init(cfg.max_batch)
        # commit to a canonical replicated sharding: program cache keys
        # include argument shardings, so an uncommitted key would recompile
        # every bucket once more after the first on-device key update
        self._rep_sharding = NamedSharding(self.mesh, P())
        self.sampling.key = jax.device_put(self.sampling.key,
                                           self._rep_sharding)

        # --- compiled programs ---------------------------------------
        # decode reads are indexed through page tables of width S/page_size:
        # every S bucket MUST be a page multiple or the final partial page
        # would clamp out of bounds and silently read/write the wrong page
        pg = cfg.page_size
        raw = _buckets(min(256, cfg.max_context), cfg.max_context + self._spec_pad)
        self.s_buckets = sorted({-(-b // pg) * pg for b in raw})
        self.c_buckets = _buckets(min(32, cfg.prefill_chunk), cfg.prefill_chunk)
        self._decode_fns: Dict[int, Any] = {}
        self._prefill_mid_fns: Dict[Tuple[int, int], Any] = {}
        self._prefill_last_fns: Dict[Tuple[int, int], Any] = {}

    # ------------------------------------------------------------------
    # compiled program builders
    # ------------------------------------------------------------------
    def _decode_fn(self, S: int):
        """Multi-step decode: N autoregressive iterations inside one jitted
        lax.scan — indices computed on device from page tables, sampled token
        fed straight back in. One host round-trip per N tokens (the round-trip
        is the latency floor on TPU; this amortizes it N-fold). Lanes that hit
        a finish condition mid-scan overshoot harmlessly into their own
        pre-allocated pages; the host trims afterwards."""
        if S not in self._decode_fns:
            cfg = self.cfg
            N = cfg.decode_steps
            impl = self.attn_impl
            rep, kv = self._rep_sharding, self.kv_sharding

            # out_shardings pinned so the pools keep the canonical kv
            # sharding across programs: without this, XLA may emit an
            # equivalent-but-differently-spec'd sharding and every *other*
            # bucket program compiles a second variant against it
            @partial(jax.jit, donate_argnums=(2, 3),
                     out_shardings=(rep, rep, rep, kv, kv))
            def step(params, tokens, k_pool, v_pool, page_tables, lengths,
                     temp, top_p, top_k, key):
                def one(carry, _):
                    tokens, lengths, k_pool, v_pool, key = carry
                    logits, k_pool, v_pool = llama.forward_decode(
                        params, cfg.model, tokens, k_pool, v_pool,
                        page_tables, lengths, attn_impl=impl)
                    tok, logp, new_key = sample(
                        logits[:, 0], temp, top_p, top_k, key)
                    return ((tok, lengths + 1, k_pool, v_pool, new_key),
                            (tok, logp))

                carry = (tokens, lengths, k_pool, v_pool, key)
                (tok, lengths, k_pool, v_pool, key), (toks, logps) = \
                    jax.lax.scan(one, carry, None, length=N)
                return toks, logps, key, k_pool, v_pool

            self._decode_fns[S] = step
        return self._decode_fns[S]

    def _prefill_fns(self, C: int, S: int, last: bool):
        cache = self._prefill_last_fns if last else self._prefill_mid_fns
        if (C, S) not in cache:
            cfg = self.cfg
            impl = "flash" if self.attn_impl == "pallas" else "xla"
            rep, kv = self._rep_sharding, self.kv_sharding

            if last:
                @partial(jax.jit, donate_argnums=(3, 4), static_argnums=(13,),
                         out_shardings=(rep, rep, rep, kv, kv))
                def fn(params, tokens, positions, k_pool, v_pool, write_idx,
                       read_idx, read_pos, read_valid, temp, top_p, top_k,
                       key, last_i):
                    logits, k_pool, v_pool = llama.forward(
                        params, cfg.model, tokens, positions, k_pool, v_pool,
                        write_idx, read_idx, read_pos, read_valid,
                        attn_impl=impl)
                    tok, logp, new_key = sample(
                        logits[:, last_i], temp, top_p, top_k, key)
                    return tok, logp, new_key, k_pool, v_pool
            else:
                @partial(jax.jit, donate_argnums=(3, 4),
                         out_shardings=(kv, kv))
                def fn(params, tokens, positions, k_pool, v_pool, write_idx,
                       read_idx, read_pos, read_valid):
                    # mid-prefill chunks skip the LM head entirely
                    _, k_pool, v_pool = llama.forward(
                        params, cfg.model, tokens, positions, k_pool, v_pool,
                        write_idx, read_idx, read_pos, read_valid,
                        attn_impl=impl)
                    return k_pool, v_pool
            cache[(C, S)] = fn
        return cache[(C, S)]

    @staticmethod
    def _bucket(n: int, buckets: List[int]) -> int:
        for b in buckets:
            if n <= b:
                return b
        return buckets[-1]

    # ------------------------------------------------------------------
    # public API (engine thread)
    # ------------------------------------------------------------------
    def submit(self, seq_id: str, request: BackendInput) -> None:
        self.waiting.append((seq_id, request))

    def cancel(self, seq_id: str) -> None:
        slot = self.by_seq.get(seq_id)
        if slot is not None:
            slot.cancelled = True
        else:
            self.waiting = collections.deque(
                (s, r) for s, r in self.waiting if s != seq_id)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.by_seq)

    @property
    def active(self) -> int:
        return len(self.by_seq)

    def utilization(self) -> Dict[str, float]:
        total = self.pool.num_pages - 1
        hit_rate = (self.prefix_hit_tokens / self.prefix_query_tokens
                    if self.prefix_query_tokens else 0.0)
        return {
            "request_active_slots": float(self.active),
            "request_total_slots": float(self.cfg.max_batch),
            "kv_active_blocks": float(total - self.pool.free_pages),
            "kv_total_blocks": float(total),
            "num_requests_waiting": float(len(self.waiting)),
            "gpu_prefix_cache_hit_rate": hit_rate,
        }

    # ------------------------------------------------------------------
    # KV export/import (disaggregated prefill -> decode transfer)
    # ------------------------------------------------------------------
    def extract_kv(self, seq_id: str, layer: Optional[int] = None,
                   count: Optional[int] = None):
        """Gather a sequence's KV out of the pool -> host numpy arrays.
        With ``layer`` set, returns that layer only ([T,Hkv,Dh] k, v) for
        layer-pipelined transfer; otherwise all layers ([L,T,Hkv,Dh]).
        ``count`` limits extraction to the first N tokens (e.g. the prompt)."""
        sc = self.pool.seqs[seq_id]
        n = sc.num_tokens if count is None else min(count, sc.num_tokens)
        slots = jnp.asarray(self.pool.write_slots(seq_id, 0, n))
        if layer is None:
            k = np.asarray(self._kv_gather(self.k_pool, slots))
            v = np.asarray(self._kv_gather(self.v_pool, slots))
        else:
            k = np.asarray(self._kv_gather_layer(self.k_pool, slots, layer))
            v = np.asarray(self._kv_gather_layer(self.v_pool, slots, layer))
        return k, v

    def _kv_gather(self, pool, slots):
        # pool [L, n_pages, Hkv, page, Dh], flat slots [n] -> [L, n, Hkv, Dh].
        # (advanced indices around the Hkv slice land in front: [n, L, ...])
        if not hasattr(self, "_gather_fn"):
            pg = self.page_size
            self._gather_fn = jax.jit(
                lambda p, s: jnp.transpose(p[:, s // pg, :, s % pg],
                                           (1, 0, 2, 3)))
        return self._gather_fn(pool, slots)

    def _kv_gather_layer(self, pool, slots, layer: int):
        if not hasattr(self, "_gather_layer_fn"):
            pg = self.page_size
            self._gather_layer_fn = jax.jit(
                lambda p, s, l: p[l][s // pg, :, s % pg], static_argnums=2)
        return self._gather_layer_fn(pool, slots, layer)

    def prefill_extract(self, seq_id: str, request: BackendInput
                        ) -> Tuple[np.ndarray, np.ndarray, int, float]:
        """Prefill-worker path: run the full (chunked) prefill for a request,
        sample its first token, gather the prompt KV to host, release the
        slot. Returns (k [L,T,Hkv,Dh], v, first_token, first_logprob).
        The caller owns queue/transfer; this runs on the engine thread."""
        from dataclasses import replace

        prompt = list(request.token_ids)
        if len(prompt) + 1 >= self.cfg.max_context:
            raise ValueError(f"prompt of {len(prompt)} exceeds max_context")
        if None not in self.slots:
            raise RuntimeError("no free slot for prefill job")
        # the first sampled token must never finish the slot (we need the KV
        # before release) — neutralize stop conditions for the prefill pass
        req = replace(request, stop=replace(
            request.stop, max_tokens=None, stop_token_ids=[],
            min_tokens=None, ignore_eos=True))
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, req, prompt)
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self.pool.create(seq_id)
        self._load_sampling(slot_idx, req)
        out: List[StepOutput] = []
        try:
            while slot.prefill_done < len(prompt):
                self._prefill_chunk(slot_idx, slot, out)
                if out and out[-1].finish == FinishReason.ERROR:
                    raise OutOfPages("prefill ran out of KV pages")
            so = out[-1]
            k, v = self.extract_kv(seq_id, count=len(prompt))
        finally:
            self._free_slot(slot_idx)
        return k, v, so.token, so.logprob

    def inject_prefilled(self, seq_id: str, request: BackendInput,
                         k: np.ndarray, v: np.ndarray,
                         first_token: int,
                         first_logprob: float = 0.0) -> StepOutput:
        """Receive a remotely-prefilled sequence: write its prompt KV into
        this pool and enter it straight into decode (prefill_done=len).
        ``k``/``v``: [L, T, Hkv, Dh] for the prompt tokens."""
        if None not in self.slots:
            raise RuntimeError("no free slot for injected sequence")
        prompt = list(request.token_ids)
        T = k.shape[1]
        if T != len(prompt):
            raise ValueError(f"KV covers {T} tokens, prompt is {len(prompt)}")
        self.pool.create(seq_id)
        self.pool.extend(seq_id, prompt)
        self._flush_evictions()
        slots = jnp.asarray(self.pool.write_slots(seq_id, 0, T))
        if not hasattr(self, "_scatter_fn"):
            pg = self.page_size
            # advanced indices around the Hkv slice put [T] in front
            self._scatter_fn = jax.jit(
                lambda p, s, vals: p.at[:, s // pg, :, s % pg].set(
                    jnp.transpose(vals, (1, 0, 2, 3))), donate_argnums=0)
        self.k_pool = self._scatter_fn(self.k_pool, slots,
                                       k.astype(self.cfg.model.dtype))
        self.v_pool = self._scatter_fn(self.v_pool, slots,
                                       v.astype(self.cfg.model.dtype))

        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, request, prompt, prefill_done=len(prompt))
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self._load_sampling(slot_idx, request)
        if request.sampling.seed is not None:
            # the prefill worker consumed one key step sampling the first
            # token; advance the freshly-seeded key the same way so token 2
            # onward matches a local prefill of the same seeded request
            s = self.sampling
            s.key = s.key.at[slot_idx].set(
                jax.random.split(s.key[slot_idx], 2)[0])
        self._append_generated(slot, int(first_token))
        slot.cum_logprob = float(first_logprob)
        fin = self._finish_reason(slot, int(first_token))
        so = StepOutput(seq_id, int(first_token), slot.cum_logprob, fin,
                        prompt_tokens=len(prompt))
        if fin is not None:
            self._free_slot(slot_idx)
        return so

    # ------------------------------------------------------------------
    def step(self) -> List[StepOutput]:
        """Run one engine iteration: advance EVERY mid-prefill sequence by one
        chunk, admit as many waiting requests as fit (one chunk each), then
        run one decode batch. Long prompts still interleave with decode chunk
        by chunk, but decode dispatches always run at full occupancy — the
        difference between ~1x and ~5x throughput when a batch arrives.

        TTFT: if the prefill/admission phase produced outputs (first tokens
        of freshly-prefilled prompts), return them immediately instead of
        holding them through a decode_steps-long dispatch — the caller
        flushes them to clients and decode runs on the next iteration. Worst
        case this costs one host round-trip per admission burst; it saves a
        full multi-step decode dispatch of first-token latency."""
        out: List[StepOutput] = []
        out.extend(self._reap_cancelled())
        n_reaped = len(out)
        for i, slot in [(i, s) for i, s in enumerate(self.slots)
                        if s is not None and s.prefill_done < len(s.prompt)]:
            self._prefill_chunk(i, slot, out)
        while self.waiting and None in self.slots:
            if not self._admit_and_prefill(out):
                break
        if len(out) > n_reaped:
            # fresh first tokens (not just cancel reaps): flush them now
            return out
        if any(s is not None and s.prefill_done >= len(s.prompt)
               for s in self.slots):
            out.extend(self._decode_step())
        return out

    # ------------------------------------------------------------------
    def _reap_cancelled(self) -> List[StepOutput]:
        outs = []
        for i, slot in enumerate(self.slots):
            if slot is not None and slot.cancelled:
                outs.append(StepOutput(slot.seq_id, slot.last_token, 0.0,
                                       FinishReason.CANCELLED))
                self._free_slot(i)
        return outs

    def _free_slot(self, i: int) -> None:
        slot = self.slots[i]
        if slot is None:
            return
        self.pool.release(slot.seq_id)
        self.by_seq.pop(slot.seq_id, None)
        self.slots[i] = None

    def _offload_evicted(self, seq_hash: int, page: int) -> None:
        """Eviction hook: queue the page for host-tier offload. The data
        stays valid until the page's new owner WRITES (the next device
        dispatch), so :meth:`_flush_evictions` batches the copies out right
        before any dispatch that could overwrite pool pages."""
        if self.tiered is None:
            return
        self._evict_buf.append((seq_hash, page))

    def _flush_evictions(self) -> None:
        if not self._evict_buf:
            return
        buf, self._evict_buf = self._evict_buf, []
        pages = [p for _, p in buf]
        k, v = self.copy_stream.d2h_pages(self.k_pool, self.v_pool, pages,
                                          pipeline=len(pages) > 4)
        for i, (seq_hash, _) in enumerate(buf):
            self.tiered.offload(seq_hash, k[i], v[i])

    def _restore_prefix(self, seq_id: str, prompt: List[int]) -> int:
        """Prefix reuse at admission: claim matching device blocks and
        upload matching host-tier blocks; returns tokens satisfied from
        cache (always < len(prompt) so the last token still computes
        logits)."""
        host_lookup = None
        fetched: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        if self.tiered is not None:
            def host_lookup(h):
                # fetch (and copy) eagerly: leasing the upload page can evict
                # a device block whose offload lands in — and LRU-drops from —
                # the very host tier we matched against
                kv = self.tiered.lookup(h)
                if kv is None:
                    return False
                fetched[h] = (kv[0].copy(), kv[1].copy())
                return True
        matched, uploads = self.pool.match_prefix(
            seq_id, prompt, len(prompt) - 1, host_lookup)
        if uploads:
            self._flush_evictions()
            pages = [p for _, p in uploads]
            ks = np.stack([fetched[h][0] for h, _ in uploads])
            vs = np.stack([fetched[h][1] for h, _ in uploads])
            self.k_pool, self.v_pool = self.copy_stream.h2d_pages(
                self.k_pool, self.v_pool, pages, ks, vs)
        return matched

    def _admit_and_prefill(self, out: List[StepOutput]) -> bool:
        """Admit the head-of-line request and run ONE prefill chunk (possibly
        finishing the prompt). Returns True if an XLA step ran."""
        seq_id, req = self.waiting[0]
        prompt = list(req.token_ids)
        if len(prompt) >= self.cfg.max_context:
            self.waiting.popleft()
            out.append(StepOutput(seq_id, 0, 0.0, FinishReason.ERROR))
            return False
        if self.pool.pages_needed(len(prompt) + 1) > self.pool.num_pages - 1:
            # can NEVER fit, even with an empty pool: reject, don't starve
            self.waiting.popleft()
            out.append(StepOutput(seq_id, 0, 0.0, FinishReason.ERROR))
            return False
        if not self.pool.can_admit(len(prompt) + 1):
            return False  # no KV space yet; decode will free some eventually
        self.waiting.popleft()
        slot_idx = self.slots.index(None)
        slot = _Slot(seq_id, req, prompt)
        self.slots[slot_idx] = slot
        self.by_seq[seq_id] = slot
        self.pool.create(seq_id)
        matched = 0
        if self.cfg.enable_prefix_reuse:
            matched = self._restore_prefix(seq_id, prompt)
            slot.prefill_done = matched
        self.last_prefix_hit = matched
        self.prefix_hit_tokens += matched
        self.prefix_query_tokens += len(prompt)
        self._load_sampling(slot_idx, req)
        return self._prefill_chunk(slot_idx, slot, out)

    def _load_sampling(self, slot_idx: int, req: BackendInput) -> None:
        s = self.sampling
        s.temperature[slot_idx] = float(req.sampling.temperature or 0.0)
        s.top_p[slot_idx] = float(req.sampling.top_p
                                  if req.sampling.top_p is not None else 1.0)
        s.top_k[slot_idx] = int(min(req.sampling.top_k or 0, STATIC_K))
        if req.sampling.seed is not None:
            s.key = s.key.at[slot_idx].set(
                jax.random.key(req.sampling.seed))

    def _prefill_chunk(self, slot_idx: int, slot: _Slot,
                       out: List[StepOutput]) -> bool:
        prompt = slot.prompt
        start = slot.prefill_done
        count = min(len(prompt) - start, self.cfg.prefill_chunk)
        is_last = start + count == len(prompt)
        C = self._bucket(count, self.c_buckets)
        S = self._bucket(start + count, self.s_buckets)

        try:
            self.pool.extend(slot.seq_id, prompt[start:start + count])
        except OutOfPages:
            out.append(StepOutput(slot.seq_id, 0, 0.0, FinishReason.ERROR))
            self._free_slot(slot_idx)
            return False

        self._flush_evictions()   # extend() may have evicted pages
        tokens = np.zeros((1, C), np.int32)
        tokens[0, :count] = prompt[start:start + count]
        positions = np.zeros((1, C), np.int32)
        positions[0, :count] = np.arange(start, start + count)
        write_idx = np.zeros((1, C), np.int32)  # pad writes -> scratch page 0
        write_idx[0, :count] = self.pool.write_slots(slot.seq_id, start, count)
        r_slots, r_pos, r_valid = self.pool.read_slots(
            slot.seq_id, start + count, S)
        args = (self.params, tokens, positions, self.k_pool, self.v_pool,
                write_idx, r_slots[None], r_pos[None], r_valid[None])
        if is_last:
            s = self.sampling
            fn = self._prefill_fns(C, S, last=True)
            tok, logp, new_key, self.k_pool, self.v_pool = fn(
                *args, s.temperature[slot_idx:slot_idx + 1],
                s.top_p[slot_idx:slot_idx + 1],
                s.top_k[slot_idx:slot_idx + 1],
                s.key[slot_idx:slot_idx + 1], count - 1)
            s.key = s.key.at[slot_idx].set(new_key[0])
            slot.prefill_done += count
            t = int(tok[0])
            try:
                self._append_generated(slot, t)
            except OutOfPages:
                out.append(StepOutput(slot.seq_id, t, float(logp[0]),
                                      FinishReason.ERROR))
                self._free_slot(slot_idx)
                return True
            slot.cum_logprob += float(logp[0])
            fin = self._finish_reason(slot, t)
            out.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin,
                                  prompt_tokens=len(prompt)))
            if fin is not None:
                self._free_slot(slot_idx)
        else:
            fn = self._prefill_fns(C, S, last=False)
            self.k_pool, self.v_pool = fn(*args)
            slot.prefill_done += count
        return True

    def _append_generated(self, slot: _Slot, token: int) -> None:
        slot.generated += 1
        slot.last_token = token
        self.pool.extend(slot.seq_id, [token])

    def _finish_reason(self, slot: _Slot, token: int) -> Optional[FinishReason]:
        req = slot.request
        if not req.stop.ignore_eos:
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            if token in eos and slot.generated >= (req.stop.min_tokens or 0):
                return FinishReason.EOS
        if req.stop.max_tokens and slot.generated >= req.stop.max_tokens:
            return FinishReason.LENGTH
        if len(slot.prompt) + slot.generated >= self.cfg.max_context:
            return FinishReason.LENGTH
        return None

    # ------------------------------------------------------------------
    def _decode_step(self) -> List[StepOutput]:
        B = self.cfg.max_batch
        N = self.cfg.decode_steps
        outs: List[StepOutput] = []
        # only fully-prefilled slots decode; mid-prefill slots keep their
        # lanes masked (scratch page table) until their prompt is in cache
        active = []
        deferred = []
        for i, slot in enumerate(self.slots):
            if slot is None or slot.prefill_done < len(slot.prompt):
                continue
            n = len(slot.prompt) + slot.generated
            try:
                # reserve room for N speculative tokens up front
                self.pool.ensure_pages(slot.seq_id, n + N)
            except OutOfPages:
                # pool pressure: defer this slot — batchmates finishing will
                # free pages — rather than killing a healthy request
                deferred.append((i, slot))
                continue
            active.append((i, slot))
        if not active:
            if deferred:
                # nothing can make progress: evict the largest consumer so
                # the rest of the system unblocks (capacity error)
                i, slot = max(deferred,
                              key=lambda t: len(self.pool.seqs[t[1].seq_id].pages))
                outs.append(StepOutput(slot.seq_id, slot.last_token,
                                       slot.cum_logprob, FinishReason.ERROR))
                self._free_slot(i)
            return outs
        self._flush_evictions()   # ensure_pages() may have evicted pages
        max_len = max(len(s.prompt) + s.generated for _, s in active) + N
        S = self._bucket(max_len, self.s_buckets)
        P = S // self.page_size

        tokens = np.zeros(B, np.int32)
        lengths = np.ones(B, np.int32)    # inactive lanes write into page 0
        page_tables = np.zeros((B, P), np.int32)
        for i, slot in active:
            n = len(slot.prompt) + slot.generated
            tokens[i] = slot.last_token
            lengths[i] = n
            page_tables[i] = self.pool.page_table_row(slot.seq_id, P)

        s = self.sampling
        fn = self._decode_fn(S)
        toks, logps, new_key, self.k_pool, self.v_pool = fn(
            self.params, tokens, self.k_pool, self.v_pool,
            page_tables, lengths, s.temperature, s.top_p, s.top_k, s.key)
        s.key = new_key
        toks_np = np.asarray(toks)    # [N, B]
        logps_np = np.asarray(logps)

        for i, slot in active:
            for j in range(N):
                t = int(toks_np[j, i])
                self.pool.account_tokens(slot.seq_id, [t])
                slot.generated += 1
                slot.last_token = t
                slot.cum_logprob += float(logps_np[j, i])
                fin = self._finish_reason(slot, t)
                outs.append(StepOutput(slot.seq_id, t, slot.cum_logprob, fin))
                if fin is not None:
                    # overshoot tokens beyond the finish are discarded; their
                    # page-pool writes are inside this seq's own pages and are
                    # released with the slot
                    self._free_slot(i)
                    break
        return outs


def _set_result(fut, res) -> None:
    if not fut.done():
        fut.set_result(res)


def _set_exception(fut, exc) -> None:
    if not fut.done():
        fut.set_exception(exc)


def _has_safetensors(path: str) -> bool:
    import glob
    import os

    return bool(glob.glob(os.path.join(path, "*.safetensors")))


# ---------------------------------------------------------------------------
# Async facade
# ---------------------------------------------------------------------------

class JaxEngine(AsyncEngine[BackendInput, EngineOutput]):
    """AsyncEngine facade: one background engine thread runs EngineCore."""

    def __init__(self, cfg: JaxEngineConfig,
                 devices: Optional[List[jax.Device]] = None):
        self.core = EngineCore(cfg, devices)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._queues: Dict[str, asyncio.Queue] = {}
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._wake = threading.Event()
        self._running = True
        self._thread = threading.Thread(target=self._run, name="jax-engine",
                                        daemon=True)
        self._thread.start()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while self._running:
            moved = False
            while True:
                try:
                    kind, seq_id, payload = self._inbox.get_nowait()
                except thread_queue.Empty:
                    break
                moved = True
                if kind == "submit":
                    self.core.submit(seq_id, payload)
                elif kind == "cancel":
                    self.core.cancel(seq_id)
                elif kind == "inject":
                    try:
                        so = self.core.inject_prefilled(seq_id, *payload)
                    except Exception:
                        log.exception("KV injection failed")
                        so = StepOutput(seq_id, 0, 0.0, FinishReason.ERROR)
                    self._deliver(so)
                elif kind == "prefill_extract":
                    request, loop, fut = payload
                    try:
                        res = self.core.prefill_extract(seq_id, request)
                        loop.call_soon_threadsafe(_set_result, fut, res)
                    except Exception as e:
                        log.exception("prefill_extract failed")
                        loop.call_soon_threadsafe(_set_exception, fut, e)
            if not self.core.has_work:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                outs = self.core.step()
            except Exception:  # engine must never die silently
                log.exception("engine step failed")
                outs = [StepOutput(sid, 0, 0.0, FinishReason.ERROR)
                        for sid in list(self.core.by_seq)]
                for sid in list(self.core.by_seq):
                    self.core.cancel(sid)
                self.core._reap_cancelled()
            for so in outs:
                try:
                    self._deliver(so)
                except Exception:  # closed loop etc. must not kill the thread
                    log.exception("failed to deliver step output")
            if not outs and not self.core.by_seq:
                # waiting requests that can't be admitted yet: don't busy-spin
                self._wake.wait(timeout=0.02)
                self._wake.clear()

    def _deliver(self, so: StepOutput) -> None:
        loop = self._loop
        if loop is None:
            return
        q = self._queues.get(so.seq_id)
        if q is not None:
            loop.call_soon_threadsafe(q.put_nowait, so)

    # ------------------------------------------------------------------
    async def generate(self, request: BackendInput,
                       context: Context) -> AsyncIterator[EngineOutput]:
        async for out in self._generate(("submit", request), context):
            yield out

    async def prefill_extract(self, request: BackendInput, context: Context
                              ) -> Tuple[np.ndarray, np.ndarray, int, float]:
        """Prefill-worker entry: compute prompt KV + first token on the
        engine thread, await the result. Returns (k, v, token, logprob)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("prefill_extract", context.id,
                         (request, loop, fut)))
        self._wake.set()
        return await fut

    async def generate_prefilled(self, request: BackendInput, context: Context,
                                 k, v, first_token: int,
                                 first_logprob: float = 0.0
                                 ) -> AsyncIterator[EngineOutput]:
        """Stream a request whose prompt KV (and first token) arrived from a
        remote prefill worker — enters decode directly."""
        payload = (request, k, v, first_token, first_logprob)
        async for out in self._generate(("inject", payload), context):
            yield out

    async def _generate(self, work, context: Context
                        ) -> AsyncIterator[EngineOutput]:
        kind, payload = work
        self._loop = asyncio.get_running_loop()
        seq_id = context.id
        q: asyncio.Queue = asyncio.Queue()
        self._queues[seq_id] = q
        self._inbox.put((kind, seq_id, payload))
        self._wake.set()

        async def watch_cancel():
            await context.stopped()
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

        cancel_task = asyncio.ensure_future(watch_cancel())
        try:
            while True:
                so: StepOutput = await q.get()
                if so.finish == FinishReason.ERROR:
                    yield EngineOutput(token_ids=[], finish_reason=FinishReason.ERROR)
                    return
                yield EngineOutput(
                    token_ids=[so.token],
                    cum_log_prob=so.logprob,
                    finish_reason=so.finish,
                )
                if so.finish is not None:
                    return
        finally:
            cancel_task.cancel()
            self._queues.pop(seq_id, None)
            self._inbox.put(("cancel", seq_id, None))
            self._wake.set()

    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._running = False
        self._wake.set()
        self._thread.join(timeout=5)
