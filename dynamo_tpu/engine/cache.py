"""Host-side paged KV cache bookkeeping for the JAX engine.

The device arrays (``k_pool``/``v_pool``: [L, H_kv, n_pages, page, D_h]) are a
head-major pool of fixed-size pages; a flat token slot
``page_id * page_size + offset`` addresses one token's KV. This module owns
the *maps*: per-sequence page tables, token-slot index computation for
scatter/gather, the sequence-hash chain, and — through
:class:`~dynamo_tpu.llm.kvbm.pool.DeviceBlockPool` — block states
(free/leased/reusable) enabling prefix reuse and tiered offload.

KV events: ``on_block_sealed`` fires when a page fills (router "stored"
event); ``on_blocks_removed`` fires when a sealed block is *evicted* from
the device pool (router "removed" event) — NOT on sequence release, because
released blocks stay matchable until evicted. ``on_block_evicted`` runs
first so the engine can offload the page to the host tier.

Reference capability: the engine-side half of the KV block manager
(lib/llm/src/kv/manager.rs:22-138 prepare_prefill_sequence, vllm patch block
manager hooks, event_manager.py stored/removed semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..llm.kvbm.pool import DeviceBlockPool, OutOfBlocks
from ..llm.tokens import (TokenSequence, chain_hash, hash_tokens,
                          lora_chain_root)


class OutOfPages(RuntimeError):
    pass


@dataclass
class SeqCache:
    """Per-sequence cache state: owned pages + token count."""

    seq_id: str
    pages: List[int] = field(default_factory=list)
    num_tokens: int = 0
    # chained-hash view of the tokens in cache (block size == page size)
    hashes: Optional[TokenSequence] = None


class PagePool:
    """Sequence bookkeeping over a :class:`DeviceBlockPool`.

    Page 0 is reserved as the scratch page: masked/inactive lanes write there
    so every jit step has fully static shapes with no host branching.
    """

    def __init__(self, num_pages: int, page_size: int):
        self.num_pages = num_pages
        self.page_size = page_size
        self.blocks = DeviceBlockPool(num_pages)
        self.blocks.on_evict = self._evicted
        self.seqs: Dict[str, SeqCache] = {}
        # hook: (seq_id, sealed TokenBlock, page, lora_id) when a page
        # fills — feeds the KV event publisher ("stored") for the router
        # index; lora_id is the adapter the sequence was created under.
        # add_seal_hook registers ADDITIONAL listeners (the engine's
        # cluster write-through) without displacing this primary slot.
        self.on_block_sealed: Optional[Callable] = None
        self._seal_hooks: List[Callable] = []
        # hook: (seq_hashes: List[int]) when sealed blocks leave the device
        # pool — the router "removed" event
        self.on_blocks_removed: Optional[Callable] = None
        # hook: (seq_hash, page) BEFORE an evicted page is recycled — the
        # engine offloads the page to the host tier here
        self.on_block_evicted: Optional[Callable] = None
        self._removed_buf: List[int] = []

    def add_seal_hook(self, cb: Callable) -> None:
        """Subscribe an extra (seq_id, TokenBlock, page, lora_id) listener
        for newly-registered sealed blocks (fires after on_block_sealed)."""
        self._seal_hooks.append(cb)

    def _fire_sealed(self, seq_id: str, sealed, page: int,
                     lora_id: int) -> None:
        if self.on_block_sealed:
            self.on_block_sealed(seq_id, sealed, page, lora_id)
        for cb in self._seal_hooks:
            cb(seq_id, sealed, page, lora_id)

    def _evicted(self, seq_hash: int, page: int) -> None:
        if self.on_block_evicted:
            self.on_block_evicted(seq_hash, page)
        # buffer removals so a batched eviction (multi-page ensure_pages /
        # extend) publishes ONE removed event, as the reference's event
        # manager batches them, instead of N single-hash events
        self._removed_buf.append(seq_hash)

    def flush_reusable(self) -> int:
        """Evict every reusable (parked) block back to the free list and
        publish their removed events as one batch."""
        n = self.blocks.flush_reusable()
        self._flush_removed()
        return n

    def _flush_removed(self) -> None:
        if self._removed_buf and self.on_blocks_removed:
            buf, self._removed_buf = self._removed_buf, []
            self.on_blocks_removed(buf)
        else:
            self._removed_buf.clear()

    # ------------------------------------------------------------------
    @property
    def free_pages(self) -> int:
        """Pages a new allocation could obtain (free + evictable)."""
        return self.blocks.allocatable

    def pages_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.page_size - 1) // self.page_size

    def can_admit(self, prompt_tokens: int, reserve_pages: int = 0) -> bool:
        return self.free_pages - reserve_pages >= self.pages_needed(prompt_tokens)

    # ------------------------------------------------------------------
    def create(self, seq_id: str, block_hashing: bool = True,
               lora_id: int = 0) -> SeqCache:
        """``lora_id`` salts the block-hash chain so blocks computed under
        different adapters never alias in reuse or in the router index."""
        if seq_id in self.seqs:
            raise ValueError(f"sequence {seq_id} already exists")
        sc = SeqCache(seq_id,
                      hashes=(TokenSequence(self.page_size, lora_id=lora_id)
                              if block_hashing else None))
        self.seqs[seq_id] = sc
        return sc

    def ensure_pages(self, seq_id: str, total_tokens: int) -> None:
        """Pre-allocate pages so the sequence can hold ``total_tokens`` (used
        before a multi-step decode dispatch writes tokens speculatively)."""
        sc = self.seqs[seq_id]
        need = self.pages_needed(total_tokens) - len(sc.pages)
        if need > self.blocks.allocatable:
            raise OutOfPages(
                f"need {need} pages, {self.blocks.allocatable} allocatable")
        for _ in range(need):
            sc.pages.append(self.blocks.lease_new())
        self._flush_removed()

    def account_tokens(self, seq_id: str, tokens: Sequence[int]) -> None:
        """Record tokens as present (pages must already exist); seals
        full-page blocks, registering them for reuse and firing the
        stored-event hook."""
        sc = self.seqs[seq_id]
        if sc.hashes is not None:
            for t in tokens:
                sealed = sc.hashes.append(int(t))
                if sealed is not None:
                    page = sc.pages[len(sc.hashes.blocks) - 1]
                    registered = self.blocks.seal(page, sealed.sequence_hash)
                    # stored events only for newly-registered blocks, so the
                    # router's per-worker refcount balances the single
                    # removed event fired at eviction
                    if registered:
                        self._fire_sealed(sc.seq_id, sealed, page,
                                          sc.hashes.lora_id)
        sc.num_tokens += len(tokens)

    def extend(self, seq_id: str, tokens: Sequence[int]) -> None:
        """Allocate-and-account in one call (prefill path)."""
        sc = self.seqs[seq_id]
        try:
            self.ensure_pages(seq_id, sc.num_tokens + len(tokens))
        except OutOfBlocks as e:
            raise OutOfPages(str(e)) from e
        self.account_tokens(seq_id, tokens)

    def release(self, seq_id: str) -> None:
        """Drop the sequence. Sealed pages park as reusable (still matchable
        by their sequence hash); partial pages return to the free list."""
        sc = self.seqs.pop(seq_id, None)
        if sc is None:
            return
        for page in sc.pages:
            self.blocks.release(page)

    # ------------------------------------------------------------------
    # prefix reuse
    # ------------------------------------------------------------------
    def match_prefix(self, seq_id: str,
                     prompt: Sequence[int], max_tokens: int,
                     host_lookup: Optional[Callable[[int], bool]] = None
                     ) -> Tuple[int, List[Tuple[int, int]]]:
        """Walk the prompt's chained block hashes, claiming matching device
        blocks for a freshly-created sequence. When a device miss occurs and
        ``host_lookup(seq_hash)`` returns True, a fresh page is leased for an
        upload instead (caller copies the data in).

        Returns (tokens_satisfied, uploads) where uploads is
        [(seq_hash, page)] the caller must fill from the host tier.
        """
        sc = self.seqs[seq_id]
        assert sc.num_tokens == 0, "match_prefix on a non-empty sequence"
        page_sz = self.page_size
        # the query chain MUST carry the sequence's lora salt: an unsalted
        # walk would adopt base-model blocks for adapter requests (and
        # never re-match the adapter's own salted blocks)
        parent: Optional[int] = lora_chain_root(
            sc.hashes.lora_id if sc.hashes is not None else 0)
        matched = 0
        uploads: List[Tuple[int, int]] = []
        limit = min(max_tokens, len(prompt))
        for start in range(0, limit - page_sz + 1, page_sz):
            blk = prompt[start:start + page_sz]
            sh = chain_hash(parent, hash_tokens(blk))
            page = self.blocks.match(sh)
            fire_stored = False
            if page is None and host_lookup is not None and host_lookup(sh):
                try:
                    page = self.blocks.lease_new()
                except OutOfBlocks:
                    break
                # host->device restore re-registers a block that fired a
                # removed event at eviction: publish stored again
                fire_stored = self.blocks.seal(page, sh)
                uploads.append((sh, page))
            if page is None:
                break
            self._adopt_block(sc, blk, page, fire_stored)
            parent = sh
            matched += page_sz
        self._flush_removed()
        return matched, uploads

    def probe_prefix(self, prompt: Sequence[int],
                     host_lookup: Optional[Callable[[int], bool]] = None,
                     lora_id: int = 0) -> int:
        """Non-claiming prefix probe: how many leading prompt tokens could be
        served from cache right now (device blocks + host tier). Feeds the
        disagg router's prefix_hit input without touching block states."""
        page_sz = self.page_size
        parent: Optional[int] = lora_chain_root(lora_id)
        n = 0
        for start in range(0, len(prompt) - page_sz + 1, page_sz):
            sh = chain_hash(parent,
                            hash_tokens(prompt[start:start + page_sz]))
            if not (self.blocks.contains(sh)
                    or (host_lookup is not None and host_lookup(sh))):
                break
            parent = sh
            n += page_sz
        return n

    def _adopt_block(self, sc: SeqCache, tokens: Sequence[int],
                     page: int, fire_stored: bool = False) -> None:
        """Attach an already-sealed device block to a fresh sequence.
        ``fire_stored`` is True only for host-tier restores (the block
        re-entered the device pool); plain device matches are already in
        the router index and must not re-fire."""
        sc.pages.append(page)
        sealed = None
        if sc.hashes is not None:
            for t in tokens:
                sealed = sc.hashes.append(int(t))
        sc.num_tokens += len(tokens)
        if fire_stored and sealed is not None:
            self._fire_sealed(sc.seq_id, sealed, page, sc.hashes.lora_id)

    # ------------------------------------------------------------------
    # index computation for the jitted forward
    # ------------------------------------------------------------------
    def write_slots(self, seq_id: str, start_token: int, count: int) -> np.ndarray:
        """Pool token-slot index for tokens [start, start+count) of a seq."""
        sc = self.seqs[seq_id]
        t = np.arange(start_token, start_token + count)
        pages = np.asarray(sc.pages, dtype=np.int32)
        return pages[t // self.page_size] * self.page_size + t % self.page_size

    def page_table_row(self, seq_id: str, padded_pages: int) -> np.ndarray:
        """This sequence's page ids padded (with scratch page 0) to a static
        width — the device-side index base for multi-step decode."""
        sc = self.seqs[seq_id]
        row = np.zeros(padded_pages, dtype=np.int32)
        n = min(len(sc.pages), padded_pages)
        row[:n] = sc.pages[:n]
        return row

    def read_slots(self, seq_id: str, length: int, padded: int
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(slots, positions, valid) arrays of static length ``padded``
        covering tokens [0, length); padding points at scratch page 0."""
        slots = np.zeros(padded, dtype=np.int32)
        pos = np.zeros(padded, dtype=np.int32)
        valid = np.zeros(padded, dtype=bool)
        n = min(length, padded)
        if n:
            slots[:n] = self.write_slots(seq_id, 0, n)
            pos[:n] = np.arange(n)
            valid[:n] = True
        return slots, pos, valid
