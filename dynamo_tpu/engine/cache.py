"""Host-side paged KV cache bookkeeping for the JAX engine.

The device arrays (``k_pool``/``v_pool``: [L, n_pages, H_kv, page, D_h]) are a
page-major pool of fixed-size pages; a flat token slot
``page_id * page_size + offset`` addresses one token's KV. This module owns the *maps*: free-page list,
per-sequence page tables, token-slot index computation for scatter/gather, and
sequence-hash bookkeeping that later feeds prefix reuse + KV events.

Reference capability: the engine-side half of the KV block manager
(lib/llm/src/kv/*, vllm patch block manager hooks) — reuse pool and event
publishing hook in here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..llm.tokens import TokenSequence


class OutOfPages(RuntimeError):
    pass


@dataclass
class SeqCache:
    """Per-sequence cache state: owned pages + token count."""

    seq_id: str
    pages: List[int] = field(default_factory=list)
    num_tokens: int = 0
    # chained-hash view of the tokens in cache (block size == page size)
    hashes: Optional[TokenSequence] = None


class PagePool:
    """Free-list allocator over the flat device pool.

    Page 0 is reserved as the scratch page: masked/inactive lanes write there
    so every jit step has fully static shapes with no host branching.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is scratch)")
        self.num_pages = num_pages
        self.page_size = page_size
        self._free: List[int] = list(range(num_pages - 1, 0, -1))  # stack; 0 reserved
        self.seqs: Dict[str, SeqCache] = {}
        # hook: called with (seq_id, sealed TokenBlock) when a page fills —
        # feeds the KV event publisher for the router index
        self.on_block_sealed: Optional[Callable] = None
        self.on_blocks_freed: Optional[Callable] = None

    # ------------------------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_needed(self, num_tokens: int) -> int:
        return (num_tokens + self.page_size - 1) // self.page_size

    def can_admit(self, prompt_tokens: int, reserve_pages: int = 0) -> bool:
        return self.free_pages - reserve_pages >= self.pages_needed(prompt_tokens)

    # ------------------------------------------------------------------
    def create(self, seq_id: str, block_hashing: bool = True) -> SeqCache:
        if seq_id in self.seqs:
            raise ValueError(f"sequence {seq_id} already exists")
        sc = SeqCache(seq_id,
                      hashes=TokenSequence(self.page_size) if block_hashing else None)
        self.seqs[seq_id] = sc
        return sc

    def ensure_pages(self, seq_id: str, total_tokens: int) -> None:
        """Pre-allocate pages so the sequence can hold ``total_tokens`` (used
        before a multi-step decode dispatch writes tokens speculatively)."""
        sc = self.seqs[seq_id]
        need = self.pages_needed(total_tokens) - len(sc.pages)
        if need > len(self._free):
            raise OutOfPages(f"need {need} pages, {len(self._free)} free")
        for _ in range(need):
            sc.pages.append(self._free.pop())

    def account_tokens(self, seq_id: str, tokens: Sequence[int]) -> None:
        """Record tokens as present (pages must already exist); seals
        full-page blocks, firing the hash-chain event hook."""
        sc = self.seqs[seq_id]
        if sc.hashes is not None:
            for t in tokens:
                sealed = sc.hashes.append(int(t))
                if sealed is not None and self.on_block_sealed:
                    page = sc.pages[len(sc.hashes.blocks) - 1]
                    self.on_block_sealed(sc.seq_id, sealed, page)
        sc.num_tokens += len(tokens)

    def extend(self, seq_id: str, tokens: Sequence[int]) -> None:
        """Allocate-and-account in one call (prefill path)."""
        sc = self.seqs[seq_id]
        self.ensure_pages(seq_id, sc.num_tokens + len(tokens))
        self.account_tokens(seq_id, tokens)

    def release(self, seq_id: str) -> None:
        sc = self.seqs.pop(seq_id, None)
        if sc is None:
            return
        if sc.hashes is not None and self.on_blocks_freed and sc.hashes.blocks:
            self.on_blocks_freed(sc.seq_id, sc.hashes.blocks)
        self._free.extend(reversed(sc.pages))

    # ------------------------------------------------------------------
    # index computation for the jitted forward
    # ------------------------------------------------------------------
    def write_slots(self, seq_id: str, start_token: int, count: int) -> np.ndarray:
        """Pool token-slot index for tokens [start, start+count) of a seq."""
        sc = self.seqs[seq_id]
        t = np.arange(start_token, start_token + count)
        pages = np.asarray(sc.pages, dtype=np.int32)
        return pages[t // self.page_size] * self.page_size + t % self.page_size

    def page_table_row(self, seq_id: str, padded_pages: int) -> np.ndarray:
        """This sequence's page ids padded (with scratch page 0) to a static
        width — the device-side index base for multi-step decode."""
        sc = self.seqs[seq_id]
        row = np.zeros(padded_pages, dtype=np.int32)
        n = min(len(sc.pages), padded_pages)
        row[:n] = sc.pages[:n]
        return row

    def read_slots(self, seq_id: str, length: int, padded: int
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(slots, positions, valid) arrays of static length ``padded``
        covering tokens [0, length); padding points at scratch page 0."""
        slots = np.zeros(padded, dtype=np.int32)
        pos = np.zeros(padded, dtype=np.int32)
        valid = np.zeros(padded, dtype=bool)
        n = min(length, padded)
        if n:
            slots[:n] = self.write_slots(seq_id, 0, n)
            pos[:n] = np.arange(n)
            valid[:n] = True
        return slots, pos, valid
