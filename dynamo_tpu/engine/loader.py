"""Checkpoint loading: HF safetensors -> sharded stacked param pytree.

Maps the HF LlamaForCausalLM parameter names onto our stacked-layer layout
(llama.init_params structure) and device_puts each tensor directly into its
NamedSharding — per-shard placement, no full-model host copy beyond the
memory-mapped safetensors views.

Reference capability: the model-weight fast path noted in SURVEY §5.4
(safetensors -> sharded jax arrays is the only 'resume'-like path).
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig


def _open_all(path: str) -> Dict[str, Any]:
    """tensor name -> (file, slice accessor) across all shards."""
    from safetensors import safe_open

    tensors: Dict[str, Any] = {}
    for fn in sorted(glob.glob(os.path.join(path, "*.safetensors"))):
        f = safe_open(fn, framework="numpy")
        for name in f.keys():
            tensors[name] = f
    return tensors


def _get(tensors: Dict[str, Any], name: str) -> np.ndarray:
    t = tensors[name].get_tensor(name)
    if t.dtype == np.uint16:  # bf16 stored raw
        t = t.view(jnp.bfloat16)
    return t


def load_llama_params_host(path: str, cfg: LlamaConfig) -> Dict[str, Any]:
    """Build the stacked host-numpy param tree from a safetensors dir
    WITHOUT any device placement — the weight-mobility cache pins these
    trees in host RAM so a later hot-swap pays only the h2d, and
    :func:`load_llama_params` device_puts the same tree at cold load."""
    tensors = _open_all(path)
    L, D, Hq, Hkv, Dh = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                         cfg.num_kv_heads, cfg.head_dim)
    # Gemma3 VLM checkpoints nest the text model under language_model; the
    # hub's actual naming is "language_model.model." (transformers <4.52
    # export), newer exports flatten to "model.language_model."
    pfx = ""
    for cand in ("model.language_model.", "language_model.model.",
                 "language_model.", "model."):
        if any(k.startswith(cand + "layers.") for k in tensors):
            pfx = cand
            break

    def lay(i: int, name: str) -> np.ndarray:
        return _get(tensors, f"{pfx}layers.{i}.{name}.weight")

    def stack(name: str, transform) -> np.ndarray:
        return np.stack([transform(lay(i, name)) for i in range(L)])

    dt = cfg.dtype
    # HF Llama calls the PRE-FFN norm "post_attention_layernorm"; Gemma2's
    # sandwich layout has four norms and names the pre-FFN one
    # "pre_feedforward_layernorm" instead
    ln2_name = ("pre_feedforward_layernorm" if cfg.sandwich_norms
                else "post_attention_layernorm")
    # HF Linear stores [out, in]; our layout is [in, ...out...]
    params: Dict[str, Any] = {
        "embed": _get(tensors, f"{pfx}embed_tokens.weight").astype(dt),
        "layers": {
            "ln1": stack("input_layernorm",
                         lambda w: w.astype(np.float32)).reshape(L, D),
            "ln2": stack(ln2_name,
                         lambda w: w.astype(np.float32)).reshape(L, D),
            "wq": stack("self_attn.q_proj",
                        lambda w: w.astype(dt).T.reshape(D, Hq, Dh)),
            "wk": stack("self_attn.k_proj",
                        lambda w: w.astype(dt).T.reshape(D, Hkv, Dh)),
            "wv": stack("self_attn.v_proj",
                        lambda w: w.astype(dt).T.reshape(D, Hkv, Dh)),
            "wo": stack("self_attn.o_proj",
                        lambda w: w.astype(dt).T.reshape(Hq, Dh, D)),
            "wg": stack("mlp.gate_proj", lambda w: w.astype(dt).T),
            "wu": stack("mlp.up_proj", lambda w: w.astype(dt).T),
            "wd": stack("mlp.down_proj", lambda w: w.astype(dt).T),
        },
        "final_norm": _get(tensors, f"{pfx}norm.weight").astype(np.float32),
    }
    if cfg.sandwich_norms:
        params["layers"]["ln1_post"] = stack(
            "post_attention_layernorm",
            lambda w: w.astype(np.float32)).reshape(L, D)
        params["layers"]["ln2_post"] = stack(
            "post_feedforward_layernorm",
            lambda w: w.astype(np.float32)).reshape(L, D)
    if cfg.qk_norm:
        params["layers"]["ln_q"] = stack(
            "self_attn.q_norm", lambda w: w.astype(np.float32)).reshape(
            L, Dh)
        params["layers"]["ln_k"] = stack(
            "self_attn.k_norm", lambda w: w.astype(np.float32)).reshape(
            L, Dh)
    if cfg.attention_bias:
        def bias(i, name, h):
            return _get(tensors, f"{pfx}layers.{i}.{name}.bias") \
                .astype(dt).reshape(h, Dh)

        params["layers"]["bq"] = np.stack(
            [bias(i, "self_attn.q_proj", Hq) for i in range(L)])
        params["layers"]["bk"] = np.stack(
            [bias(i, "self_attn.k_proj", Hkv) for i in range(L)])
        params["layers"]["bv"] = np.stack(
            [bias(i, "self_attn.v_proj", Hkv) for i in range(L)])
    if not cfg.tie_embeddings:
        # the VLM nesting puts lm_head BESIDE the inner model
        # ("language_model.lm_head.weight"), not under the layer prefix
        head = next(
            (k for k in ("lm_head.weight", f"{pfx}lm_head.weight",
                         pfx.rsplit("model.", 1)[0] + "lm_head.weight")
             if k in tensors), f"{pfx}lm_head.weight")
        params["lm_head"] = _get(tensors, head).astype(dt).T
    return params


def load_llama_params(path: str, cfg: LlamaConfig,
                      shardings: Dict[str, Any]) -> Dict[str, Any]:
    params = load_llama_params_host(path, cfg)
    from .engine import global_put
    from ..obs.flows import record_flow

    t0 = time.perf_counter()
    placed = jax.tree.map(lambda a, s: global_put(a, s), params, shardings)
    # one flow for the whole cold load: puts are enqueued async, so this
    # meters the enqueue wall-time, not the device copy — the swap path's
    # barrier-bounded record is the honest h2d rate
    record_flow("weight_prefetch",
                sum(int(np.asarray(a).nbytes)
                    for a in jax.tree.leaves(params)),
                time.perf_counter() - t0)
    return placed


def save_llama_params(path: str, params: Dict[str, Any], cfg: LlamaConfig) -> None:
    """Write params back out in HF layout (used by tests to round-trip)."""
    from safetensors.numpy import save_file

    os.makedirs(path, exist_ok=True)
    # safetensors writes the raw buffer: every transposed view MUST be made
    # contiguous first or the transpose is silently lost
    C = np.ascontiguousarray
    L, D, Hq, Hkv, Dh = (cfg.num_layers, cfg.hidden_size, cfg.num_heads,
                         cfg.num_kv_heads, cfg.head_dim)
    lp = params["layers"]
    out: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    sandwich = "ln1_post" in lp
    for i in range(L):
        p = f"model.layers.{i}."
        out[p + "input_layernorm.weight"] = np.asarray(lp["ln1"][i], np.float32)
        if sandwich:
            # Gemma2 naming: ln2 is the PRE-ffw norm; post_attention is
            # the attn-branch output norm (see load_llama_params)
            out[p + "pre_feedforward_layernorm.weight"] = np.asarray(
                lp["ln2"][i], np.float32)
            out[p + "post_attention_layernorm.weight"] = np.asarray(
                lp["ln1_post"][i], np.float32)
            out[p + "post_feedforward_layernorm.weight"] = np.asarray(
                lp["ln2_post"][i], np.float32)
        else:
            out[p + "post_attention_layernorm.weight"] = np.asarray(
                lp["ln2"][i], np.float32)
        out[p + "self_attn.q_proj.weight"] = C(np.asarray(
            lp["wq"][i], np.float32).reshape(D, Hq * Dh).T)
        out[p + "self_attn.k_proj.weight"] = C(np.asarray(
            lp["wk"][i], np.float32).reshape(D, Hkv * Dh).T)
        out[p + "self_attn.v_proj.weight"] = C(np.asarray(
            lp["wv"][i], np.float32).reshape(D, Hkv * Dh).T)
        out[p + "self_attn.o_proj.weight"] = C(np.asarray(
            lp["wo"][i], np.float32).reshape(Hq * Dh, D).T)
        out[p + "mlp.gate_proj.weight"] = C(np.asarray(lp["wg"][i], np.float32).T)
        out[p + "mlp.up_proj.weight"] = C(np.asarray(lp["wu"][i], np.float32).T)
        out[p + "mlp.down_proj.weight"] = C(np.asarray(lp["wd"][i], np.float32).T)
        if "ln_q" in lp:
            out[p + "self_attn.q_norm.weight"] = np.asarray(
                lp["ln_q"][i], np.float32)
            out[p + "self_attn.k_norm.weight"] = np.asarray(
                lp["ln_k"][i], np.float32)
        if "bq" in lp:
            out[p + "self_attn.q_proj.bias"] = C(np.asarray(
                lp["bq"][i], np.float32).reshape(-1))
            out[p + "self_attn.k_proj.bias"] = C(np.asarray(
                lp["bk"][i], np.float32).reshape(-1))
            out[p + "self_attn.v_proj.bias"] = C(np.asarray(
                lp["bv"][i], np.float32).reshape(-1))
    if "lm_head" in params:
        out["lm_head.weight"] = C(np.asarray(params["lm_head"], np.float32).T)
    save_file(out, os.path.join(path, "model.safetensors"))
