"""Batched token sampling inside jit.

One static-shaped sampler covers all slots: per-slot temperature/top-k/top-p
vectors select behavior lane-wise (greedy lanes use argmax; sampling lanes use
temperature + nucleus/top-k restricted to a static K window — restriction to
the top-K=64 candidates is exact for top-k<=64 and a standard approximation
for pure top-p, since mass beyond the top-64 logits is negligible for LLMs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

STATIC_K = 64


@dataclass
class SamplingState:
    """Per-slot device vectors (length = max_batch)."""

    temperature: jax.Array  # f32, 0 => greedy
    top_p: jax.Array        # f32 in (0,1], 1 => off
    top_k: jax.Array        # i32, 0 => off (capped at STATIC_K)
    key: jax.Array          # [B] typed PRNG keys (new-style jax.random.key)
    freq_pen: jax.Array     # f32, 0 => off (OpenAI frequency_penalty)
    pres_pen: jax.Array     # f32, 0 => off (OpenAI presence_penalty)

    @classmethod
    def host_init(cls, max_batch: int) -> "SamplingState":
        return cls(
            temperature=np.zeros(max_batch, np.float32),
            top_p=np.ones(max_batch, np.float32),
            top_k=np.zeros(max_batch, np.int32),
            key=jax.random.split(jax.random.key(0), max_batch),
            freq_pen=np.zeros(max_batch, np.float32),
            pres_pen=np.zeros(max_batch, np.float32),
        )


def apply_penalties(logits: jax.Array, counts: jax.Array,
                    freq_pen: jax.Array, pres_pen: jax.Array) -> jax.Array:
    """OpenAI frequency/presence penalties over GENERATED-token counts
    (completion text only, the vLLM-compatible reading): zero-penalty lanes
    are a bitwise no-op. logits [B,V] f32, counts [B,V] i32."""
    cf = counts.astype(jnp.float32)
    return (logits - freq_pen[:, None] * cf
            - pres_pen[:, None] * (cf > 0).astype(jnp.float32))


def sample(logits: jax.Array, temperature: jax.Array, top_p: jax.Array,
           top_k: jax.Array, key: jax.Array
           ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """logits [B,V] f32 -> (tokens [B] i32, logprob [B] f32, new_keys [B]).

    Greedy lanes (temperature==0) take argmax; others sample within the
    top-STATIC_K window with temperature, then top-k/top-p masks.
    """
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1)

    vals, idxs = jax.lax.top_k(logits, STATIC_K)  # [B,K]
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = vals / temp
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-k mask (0 => off)
    karr = jnp.where(top_k[:, None] > 0, top_k[:, None], STATIC_K)
    kmask = jnp.arange(STATIC_K)[None, :] < karr
    # top-p (nucleus) mask over the sorted window: keep the smallest prefix
    # with cumulative mass >= top_p (always keep the first candidate)
    cum = jnp.cumsum(probs, axis=-1)
    pmask = (cum - probs) < top_p[:, None]
    mask = kmask & pmask
    masked = jnp.where(mask, scaled, -jnp.inf)

    split = jax.vmap(lambda k: jax.random.split(k, 2))(key)  # [B,2] typed
    new_keys, sub = split[:, 0], split[:, 1]
    draw = jax.vmap(jax.random.categorical)(sub, masked)
    sampled_tok = jnp.take_along_axis(idxs, draw[:, None], axis=-1)[:, 0]

    token = jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logprob = jnp.take_along_axis(logp_all, token[:, None], axis=-1)[:, 0]
    return token.astype(jnp.int32), logprob, new_keys
