"""Batched token sampling inside jit.

One static-shaped sampler covers all slots: per-slot temperature/top-k/top-p
vectors select behavior lane-wise (greedy lanes use argmax; sampling lanes use
temperature + nucleus/top-k restricted to a static K window — restriction to
the top-K=64 candidates is exact for top-k<=64 and a standard approximation
for pure top-p, since mass beyond the top-64 logits is negligible for LLMs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

STATIC_K = 64


def resume_seed(seed: int, resume_pos: int) -> int:
    """Deterministic per-resume-position seed fold (mid-stream failover,
    llm/resume.py). A resumed request replays its emitted tokens verbatim
    as forced prefix, but the dead worker's RNG draws at those positions
    are unreplayable — continuing from the ORIGINAL seed's key would
    re-issue draws the stream already consumed. Folding the resume
    position in gives the continuation a fresh, deterministic stream:
    the same (seed, resume_pos) always resumes identically, and
    resume_pos == 0 is the identity (an un-resumed request's key chain
    is untouched)."""
    if not resume_pos:
        return seed
    # splitmix64-style mix, stable across processes/platforms
    x = (seed ^ (resume_pos * 0x9E3779B97F4A7C15)) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


@dataclass
class SamplingState:
    """Per-slot device vectors (length = max_batch)."""

    temperature: jax.Array  # f32, 0 => greedy
    top_p: jax.Array        # f32 in (0,1], 1 => off
    top_k: jax.Array        # i32, 0 => off (capped at STATIC_K)
    key: jax.Array          # [B] typed PRNG keys (new-style jax.random.key)
    freq_pen: jax.Array     # f32, 0 => off (OpenAI frequency_penalty)
    pres_pen: jax.Array     # f32, 0 => off (OpenAI presence_penalty)

    @classmethod
    def host_init(cls, max_batch: int) -> "SamplingState":
        return cls(
            temperature=np.zeros(max_batch, np.float32),
            top_p=np.ones(max_batch, np.float32),
            top_k=np.zeros(max_batch, np.int32),
            key=jax.random.split(jax.random.key(0), max_batch),
            freq_pen=np.zeros(max_batch, np.float32),
            pres_pen=np.zeros(max_batch, np.float32),
        )


def apply_penalties(logits: jax.Array, counts: jax.Array,
                    freq_pen: jax.Array, pres_pen: jax.Array) -> jax.Array:
    """OpenAI frequency/presence penalties over GENERATED-token counts
    (completion text only, the vLLM-compatible reading): zero-penalty lanes
    are a bitwise no-op. logits [B,V] f32, counts [B,V] i32."""
    cf = counts.astype(jnp.float32)
    return (logits - freq_pen[:, None] * cf
            - pres_pen[:, None] * (cf > 0).astype(jnp.float32))


def sample(logits: jax.Array, temperature: jax.Array, top_p: jax.Array,
           top_k: jax.Array, key: jax.Array
           ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """logits [B,V] f32 -> (tokens [B] i32, logprob [B] f32, new_keys [B]).

    Greedy lanes (temperature==0) take argmax; others sample within the
    top-STATIC_K window with temperature, then top-k/top-p masks.
    """
    B, V = logits.shape
    greedy_tok = jnp.argmax(logits, axis=-1)

    vals, idxs = jax.lax.top_k(logits, STATIC_K)  # [B,K]
    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = vals / temp
    probs = jax.nn.softmax(scaled, axis=-1)
    # top-k mask (0 => off)
    karr = jnp.where(top_k[:, None] > 0, top_k[:, None], STATIC_K)
    kmask = jnp.arange(STATIC_K)[None, :] < karr
    # top-p (nucleus) mask over the sorted window: keep the smallest prefix
    # with cumulative mass >= top_p (always keep the first candidate)
    cum = jnp.cumsum(probs, axis=-1)
    pmask = (cum - probs) < top_p[:, None]
    mask = kmask & pmask
    masked = jnp.where(mask, scaled, -jnp.inf)

    split = jax.vmap(lambda k: jax.random.split(k, 2))(key)  # [B,2] typed
    new_keys, sub = split[:, 0], split[:, 1]
    draw = jax.vmap(jax.random.categorical)(sub, masked)
    sampled_tok = jnp.take_along_axis(idxs, draw[:, None], axis=-1)[:, 0]

    token = jnp.where(temperature <= 0.0, greedy_tok, sampled_tok)
    logp_all = jax.nn.log_softmax(logits, axis=-1)
    logprob = jnp.take_along_axis(logp_all, token[:, None], axis=-1)[:, 0]
    return token.astype(jnp.int32), logprob, new_keys


# ---------------------------------------------------------------------------
# speculative decoding: verify-side sampling (in-jit) + host-side acceptance
# ---------------------------------------------------------------------------
# The verify program runs one forward over T = K+1 positions per lane
# (position 0 = the last committed token; positions 1..K = draft tokens) and
# hands the host everything acceptance needs in ONE packed fetch:
#
#   greedy_tok[t]   argmax of the target distribution at position t
#   full_tok[t]     a token sampled from the full target distribution
#   resid_tok[i]    a token sampled from the RESIDUAL distribution at draft
#                   position i: the target with the draft token's mass
#                   removed, renormalized
#   p_draft[i]      target probability of draft token i (within the masked
#                   sampling window — the distribution sample() actually
#                   draws from)
#   u[i]            uniform draw for the accept test
#
# Both in-tree proposers are DETERMINISTIC (n-gram lookup; greedy draft
# model), i.e. the proposal distribution q is a point mass at the drafted
# token. Rejection sampling then reduces to: accept draft d with probability
# min(1, p(d)/q(d)) = p(d); on rejection emit a token from
# norm(max(0, p - q)) = p with d's mass removed — which preserves the target
# distribution exactly (Leviathan et al., 2023, spec-sampling lemma with a
# delta proposal). Greedy lanes skip all of that: accept iff d == argmax.


def spec_pack_width(K: int) -> int:
    """Columns in the packed verify output for draft length ``K``."""
    return 4 * (K + 1) + 5 * K


def spec_verify(logits: jax.Array, drafts: jax.Array,
                temperature: jax.Array, top_p: jax.Array, top_k: jax.Array,
                key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """In-jit verify sampling. ``logits`` [B, K+1, V] f32 (penalties already
    applied), ``drafts`` [B, K] i32. Returns (packed [B, spec_pack_width(K)]
    f32, new_keys [B]). Token ids < 2^24 are exact in f32, so one packed
    array carries ids and logprobs losslessly (same trick as decode)."""
    B, T, V = logits.shape
    K = T - 1
    greedy = jnp.argmax(logits, axis=-1)                          # [B,T]
    logp_all = jax.nn.log_softmax(logits, axis=-1)                # [B,T,V]
    logp_greedy = jnp.take_along_axis(
        logp_all, greedy[..., None], axis=-1)[..., 0]             # [B,T]

    # the masked sampling window, replicating sample() exactly: top-STATIC_K
    # candidates, temperature scaling, then top-k/top-p masks
    vals, idxs = jax.lax.top_k(logits, STATIC_K)                  # [B,T,Kw]
    temp = jnp.maximum(temperature, 1e-6)[:, None, None]
    scaled = vals / temp
    probs = jax.nn.softmax(scaled, axis=-1)
    karr = jnp.where(top_k > 0, top_k, STATIC_K)[:, None, None]
    kmask = jnp.arange(STATIC_K)[None, None, :] < karr
    cum = jnp.cumsum(probs, axis=-1)
    pmask = (cum - probs) < top_p[:, None, None]
    mask = kmask & pmask
    masked = jnp.where(mask, scaled, -jnp.inf)                    # [B,T,Kw]
    win_p = jax.nn.softmax(masked, axis=-1)

    # draft-token probability under the target sampling distribution; a
    # draft outside the masked window has p=0 and is always rejected (the
    # non-spec sampler could never have emitted it)
    in_win = (idxs[:, :K] == drafts[:, :, None]) & mask[:, :K]    # [B,K,Kw]
    p_draft = jnp.sum(jnp.where(in_win, win_p[:, :K], 0.0), -1)   # [B,K]
    resid = jnp.where(in_win, -jnp.inf, masked[:, :K])            # [B,K,Kw]

    # per-lane subkeys: T full draws + K residual draws + 1 uniform vector
    sub = jax.vmap(lambda k: jax.random.split(k, T + K + 2))(key)
    new_keys = sub[:, 0]
    cat = jax.vmap(jax.vmap(jax.random.categorical))
    full_w = cat(sub[:, 1:1 + T], masked)                         # [B,T]
    resid_w = cat(sub[:, 1 + T:1 + T + K], resid)                 # [B,K]
    full_tok = jnp.take_along_axis(idxs, full_w[..., None], -1)[..., 0]
    resid_tok = jnp.take_along_axis(
        idxs[:, :K], resid_w[..., None], -1)[..., 0]
    u = jax.vmap(lambda k: jax.random.uniform(k, (K,)))(sub[:, T + K + 1])

    # logprobs are reported from the UNSCALED post-penalty distribution,
    # matching sample()'s contract
    def lp_at(tok):
        return jnp.take_along_axis(
            logp_all[:, :tok.shape[1]], tok[..., None].astype(jnp.int32),
            axis=-1)[..., 0]

    packed = jnp.concatenate([
        greedy.astype(jnp.float32), logp_greedy,
        full_tok.astype(jnp.float32), lp_at(full_tok),
        resid_tok.astype(jnp.float32), lp_at(resid_tok),
        lp_at(drafts), p_draft, u.astype(jnp.float32),
    ], axis=1)
    return packed, new_keys


def spec_unpack(packed: np.ndarray, K: int) -> Dict[str, np.ndarray]:
    """Split the packed verify fetch back into named host arrays [B, ...]."""
    T = K + 1
    cuts = {"greedy_tok": T, "logp_greedy": T, "full_tok": T,
            "logp_full": T, "resid_tok": K, "logp_resid": K,
            "logp_draft": K, "p_draft": K, "u": K}
    out: Dict[str, np.ndarray] = {}
    off = 0
    for name, w in cuts.items():
        out[name] = packed[:, off:off + w]
        off += w
    return out


def spec_accept(drafts: List[int], is_greedy: bool, lane: Dict[str, np.ndarray]
                ) -> Tuple[List[int], List[float], int]:
    """Host-side acceptance for ONE lane. ``lane`` holds that lane's rows of
    :func:`spec_unpack`'s arrays. Returns (tokens, token_logprobs,
    n_accepted_drafts); between 1 and len(drafts)+1 tokens are emitted.

    Greedy: accept drafts while they match argmax; the emitted token at the
    first mismatch IS the argmax (what non-spec decode would have produced),
    so greedy output is token-identical to the non-speculative path.
    Temperature>0: accept draft i iff u_i < p(d_i); on rejection emit the
    residual-distribution token; if every draft is accepted, emit one bonus
    token sampled from the full target distribution at the next position."""
    toks: List[int] = []
    lps: List[float] = []
    acc = 0
    for i, d in enumerate(drafts):
        if is_greedy:
            tgt = int(lane["greedy_tok"][i])
            toks.append(tgt)
            lps.append(float(lane["logp_greedy"][i]))
            if tgt != int(d):
                return toks, lps, acc
            acc += 1
        elif float(lane["u"][i]) < float(lane["p_draft"][i]):
            toks.append(int(d))
            lps.append(float(lane["logp_draft"][i]))
            acc += 1
        else:
            toks.append(int(lane["resid_tok"][i]))
            lps.append(float(lane["logp_resid"][i]))
            return toks, lps, acc
    j = len(drafts)
    if is_greedy:
        toks.append(int(lane["greedy_tok"][j]))
        lps.append(float(lane["logp_greedy"][j]))
    else:
        toks.append(int(lane["full_tok"][j]))
        lps.append(float(lane["logp_full"][j]))
    return toks, lps, acc
