"""Cluster deployment layer (L7): deployment resources, the reconciling
operator, k8s manifest rendering, and the artifact/api store.

Reference capability: deploy/dynamo/operator (Go CRDs + controllers),
deploy/dynamo/api-store (FastAPI artifact store), deploy/dynamo/helm and
deploy/Kubernetes (charts). Re-designed for this stack: desired state lives
in dynstore (the discovery plane we already run), the operator reconciles it
into local worker processes, and :mod:`kube` reconciles rendered manifests
against a Kubernetes API (server-side apply, owner-ref GC, conditions). The
artifact store is an aiohttp service over pluggable object storage
(:mod:`object_store`: local filesystem or S3-compatible); :mod:`imagebuild`
packages graph sources into OCI build contexts.
"""

from .crd import (Condition, Deployment, DeploymentSpec, DeploymentStatus,
                  IngressSpec, ServiceSpec)
from .kube import FakeKubeApi, KubeReconciler
from .manifests import render_envoy_config, render_ingress, render_manifests
from .object_store import LocalFsStore, MinioStub, ObjectStore, S3Store, open_object_store
from .operator import FakeRunner, LocalRunner, Operator
from .rest_api import KubeApiError, RestKubeApi, register_kind

__all__ = [
    "Condition", "Deployment", "DeploymentSpec", "DeploymentStatus",
    "ServiceSpec", "IngressSpec", "Operator", "LocalRunner", "FakeRunner",
    "KubeReconciler", "FakeKubeApi",
    "RestKubeApi", "KubeApiError", "register_kind",
    "render_manifests", "render_ingress", "render_envoy_config",
    "ObjectStore", "LocalFsStore", "S3Store", "MinioStub",
    "open_object_store",
]
