"""Cluster deployment layer (L7): deployment resources, the reconciling
operator, k8s manifest rendering, and the artifact/api store.

Reference capability: deploy/dynamo/operator (Go CRDs + controllers),
deploy/dynamo/api-store (FastAPI artifact store), deploy/dynamo/helm and
deploy/Kubernetes (charts). Re-designed for this stack: desired state lives
in dynstore (the discovery plane we already run), the operator reconciles it
into local worker processes or renders k8s manifests for a real cluster, and
the artifact store is an aiohttp service over a content directory.
"""

from .crd import Condition, Deployment, DeploymentSpec, DeploymentStatus, ServiceSpec
from .operator import FakeRunner, LocalRunner, Operator

__all__ = [
    "Condition", "Deployment", "DeploymentSpec", "DeploymentStatus",
    "ServiceSpec", "Operator", "LocalRunner", "FakeRunner",
]
