"""API/artifact store: HTTP CRUD for graph artifacts and deployments.

Artifacts (packaged service graphs — a tarball or any bytes) are versioned
under a content directory; deployments are Deployment resources written into
dynstore, where the operator watches them. This is the control-plane front
door the reference runs as its FastAPI api-store.

    POST   /api/v1/artifacts/{name}/versions          (body = bytes)
    GET    /api/v1/artifacts                          list
    GET    /api/v1/artifacts/{name}/versions/{v}      download
    DELETE /api/v1/artifacts/{name}/versions/{v}
    POST   /api/v1/deployments                        (body = resource JSON)
    GET    /api/v1/deployments[/{ns}/{name}]          list / get + status
    DELETE /api/v1/deployments/{ns}/{name}

Reference capability: deploy/dynamo/api-store/ai_dynamo_store/api/
dynamo.py:62-390 (upload/download, versioning, deployment records).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Optional

from aiohttp import web

from ..runtime.scale.shards import make_store_client
from ..runtime.store_client import StoreClient
from .crd import DEPLOY_PREFIX, Deployment, SpecError, deploy_key, status_key


class ApiStore:
    def __init__(self, root: str, store_host: str = "127.0.0.1",
                 store_port: int = 4222, http_port: int = 0,
                 advertise_host: str = "127.0.0.1"):
        """``root``: a storage URL — a local directory / ``file://`` path
        (PVC analogue) or ``s3://bucket?endpoint=...`` (S3-compatible
        object storage, ref dynamo.py:550-565)."""
        from .object_store import open_object_store

        self.objects = open_object_store(root)
        self.store_host = store_host
        self.store_port = store_port
        self.http_port = http_port
        # host operators/workers use to fetch artifacts — must be reachable
        # from THEIR machines, not just ours
        self.advertise_host = advertise_host
        self.client: Optional[StoreClient] = None
        self._runner: Optional[web.AppRunner] = None
        # version allocation is a read-modify-write on .next_version; two
        # concurrent uploads of the same artifact must not alias one version
        # (one lock for all uploads: bounded, and uploads are rare)
        self._upload_lock = None

    # ------------------------------------------------------------------
    def _build_app(self) -> web.Application:
        app = web.Application(client_max_size=1 << 30)
        r = app.router
        r.add_post("/api/v1/artifacts/{name}/versions", self._upload)
        r.add_get("/api/v1/artifacts", self._list_artifacts)
        r.add_get("/api/v1/artifacts/{name}/versions/{v}", self._download)
        r.add_delete("/api/v1/artifacts/{name}/versions/{v}", self._del_art)
        r.add_post("/api/v1/deployments", self._apply_deployment)
        r.add_get("/api/v1/deployments", self._list_deployments)
        r.add_get("/api/v1/deployments/{ns}/{name}", self._get_deployment)
        r.add_delete("/api/v1/deployments/{ns}/{name}", self._del_deployment)
        return app

    async def start(self) -> int:
        self.client = await make_store_client(self.store_host,
                                        self.store_port).connect()
        self._runner = web.AppRunner(self._build_app())
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", self.http_port)
        await site.start()
        self.http_port = site._server.sockets[0].getsockname()[1]
        return self.http_port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        if self.client is not None:
            await self.client.close()
        await self.objects.close()

    # ------------------------------------------------------------------
    _NAME_RE = None

    @classmethod
    def _safe(cls, name: str) -> str:
        # strict charset: artifact names become object-store keys and URL
        # path segments on every backend — '?', '#', '%', spaces etc. would
        # change meaning downstream
        import re

        if cls._NAME_RE is None:
            cls._NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")
        if not name or name.startswith(".") or not cls._NAME_RE.match(name):
            raise web.HTTPBadRequest(text="invalid name")
        return name

    async def _upload(self, req: web.Request) -> web.Response:
        import asyncio

        name = self._safe(req.match_info["name"])
        data = await req.read()
        digest = hashlib.sha256(data).hexdigest()
        if self._upload_lock is None:
            self._upload_lock = asyncio.Lock()
        async with self._upload_lock:
            return await self._upload_locked(name, data, digest)

    async def _upload_locked(self, name: str, data: bytes,
                             digest: str) -> web.Response:
        # versions are monotonic even across deletes (a counter object, not
        # max(existing)+1): reusing a deleted version's number would alias
        # different content under one artifact://name/version
        counter_key = f"{name}/.next_version"
        existing = [int(k.rsplit("/", 1)[1])
                    for k in await self.objects.list(f"{name}/")
                    if k.rsplit("/", 1)[1].isdigit()]
        floor = max(existing, default=0)
        raw = await self.objects.get(counter_key)
        if raw is not None:
            try:
                floor = max(floor, int(raw.decode().strip()) - 1)
            except ValueError:
                pass
        version = floor + 1
        # The asyncio lock serializes allocation within ONE api-store
        # process; a shared backend (S3) with multiple replicas has no CAS
        # in the ObjectStore interface, so cross-replica races are detected
        # opportunistically instead: write, re-read, and if another replica
        # overwrote our version slot (digest mismatch) move to the next
        # number. Both racers converge — the overwritten one retries, the
        # surviving one verifies its own digest. Deploy one api-store per
        # bucket to avoid even this window.
        for _ in range(8):
            await self.objects.put(counter_key, str(version + 1).encode())
            await self.objects.put(f"{name}/{version}", data)
            echo = await self.objects.get(f"{name}/{version}")
            if echo is not None \
                    and hashlib.sha256(echo).hexdigest() == digest:
                break
            version += 1
        else:
            raise web.HTTPConflict(text="version allocation kept racing")
        meta = {"version": version, "sha256": digest, "size": len(data),
                "uploaded": time.time()}
        await self.objects.put(f"{name}/{version}.json",
                               json.dumps(meta).encode())
        # register in the store so artifact:// graph refs resolve
        from .artifacts import register

        url = (f"http://{self.advertise_host}:{self.http_port}"
               f"/api/v1/artifacts/{name}/versions/{version}")
        await register(self.client, name, version, url, digest, len(data))
        return web.json_response({"name": name, **meta}, status=201)

    async def _list_artifacts(self, _req: web.Request) -> web.Response:
        import asyncio

        pairs = []
        for key in await self.objects.list():
            parts = key.split("/")
            if len(parts) == 2 and parts[1].isdigit():
                pairs.append((parts[0], int(parts[1])))
        # metadata fetches go out concurrently: on the S3 backend each is a
        # network round-trip and a serial loop would be N+1
        raws = await asyncio.gather(
            *(self.objects.get(f"{n}/{v}.json") for n, v in pairs))
        out: dict = {}
        for (name, v), raw in zip(pairs, raws):
            out.setdefault(name, []).append(
                json.loads(raw.decode()) if raw else {"version": v})
        for versions in out.values():
            versions.sort(key=lambda m: m["version"])
        return web.json_response(
            {"artifacts": {k: out[k] for k in sorted(out)}})

    async def _download(self, req: web.Request) -> web.Response:
        name = self._safe(req.match_info["name"])
        v = self._safe(req.match_info["v"])
        data = await self.objects.get(f"{name}/{v}")
        if data is None:
            raise web.HTTPNotFound(text="no such artifact version")
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _del_art(self, req: web.Request) -> web.Response:
        name = self._safe(req.match_info["name"])
        v = self._safe(req.match_info["v"])
        if not v.isdigit():
            raise web.HTTPNotFound(text="no such artifact version")
        if not await self.objects.delete(f"{name}/{v}"):
            raise web.HTTPNotFound(text="no such artifact version")
        await self.objects.delete(f"{name}/{v}.json")
        # unregister, or artifact://name (latest) would resolve to a
        # version whose content is gone
        from .artifacts import descriptor_key

        await self.client.delete(descriptor_key(name, int(v)))
        return web.json_response({"deleted": f"{name}/{v}"})

    # ------------------------------------------------------------------
    async def _apply_deployment(self, req: web.Request) -> web.Response:
        try:
            dep = Deployment.from_dict(await req.json())
        except (SpecError, ValueError) as e:
            raise web.HTTPBadRequest(text=str(e))
        from .operator import apply

        await apply(self.client, dep)
        return web.json_response({"applied": dep.key(),
                                  "generation": dep.generation}, status=201)

    async def _list_deployments(self, _req: web.Request) -> web.Response:
        items = []
        for key, raw in await self.client.get_prefix(DEPLOY_PREFIX):
            try:
                items.append(Deployment.from_bytes(raw).to_dict())
            except (SpecError, ValueError):
                continue
        return web.json_response({"deployments": items})

    async def _get_deployment(self, req: web.Request) -> web.Response:
        ns, name = req.match_info["ns"], req.match_info["name"]
        raw = await self.client.get(deploy_key(ns, name))
        if raw is None:
            raise web.HTTPNotFound(text="no such deployment")
        out = Deployment.from_bytes(raw).to_dict()
        sraw = await self.client.get(status_key(ns, name))
        if sraw is not None:
            out["status"] = json.loads(sraw.decode())
        return web.json_response(out)

    async def _del_deployment(self, req: web.Request) -> web.Response:
        ns, name = req.match_info["ns"], req.match_info["name"]
        if not await self.client.delete(deploy_key(ns, name)):
            raise web.HTTPNotFound(text="no such deployment")
        return web.json_response({"deleted": f"{ns}/{name}"})


def main(argv=None) -> None:
    import argparse
    import asyncio

    ap = argparse.ArgumentParser("dynamo-api-store")
    ap.add_argument("--root", default="./artifacts",
                    help="artifact storage: a directory / file:// path, or "
                         "s3://bucket?endpoint=http://host:port")
    ap.add_argument("--store", default="127.0.0.1:4222")
    ap.add_argument("--port", type=int, default=8082)
    ap.add_argument("--advertise-host", default="127.0.0.1")
    args = ap.parse_args(argv)
    host, port = args.store.split(":")

    async def run():
        store = ApiStore(args.root, host, int(port), args.port,
                         advertise_host=args.advertise_host)
        p = await store.start()
        print(f"api-store on 127.0.0.1:{p}", flush=True)
        while True:
            await asyncio.sleep(3600)

    asyncio.run(run())


if __name__ == "__main__":
    main()
