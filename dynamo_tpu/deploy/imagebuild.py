"""Image-build orchestration for graph deployments.

Packages a service-graph module (or package directory) into an OCI build
context — a tar holding a rendered Dockerfile plus the graph sources under
``app/`` — and optionally drives an external builder command over it.
The runtime image itself ships the framework; the graph image layers the
user's code on top, exactly the split the reference operator's image-build
pipeline produces for its deployments.

Reference capability: the operator-driven image build of
deploy/dynamo/operator (builds artifact bundles into runnable images);
scoped here to deterministic context rendering + builder dispatch, since
this stack assumes a docker/buildkit binary rather than an in-cluster
builder.
"""

from __future__ import annotations

import io
import os
import shlex
import subprocess
import tarfile
import time
from typing import Optional

DOCKERFILE_TEMPLATE = """\
FROM {base}
# graph sources layered over the framework runtime image
COPY app/ /app/
ENV PYTHONPATH=/app
# the orchestrator/operator overrides the entry per service; this default
# just proves the image is runnable
CMD ["python", "-c", "import sys; sys.path.insert(0, '/app'); \
print('dynamo-tpu graph image ready')"]
"""


def render_dockerfile(base_image: str) -> str:
    return DOCKERFILE_TEMPLATE.format(base=base_image)


def build_context(path: str, base_image: str = "dynamo-tpu:latest",
                  out_path: Optional[str] = None) -> str:
    """Write an OCI build context tar for the graph at ``path`` (a single
    module file or a package directory). Returns the tar path."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    name = os.path.splitext(os.path.basename(path.rstrip("/")))[0]
    out = out_path or f"{name}-context.tar"
    with tarfile.open(out, "w") as tar:
        df = render_dockerfile(base_image).encode()
        info = tarfile.TarInfo("Dockerfile")
        info.size = len(df)
        info.mtime = int(time.time())
        tar.addfile(info, io.BytesIO(df))
        if os.path.isdir(path):
            tar.add(path, arcname=f"app/{os.path.basename(path.rstrip('/'))}",
                    filter=_clean)
        else:
            tar.add(path, arcname=f"app/{os.path.basename(path)}",
                    filter=_clean)
    return out


def _clean(info: tarfile.TarInfo) -> Optional[tarfile.TarInfo]:
    base = os.path.basename(info.name)
    if base == "__pycache__" or base.endswith(".pyc"):
        return None
    info.uid = info.gid = 0
    info.uname = info.gname = ""
    return info


def run_builder(builder: str, context_tar: str, tag: str) -> int:
    """Run an external image builder over the context: the builder command
    gets ``-t <tag> -`` appended and the context streamed on stdin (the
    `docker build` contract; buildkit frontends accept the same shape)."""
    cmd = shlex.split(builder) + ["-t", tag, "-"]
    with open(context_tar, "rb") as f:
        proc = subprocess.run(cmd, stdin=f)
    return proc.returncode
