"""Artifact-based graph resolution: ``artifact://name/version#module:Class``.

The api-store registers every uploaded artifact version in the dynstore
(descriptor with content URL + sha256). A deployment may then name its
graph by artifact instead of an import path; the operator (and worker
children via ``DYNAMO_ARTIFACT_PATH``) download the bundle, verify its
digest, extract it into a content-addressed cache dir and import the entry
class from there.

Reference capability: the api-store → operator artifact flow
(deploy/dynamo/api-store upload/download + dynamonimrequest_controller
image/artifact resolution), re-based on our store + HTTP planes.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
from typing import Optional, Tuple

ARTIFACT_SCHEME = "artifact://"
ARTIFACT_PREFIX = "deploy/artifacts/"          # store key prefix
CACHE_DIR = os.path.expanduser("~/.cache/dynamo_tpu/artifacts")


class ArtifactError(RuntimeError):
    pass


def is_artifact_ref(graph: str) -> bool:
    return graph.startswith(ARTIFACT_SCHEME)


def parse_ref(ref: str) -> Tuple[str, Optional[int], str]:
    """``artifact://name/version#module:Class`` -> (name, version|None,
    class_spec). Version omitted or 'latest' means newest."""
    if not is_artifact_ref(ref):
        raise ArtifactError(f"not an artifact ref: {ref!r}")
    rest = ref[len(ARTIFACT_SCHEME):]
    if "#" not in rest:
        raise ArtifactError(
            "artifact ref needs '#module:Class' entry point")
    locator, class_spec = rest.split("#", 1)
    if ":" not in class_spec:
        raise ArtifactError("entry point must be 'module:Class'")
    parts = locator.split("/")
    name = parts[0]
    if not name:
        raise ArtifactError("artifact name is empty")
    version: Optional[int] = None
    if len(parts) > 1 and parts[1] not in ("", "latest"):
        try:
            version = int(parts[1])
        except ValueError:
            raise ArtifactError(f"bad artifact version {parts[1]!r}")
    return name, version, class_spec


def descriptor_key(name: str, version: int) -> str:
    return f"{ARTIFACT_PREFIX}{name}/{version:08d}"


async def register(client, name: str, version: int, url: str,
                   sha256: str, size: int) -> None:
    """Called by the api-store after an upload: make the version
    discoverable through the store."""
    await client.put(descriptor_key(name, version), json.dumps(
        {"name": name, "version": version, "url": url,
         "sha256": sha256, "size": size}).encode())


async def resolve(client, ref: str) -> Tuple[str, str]:
    """Materialize an artifact ref. Returns (extract_dir, class_spec).

    The bundle may be a tarball (extracted as-is) or a single .py file
    (written as module.py per the entry module name)."""
    name, version, class_spec = parse_ref(ref)
    if version is None:
        items = await client.get_prefix(f"{ARTIFACT_PREFIX}{name}/")
        if not items:
            raise ArtifactError(f"artifact {name!r} not registered")
        raw = sorted(items)[-1][1]
    else:
        raw = await client.get(descriptor_key(name, version))
        if raw is None:
            raise ArtifactError(f"artifact {name!r} v{version} not registered")
    desc = json.loads(raw.decode())
    target = os.path.join(CACHE_DIR, name, str(desc["version"]))
    stamp = os.path.join(target, ".sha256")
    if os.path.exists(stamp):
        with open(stamp) as f:
            if f.read().strip() == desc["sha256"]:
                return target, class_spec      # cache hit

    data = await _fetch(desc["url"])
    digest = hashlib.sha256(data).hexdigest()[:len(desc["sha256"])]
    if digest != desc["sha256"]:
        raise ArtifactError(
            f"artifact {name!r} digest mismatch: {digest} != {desc['sha256']}")
    # clear any stale extraction (a differing bundle once lived here):
    # leftovers would stay importable next to the new content
    if os.path.isdir(target):
        import shutil

        shutil.rmtree(target)
    os.makedirs(target, exist_ok=True)
    _extract(data, target, class_spec)
    with open(stamp, "w") as f:
        f.write(desc["sha256"])
    return target, class_spec


async def _fetch(url: str) -> bytes:
    import aiohttp

    async with aiohttp.ClientSession() as s:
        async with s.get(url) as r:
            if r.status != 200:
                raise ArtifactError(f"artifact fetch {url}: HTTP {r.status}")
            return await r.read()


def _extract(data: bytes, target: str, class_spec: str) -> None:
    buf = io.BytesIO(data)
    try:
        with tarfile.open(fileobj=buf) as tf:
            for m in tf.getmembers():
                # no absolute paths / parent escapes out of the bundle
                if m.name.startswith(("/", "..")) or ".." in m.name.split("/"):
                    raise ArtifactError(f"unsafe path in bundle: {m.name}")
            # 'data' filter additionally blocks symlink/device escapes the
            # name check above cannot see
            tf.extractall(target, filter="data")
        return
    except tarfile.ReadError:
        pass
    # single-file bundle: write as the entry module
    mod = class_spec.split(":", 1)[0]
    if "." in mod:
        raise ArtifactError(
            "single-file bundles need a top-level entry module")
    with open(os.path.join(target, f"{mod}.py"), "wb") as f:
        f.write(data)


def load_entry(extract_dir: str, class_spec: str):
    """Import the entry class from an extracted artifact dir.

    sys.modules is version-aware: if the entry's top-level package is
    already imported from a DIFFERENT directory (an older artifact version,
    or another deployment's bundle reusing the name), those modules are
    purged first so this bundle's code actually loads. The extract dir is
    appended (not prepended) to sys.path so bundles cannot shadow framework
    imports."""
    import importlib
    import sys

    top = class_spec.split(":", 1)[0].split(".", 1)[0]
    existing = sys.modules.get(top)
    if existing is not None:
        mod_file = getattr(existing, "__file__", "") or ""
        if not mod_file.startswith(extract_dir + os.sep):
            for k in [k for k in sys.modules
                      if k == top or k.startswith(top + ".")]:
                del sys.modules[k]
    # older versions of the SAME artifact must leave sys.path, or the purged
    # module would simply re-import from them
    family = os.path.dirname(extract_dir) + os.sep
    sys.path[:] = [p for p in sys.path
                   if not (p.startswith(family) and p != extract_dir)]
    if extract_dir not in sys.path:
        sys.path.append(extract_dir)
    importlib.invalidate_caches()
    from ..sdk.serve_child import load_class

    return load_class(class_spec)
