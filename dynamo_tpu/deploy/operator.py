"""The deployment operator: reconciles desired state (Deployment resources
in dynstore) into running service workers.

Level-triggered, like a k8s controller: every event (prefix watch) and every
resync tick runs the same ``_reconcile_all`` pass that diffs desired workers
(graph services × replicas) against actual ones and starts/stops the
difference; dead workers are restarted on the next pass, removed resources
are torn down, and observed state is written back to ``deploy/status/``.

Runners abstract "how a worker runs": ``LocalRunner`` spawns per-service
child processes (the same entry the serve orchestrator uses);
``FakeRunner`` records calls for tests. A real-cluster deployment renders
manifests instead (see manifests.py) — the operator there is k8s itself.

Reference capability: deploy/dynamo/operator/internal/controller/
dynamodeployment_controller.go (reconcile loop, conditions, child-resource
ownership), scoped to this stack's process model.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import time
from typing import Any, Dict, Optional, Tuple

from ..runtime.scale.shards import make_store_client
from ..runtime.store_client import StoreClient
from .crd import (
    DEPLOY_PREFIX,
    Deployment,
    DeploymentStatus,
    ServiceSpec,
    SpecError,
    status_key,
)

log = logging.getLogger("dynamo_tpu.deploy.operator")

WorkerKey = Tuple[str, str, int]        # (dep key, service, replica index)


class Runner:
    """How a single service worker runs. Handles are opaque."""

    def start(self, dep: Deployment, service: str, idx: int,
              sspec: ServiceSpec, class_spec: str) -> Any:
        raise NotImplementedError

    def stop(self, handle: Any) -> None:
        raise NotImplementedError

    def alive(self, handle: Any) -> bool:
        raise NotImplementedError


class LocalRunner(Runner):
    """Spawns ``python -m dynamo_tpu.sdk.serve_child`` per worker."""

    def __init__(self, store: str, platform: str = "cpu"):
        self.store = store
        self.platform = platform

    def start(self, dep, service, idx, sspec, class_spec):
        from ..sdk.service import SERVICE_CONFIG_ENV

        env = dict(os.environ)
        env[SERVICE_CONFIG_ENV] = json.dumps({service: sspec.config}
                                             if sspec.config else {})
        env.update(sspec.envs)
        if sspec.tpu_chips and self.platform == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                                f"{sspec.tpu_chips}")
        elif not sspec.tpu_chips:
            env["JAX_PLATFORMS"] = "cpu"
        return subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.sdk.serve_child",
             class_spec, "--store", dep.spec.store or self.store],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

    def stop(self, handle):
        handle.terminate()
        try:
            handle.wait(timeout=5)
        except subprocess.TimeoutExpired:
            handle.kill()

    def alive(self, handle):
        return handle.poll() is None


class FakeRunner(Runner):
    """Test double: every started worker is a dict whose liveness the test
    flips."""

    def __init__(self):
        self.started = []
        self.stopped = []

    def start(self, dep, service, idx, sspec, class_spec):
        h = {"dep": dep.key(), "service": service, "idx": idx,
             "chips": sspec.tpu_chips, "class": class_spec,
             "envs": dict(sspec.envs), "alive": True}
        self.started.append(h)
        return h

    def stop(self, handle):
        handle["alive"] = False
        self.stopped.append(handle)

    def alive(self, handle):
        return handle["alive"]


class Operator:
    def __init__(self, store_host: str = "127.0.0.1", store_port: int = 4222,
                 runner: Optional[Runner] = None,
                 resync_interval: float = 5.0):
        self.store_host = store_host
        self.store_port = store_port
        self.runner = runner or LocalRunner(f"{store_host}:{store_port}")
        self.resync_interval = resync_interval
        self.client: Optional[StoreClient] = None
        self._desired: Dict[str, Deployment] = {}
        self._workers: Dict[WorkerKey, Any] = {}
        self._artifact_dirs: Dict[str, str] = {}   # dep key -> resolved dir
        self._dirty = asyncio.Event()
        self._stop = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------------
    async def start(self) -> "Operator":
        self.client = await make_store_client(self.store_host,
                                        self.store_port).connect()
        await self.client.watch_prefix(DEPLOY_PREFIX, self._on_event)
        for key, value in await self.client.get_prefix(DEPLOY_PREFIX):
            self._ingest(key, value)
        self._task = asyncio.create_task(self._run())
        self._dirty.set()
        return self

    async def close(self) -> None:
        self._stop.set()
        self._dirty.set()
        if self._task is not None:
            await self._task
        for handle in self._workers.values():
            self.runner.stop(handle)
        self._workers.clear()
        if self.client is not None:
            await self.client.close()

    # ------------------------------------------------------------------
    def _ingest(self, key: str, value: Optional[bytes]) -> None:
        dep_key = key[len(DEPLOY_PREFIX):]
        if value is None:
            self._desired.pop(dep_key, None)
            return
        try:
            dep = Deployment.from_bytes(value)
        except (SpecError, ValueError) as e:
            log.error("invalid deployment at %s: %s", key, e)
            return
        self._desired[dep_key] = dep

    async def _on_event(self, key: str, value: Optional[bytes],
                        deleted: bool = False) -> None:
        self._ingest(key, None if deleted else value)
        self._dirty.set()

    # ------------------------------------------------------------------
    async def _run(self) -> None:
        while not self._stop.is_set():
            self._dirty.clear()
            try:
                await self._reconcile_all()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("reconcile pass failed")
            try:
                await asyncio.wait_for(self._dirty.wait(),
                                       self.resync_interval)
            except asyncio.TimeoutError:
                pass

    async def _reconcile_all(self) -> None:
        # tear down workers of deleted deployments
        live = set(self._desired)
        for wkey in [k for k in self._workers if k[0] not in live]:
            self.runner.stop(self._workers.pop(wkey))
        for dk in [k for k in self._artifact_dirs if k not in live]:
            del self._artifact_dirs[dk]
        removed_status = []
        for dep_key, dep in list(self._desired.items()):
            await self._reconcile_one(dep_key, dep)
        # drop status of deployments that no longer exist
        if self.client is not None:
            for skey, _ in await self.client.get_prefix("deploy/status/"):
                if skey[len("deploy/status/"):] not in live:
                    removed_status.append(skey)
            for skey in removed_status:
                await self.client.delete(skey)

    async def _reconcile_one(self, dep_key: str, dep: Deployment) -> None:
        status = DeploymentStatus(observed_generation=dep.generation)
        try:
            artifact_dir = None
            graph = dep.spec.graph
            from .artifacts import is_artifact_ref, load_entry, resolve

            if is_artifact_ref(graph):
                artifact_dir, class_spec = await resolve(self.client, graph)
                entry = load_entry(artifact_dir, class_spec)
                services = self._collect_services(entry)
                prev_dir = self._artifact_dirs.get(dep_key)
                if prev_dir is not None and prev_dir != artifact_dir:
                    # a new artifact version resolved (latest moved, or the
                    # spec pinned a different one): restart the whole
                    # deployment — a key-only diff would leave old workers
                    # on the previous bundle, a silent mixed-version state
                    for wkey in [k for k in self._workers
                                 if k[0] == dep_key]:
                        self.runner.stop(self._workers.pop(wkey))
                self._artifact_dirs[dep_key] = artifact_dir
            else:
                services = self._resolve_graph(dep)
        except Exception as e:  # noqa: BLE001 - bad graph => failed status
            status.state = "failed"
            status.set_condition("GraphResolved", "False",
                                 "ImportError", str(e))
            await self._write_status(dep, status)
            return
        status.set_condition("GraphResolved", "True", "Resolved",
                             f"{len(services)} services")

        desired: Dict[WorkerKey, Tuple[ServiceSpec, str]] = {}
        for name, (class_spec, default_workers, default_chips) in \
                services.items():
            sspec = dep.spec.services.get(name) or ServiceSpec(
                replicas=default_workers, tpu_chips=default_chips)
            if artifact_dir is not None:
                # worker children must see the extracted bundle on sys.path
                import dataclasses

                sspec = dataclasses.replace(
                    sspec, envs={**sspec.envs,
                                 "DYNAMO_ARTIFACT_PATH": artifact_dir})
            for idx in range(sspec.replicas):
                desired[(dep_key, name, idx)] = (sspec, class_spec)

        # stop: actual workers not desired anymore, or dead ones
        for wkey in [k for k in self._workers
                     if k[0] == dep_key and k not in desired]:
            self.runner.stop(self._workers.pop(wkey))
        for wkey in [k for k, h in self._workers.items()
                     if k[0] == dep_key and not self.runner.alive(h)]:
            self._workers.pop(wkey)

        # start: desired workers with no live handle
        for wkey, (sspec, class_spec) in desired.items():
            if wkey not in self._workers:
                self._workers[wkey] = self.runner.start(
                    dep, wkey[1], wkey[2], sspec, class_spec)

        ready: Dict[str, int] = {}
        for wkey, h in self._workers.items():
            if wkey[0] == dep_key and self.runner.alive(h):
                ready[wkey[1]] = ready.get(wkey[1], 0) + 1
        status.ready_replicas = ready
        want = len(desired)
        have = sum(ready.values())
        status.state = "ready" if have >= want else "deploying"
        status.set_condition("WorkersReady",
                             "True" if have >= want else "False",
                             "Reconciled", f"{have}/{want} workers")
        await self._write_status(dep, status)

    # ------------------------------------------------------------------
    @staticmethod
    def _collect_services(entry) -> Dict[str, Tuple[str, int, int]]:
        """service name -> (class import spec, default workers, default
        chips) for every runnable service reachable from the entry class."""
        from ..sdk.service import collect_graph

        out: Dict[str, Tuple[str, int, int]] = {}
        for cls in collect_graph(entry):
            spec = cls._dynamo_spec
            if not (spec.endpoints or spec.on_start or spec.dependencies):
                continue  # pure grouping node
            out[spec.name] = (f"{cls.__module__}:{cls.__name__}",
                              spec.workers, int(spec.resources.get("tpu", 0)))
        return out

    @staticmethod
    def _resolve_graph(dep: Deployment) -> Dict[str, Tuple[str, int, int]]:
        from ..sdk.serve_child import load_class

        return Operator._collect_services(load_class(dep.spec.graph))

    async def _write_status(self, dep: Deployment,
                            status: DeploymentStatus) -> None:
        if self.client is None:
            return
        await self.client.put(
            status_key(dep.namespace, dep.name),
            json.dumps(status.to_dict()).encode())


async def apply(client: StoreClient, dep: Deployment) -> None:
    """kubectl-apply equivalent: upsert the resource (bumping generation)."""
    from .crd import deploy_key

    key = deploy_key(dep.namespace, dep.name)
    old = await client.get(key)
    if old is not None:
        try:
            dep.generation = Deployment.from_bytes(old).generation + 1
        except (SpecError, ValueError):
            pass
    await client.put(key, dep.to_bytes())


async def delete(client: StoreClient, namespace: str, name: str) -> bool:
    from .crd import deploy_key

    return await client.delete(deploy_key(namespace, name))


async def get_status(client: StoreClient, namespace: str,
                     name: str) -> Optional[DeploymentStatus]:
    raw = await client.get(status_key(namespace, name))
    if raw is None:
        return None
    return DeploymentStatus.from_dict(json.loads(raw.decode()))
