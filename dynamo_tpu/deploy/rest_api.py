"""REST adapter: drive a REAL Kubernetes apiserver with the reconciler.

:class:`RestKubeApi` implements the exact method surface
:class:`~dynamo_tpu.deploy.kube.FakeKubeApi` exposes (apply/get/list/
delete), so ``KubeReconciler(api=RestKubeApi(...))`` reconciles an actual
cluster with the identical loop (VERDICT r3 missing #3; reference operator:
deploy/dynamo/operator/internal/controller/
dynamodeployment_controller.go:68, a client-go controller).

- ``apply`` is true server-side apply: ``PATCH ...?fieldManager=dynamo-tpu
  &force=true`` with ``application/apply-patch+yaml`` (JSON is a YAML
  subset, so the manifest is sent as-is).
- ``list`` uses ``labelSelector``; ``delete`` requests foreground
  propagation so ownerReference children are collected like the fake's
  cascade.
- Auth: bearer token (+ optional CA / insecure TLS), or loaded from a
  kubeconfig's current-context cluster+user. Stdlib-only (urllib).
"""

from __future__ import annotations

import base64
import json
import os
import ssl
import tempfile
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

# kind -> (apiVersion, plural). Extend via register_kind for CRDs beyond
# ours. Matches the kinds manifests.py renders.
_KINDS: Dict[str, Tuple[str, str]] = {
    "DynamoDeployment": ("dynamo.tpu/v1alpha1", "dynamodeployments"),
    "Deployment": ("apps/v1", "deployments"),
    "Service": ("v1", "services"),
    "ConfigMap": ("v1", "configmaps"),
    "Secret": ("v1", "secrets"),
    "Pod": ("v1", "pods"),
    "Ingress": ("networking.k8s.io/v1", "ingresses"),
}


def register_kind(kind: str, api_version: str, plural: str) -> None:
    _KINDS[kind] = (api_version, plural)


class KubeApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"apiserver returned {status}: {body[:300]}")
        self.status = status
        self.body = body


class RestKubeApi:
    """FakeKubeApi-surface adapter over the Kubernetes REST API."""

    def __init__(self, base_url: str, token: Optional[str] = None,
                 ca_file: Optional[str] = None,
                 insecure_skip_verify: bool = False,
                 field_manager: str = "dynamo-tpu",
                 force: bool = True,
                 timeout: float = 30.0):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.field_manager = field_manager
        # force=True (default) is the controller stance: this manager owns
        # what it renders. force=False surfaces SSA conflicts as
        # KubeApiError(409) instead — for co-managed objects.
        self.force = force
        self.timeout = timeout
        if base_url.startswith("https"):
            if insecure_skip_verify:
                self._ctx: Optional[ssl.SSLContext] = \
                    ssl._create_unverified_context()
            else:
                self._ctx = ssl.create_default_context(cafile=ca_file)
        else:
            self._ctx = None

    # ------------------------------------------------------------------
    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None,
                        context: Optional[str] = None,
                        **kw) -> "RestKubeApi":
        """Build from a kubeconfig (current-context unless ``context``).
        Supports token auth and cluster CA (inline or file); client-cert
        auth is out of scope for this adapter."""
        path = path or os.environ.get(
            "KUBECONFIG", os.path.expanduser("~/.kube/config"))
        cfg = _load_yamlish(path)
        ctx_name = context or cfg.get("current-context")
        ctx = _named(cfg.get("contexts", []), ctx_name)["context"]
        cluster = _named(cfg.get("clusters", []), ctx["cluster"])["cluster"]
        user = _named(cfg.get("users", []), ctx["user"])["user"]
        token = user.get("token")
        ca_file = cluster.get("certificate-authority")
        ca_data = cluster.get("certificate-authority-data")
        if ca_data and not ca_file:
            f = tempfile.NamedTemporaryFile(
                "wb", suffix=".crt", delete=False)
            f.write(base64.b64decode(ca_data))
            f.close()
            ca_file = f.name
        return cls(cluster["server"], token=token, ca_file=ca_file,
                   insecure_skip_verify=bool(
                       cluster.get("insecure-skip-tls-verify")), **kw)

    # ------------------------------------------------------------------
    def _path(self, kind: str, namespace: Optional[str],
              name: Optional[str] = None,
              api_version: Optional[str] = None) -> str:
        if api_version is None:
            if kind not in _KINDS:
                raise KeyError(f"unknown kind {kind!r}; register_kind() it")
            api_version, plural = _KINDS[kind]
        else:
            plural = (_KINDS[kind][1] if kind in _KINDS
                      else kind.lower() + "s")
        root = ("/api/" + api_version if "/" not in api_version
                else "/apis/" + api_version)
        p = root
        if namespace is not None:
            p += f"/namespaces/{namespace}"
        p += "/" + plural
        if name is not None:
            p += "/" + name
        return p

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None,
                 content_type: str = "application/json",
                 query: Optional[Dict[str, str]] = None
                 ) -> Tuple[int, Any]:
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", content_type)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ctx) as r:
                raw = r.read()
                return r.status, (json.loads(raw) if raw else None)
        except urllib.error.HTTPError as e:
            raw = e.read().decode(errors="replace")
            if e.code in (404, 409):
                return e.code, raw
            raise KubeApiError(e.code, raw) from e

    # ------------------------------------------------------------------
    # FakeKubeApi surface
    # ------------------------------------------------------------------
    def apply(self, manifest: Dict[str, Any]) -> Dict[str, Any]:
        md = manifest.get("metadata", {})
        ns = md.get("namespace", "default")
        path = self._path(manifest["kind"], ns, md["name"],
                          api_version=manifest.get("apiVersion"))
        status, obj = self._request(
            "PATCH", path, body=manifest,
            content_type="application/apply-patch+yaml",
            query={"fieldManager": self.field_manager,
                   "force": "true" if self.force else "false"})
        if status == 404 or not isinstance(obj, dict):
            raise KubeApiError(status, str(obj))
        return obj

    def get(self, kind: str, namespace: str,
            name: str) -> Optional[Dict[str, Any]]:
        status, obj = self._request("GET", self._path(kind, namespace, name))
        if status == 404:
            return None
        return obj

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
        query = {}
        if labels:
            query["labelSelector"] = ",".join(
                f"{k}={v}" for k, v in sorted(labels.items()))
        status, obj = self._request("GET", self._path(kind, namespace),
                                    query=query or None)
        if status == 404 or not isinstance(obj, dict):
            return []
        items = obj.get("items", [])
        # servers omit kind on list items; the reconciler keys on it
        for it in items:
            it.setdefault("kind", kind)
        return items

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        status, _ = self._request(
            "DELETE", self._path(kind, namespace, name),
            body={"propagationPolicy": "Foreground"})
        return status != 404


# ---------------------------------------------------------------------------
# kubeconfig helpers (minimal YAML subset: kubeconfigs are flat mappings +
# lists of mappings, which this parser covers; exotic YAML → use JSON
# kubeconfig or pass explicit args)
# ---------------------------------------------------------------------------

def _named(seq: List[Dict[str, Any]], name: str) -> Dict[str, Any]:
    for item in seq:
        if item.get("name") == name:
            return item
    raise KeyError(f"kubeconfig entry {name!r} not found")


def _load_yamlish(path: str) -> Dict[str, Any]:
    text = open(path).read()
    if text.lstrip().startswith("{"):
        return json.loads(text)
    try:
        import yaml  # type: ignore

        return yaml.safe_load(text)
    except ImportError:
        pass
    return _mini_yaml(text)


def _mini_yaml(text: str) -> Dict[str, Any]:
    """Tiny YAML-subset parser good enough for stock kubeconfigs:
    nested mappings, block lists of mappings, scalar values."""
    root: Dict[str, Any] = {}
    # stack of (indent, container)
    stack: List[Tuple[int, Any]] = [(-1, root)]
    last_key: Optional[str] = None
    for raw in text.splitlines():
        if not raw.strip() or raw.lstrip().startswith("#"):
            continue
        indent = len(raw) - len(raw.lstrip())
        line = raw.strip()
        while stack and indent <= stack[-1][0]:
            stack.pop()
        parent = stack[-1][1]
        if line.startswith("- "):
            item: Dict[str, Any] = {}
            if not isinstance(parent, list):
                # "key:\n- a" — attach the list to the pending key
                lst: List[Any] = []
                parent[last_key] = lst
                parent = lst
                stack.append((indent - 1, lst))
            body = line[2:]
            if ":" in body:
                k, _, v = body.partition(":")
                v = v.strip().strip('"\'')
                if v:
                    item[k.strip()] = _scalar(v)
                else:
                    item[k.strip()] = {}
            parent.append(item)
            stack.append((indent, item))
            continue
        k, _, v = line.partition(":")
        k = k.strip()
        v = v.strip().strip('"\'')
        if v:
            parent[k] = _scalar(v)
        else:
            child: Dict[str, Any] = {}
            parent[k] = child
            stack.append((indent, child))
        last_key = k
    return root


def _scalar(v: str) -> Any:
    if v in ("true", "True"):
        return True
    if v in ("false", "False"):
        return False
    return v
