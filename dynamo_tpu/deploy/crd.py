"""Deployment resource model — the CRD equivalent.

A ``Deployment`` names a service graph (an SDK ``@service`` entry point or
an artifact in the api-store) plus per-service overrides (replicas, TPU
chips, config). Desired state is stored under ``deploy/deployments/{ns}/
{name}``; the operator writes observed state to ``deploy/status/{ns}/
{name}``.

Reference capability: deploy/dynamo/operator/api/v1alpha1/
dynamodeployment_types.go:30-80 (DynamoDeploymentSpec{DynamoNim, Services},
Status{State, Conditions}) and dynamonimdeployment_types.go (per-service
resources/replicas/envs).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

VALID_STATES = ("pending", "deploying", "ready", "degraded", "failed",
                "terminating")


class SpecError(ValueError):
    """Malformed deployment resource."""


@dataclass
class ServiceSpec:
    """Per-service override inside a deployment (DynamoNimDeployment row)."""

    replicas: int = 1
    tpu_chips: int = 0                  # chips per replica (0 = CPU service)
    config: Dict[str, Any] = field(default_factory=dict)  # service YAML config
    envs: Dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"replicas": self.replicas, "tpu_chips": self.tpu_chips,
                "config": self.config, "envs": self.envs}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServiceSpec":
        replicas = int(d.get("replicas", 1))
        if replicas < 0:
            raise SpecError("replicas must be >= 0")
        chips = int(d.get("tpu_chips", 0))
        if chips < 0:
            raise SpecError("tpu_chips must be >= 0")
        return cls(replicas=replicas, tpu_chips=chips,
                   config=dict(d.get("config", {}) or {}),
                   envs={str(k): str(v)
                         for k, v in (d.get("envs", {}) or {}).items()})


@dataclass
class IngressSpec:
    """External traffic for the graph's HTTP frontend (reference renders
    Ingress + an Envoy header-routed debug/production split,
    deploy/dynamo/operator/internal/envoy/envoy.go)."""

    enabled: bool = False
    host: Optional[str] = None          # None => match-all virtual host
    service: str = "Frontend"           # graph service that serves HTTP
    port: int = 8080
    path: str = "/"
    tls_secret: Optional[str] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    # Envoy sidecar: requests carrying ``debug_header: debug_value`` route
    # to the debug backend; everything else to the frontend service
    envoy: bool = False
    debug_header: str = "x-dynamo-debug"
    debug_value: str = "1"
    debug_service: Optional[str] = None  # None => same service

    def to_dict(self) -> Dict[str, Any]:
        return {"enabled": self.enabled, "host": self.host,
                "service": self.service, "port": self.port,
                "path": self.path, "tls_secret": self.tls_secret,
                "annotations": self.annotations, "envoy": self.envoy,
                "debug_header": self.debug_header,
                "debug_value": self.debug_value,
                "debug_service": self.debug_service}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "IngressSpec":
        port = int(d.get("port", 8080))
        if not (0 < port < 65536):
            raise SpecError(f"ingress.port invalid: {port}")
        return cls(enabled=bool(d.get("enabled", False)),
                   host=d.get("host"),
                   service=str(d.get("service", "Frontend")),
                   port=port,
                   path=str(d.get("path", "/")),
                   tls_secret=d.get("tls_secret"),
                   annotations={str(k): str(v) for k, v in
                                (d.get("annotations", {}) or {}).items()},
                   envoy=bool(d.get("envoy", False)),
                   debug_header=str(d.get("debug_header", "x-dynamo-debug")),
                   debug_value=str(d.get("debug_value", "1")),
                   debug_service=d.get("debug_service"))


@dataclass
class DeploymentSpec:
    graph: str                          # "pkg.module:EntryService" or artifact
    services: Dict[str, ServiceSpec] = field(default_factory=dict)
    store: Optional[str] = None         # host:port of shared dynstore
    platform: str = "auto"              # auto | tpu | cpu
    ingress: Optional[IngressSpec] = None

    def to_dict(self) -> Dict[str, Any]:
        return {"graph": self.graph,
                "services": {k: v.to_dict() for k, v in self.services.items()},
                "store": self.store, "platform": self.platform,
                "ingress": self.ingress.to_dict() if self.ingress else None}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSpec":
        graph = d.get("graph")
        if not isinstance(graph, str) or not graph:
            raise SpecError("spec.graph must be a non-empty string")
        platform = d.get("platform", "auto")
        if platform not in ("auto", "tpu", "cpu"):
            raise SpecError(f"spec.platform invalid: {platform!r}")
        return cls(
            graph=graph,
            services={str(k): ServiceSpec.from_dict(v or {})
                      for k, v in (d.get("services", {}) or {}).items()},
            store=d.get("store"),
            platform=platform,
            ingress=(IngressSpec.from_dict(d["ingress"])
                     if d.get("ingress") else None),
        )


@dataclass
class Condition:
    """k8s-style status condition (metav1.Condition shape)."""

    type: str
    status: str                         # "True" | "False"
    reason: str = ""
    message: str = ""
    last_transition: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {"type": self.type, "status": self.status,
                "reason": self.reason, "message": self.message,
                "lastTransition": self.last_transition}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Condition":
        return cls(d.get("type", ""), d.get("status", ""),
                   d.get("reason", ""), d.get("message", ""),
                   float(d.get("lastTransition", 0.0)))


@dataclass
class DeploymentStatus:
    state: str = "pending"
    conditions: List[Condition] = field(default_factory=list)
    ready_replicas: Dict[str, int] = field(default_factory=dict)
    observed_generation: int = 0

    def set_condition(self, ctype: str, status: str, reason: str = "",
                      message: str = "") -> None:
        for c in self.conditions:
            if c.type == ctype:
                if c.status != status:
                    c.last_transition = time.time()
                c.status, c.reason, c.message = status, reason, message
                return
        self.conditions.append(
            Condition(ctype, status, reason, message, time.time()))

    def to_dict(self) -> Dict[str, Any]:
        return {"state": self.state,
                "conditions": [c.to_dict() for c in self.conditions],
                "readyReplicas": self.ready_replicas,
                "observedGeneration": self.observed_generation}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentStatus":
        return cls(
            state=d.get("state", "pending"),
            conditions=[Condition.from_dict(c)
                        for c in d.get("conditions", [])],
            ready_replicas=dict(d.get("readyReplicas", {})),
            observed_generation=int(d.get("observedGeneration", 0)),
        )


@dataclass
class Deployment:
    name: str
    namespace: str = "default"
    spec: DeploymentSpec = None  # type: ignore[assignment]
    status: DeploymentStatus = field(default_factory=DeploymentStatus)
    generation: int = 1

    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def to_dict(self) -> Dict[str, Any]:
        return {"apiVersion": "dynamo.tpu/v1alpha1",
                "kind": "DynamoDeployment",
                "metadata": {"name": self.name, "namespace": self.namespace,
                             "generation": self.generation},
                "spec": self.spec.to_dict()}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Deployment":
        meta = d.get("metadata") or {}
        name = meta.get("name")
        if not isinstance(name, str) or not name:
            raise SpecError("metadata.name is required")
        kind = d.get("kind", "DynamoDeployment")
        if kind != "DynamoDeployment":
            raise SpecError(f"unsupported kind {kind!r}")
        return cls(name=name,
                   namespace=meta.get("namespace", "default"),
                   spec=DeploymentSpec.from_dict(d.get("spec") or {}),
                   generation=int(meta.get("generation", 1)))

    # serialization on the store wire
    def to_bytes(self) -> bytes:
        return json.dumps(self.to_dict()).encode()

    @classmethod
    def from_bytes(cls, b: bytes) -> "Deployment":
        return cls.from_dict(json.loads(b.decode()))


# store key layout
DEPLOY_PREFIX = "deploy/deployments/"
STATUS_PREFIX = "deploy/status/"


def deploy_key(namespace: str, name: str) -> str:
    return f"{DEPLOY_PREFIX}{namespace}/{name}"


def status_key(namespace: str, name: str) -> str:
    return f"{STATUS_PREFIX}{namespace}/{name}"
