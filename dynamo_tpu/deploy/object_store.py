"""Artifact object storage backends for the api-store.

The reference api-store uploads artifact bundles to S3 or a PVC
(ai_dynamo_store/api/dynamo.py:48,550-565); here the same seam is an async
key/value object interface with two backends:

- :class:`LocalFsStore` — keys are paths under a root directory (the PVC
  analogue, and the default).
- :class:`S3Store` — a minimal S3 REST subset (PUT/GET/DELETE object +
  ListObjectsV2) against any S3-compatible endpoint; unsigned requests, so
  it pairs with in-cluster minio-style gateways or :class:`MinioStub`.

:class:`MinioStub` is an in-process aiohttp server speaking that same
subset, used by tests (and usable as a dev fixture).

Pick a backend with a storage URL: ``file:///var/artifacts`` or
``s3://bucket?endpoint=http://minio:9000``.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlsplit
from xml.sax.saxutils import escape


class ObjectStore:
    async def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    async def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    async def delete(self, key: str) -> bool:
        raise NotImplementedError

    async def list(self, prefix: str = "") -> List[str]:
        raise NotImplementedError

    async def close(self) -> None:
        pass


def open_object_store(url: str) -> ObjectStore:
    """``file:///path`` (or a bare path) | ``s3://bucket?endpoint=...``."""
    if url.startswith("s3://"):
        parts = urlsplit(url)
        q = parse_qs(parts.query)
        endpoint = (q.get("endpoint") or [None])[0]
        if not endpoint:
            raise ValueError("s3:// storage needs ?endpoint=http://host:port")
        return S3Store(endpoint, parts.netloc)
    if url.startswith("file://"):
        url = urlsplit(url).path
    return LocalFsStore(url)


class LocalFsStore(ObjectStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        root = os.path.abspath(self.root)
        p = os.path.abspath(os.path.normpath(os.path.join(self.root, key)))
        # commonpath, not startswith: a sibling dir whose name has the
        # root as a prefix (root=/data/artifacts, key=../artifacts-x/f)
        # must not pass the escape guard
        if p != root and os.path.commonpath([root, p]) != root:
            raise ValueError(f"key escapes root: {key!r}")
        return p

    async def put(self, key: str, data: bytes) -> None:
        p = self._path(key)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        with open(p, "wb") as f:
            f.write(data)

    async def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (OSError, ValueError):
            return None

    async def delete(self, key: str) -> bool:
        try:
            os.unlink(self._path(key))
            return True
        except OSError:
            return False

    async def list(self, prefix: str = "") -> List[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for f in files:
                rel = os.path.relpath(os.path.join(dirpath, f), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


class S3Store(ObjectStore):
    """Minimal S3 REST client (path-style, unsigned)."""

    def __init__(self, endpoint: str, bucket: str):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self._session = None

    async def _sess(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    def _url(self, key: str) -> str:
        from urllib.parse import quote

        return f"{self.endpoint}/{self.bucket}/{quote(key)}"

    async def put(self, key: str, data: bytes) -> None:
        s = await self._sess()
        async with s.put(self._url(key), data=data) as resp:
            if resp.status >= 300:
                raise IOError(f"s3 put {key}: {resp.status}")

    async def get(self, key: str) -> Optional[bytes]:
        s = await self._sess()
        async with s.get(self._url(key)) as resp:
            if resp.status == 404:
                return None
            if resp.status >= 300:
                raise IOError(f"s3 get {key}: {resp.status}")
            return await resp.read()

    async def delete(self, key: str) -> bool:
        # S3 DELETE is 204 whether or not the key existed; the ObjectStore
        # contract (and the api-store's 404 path) needs the truth
        if await self.get(key) is None:
            return False
        s = await self._sess()
        async with s.delete(self._url(key)) as resp:
            return resp.status < 300

    async def list(self, prefix: str = "") -> List[str]:
        from urllib.parse import quote

        s = await self._sess()
        keys: List[str] = []
        token: Optional[str] = None
        while True:     # ListObjectsV2 pages at 1000 keys
            url = (f"{self.endpoint}/{self.bucket}"
                   f"?list-type=2&prefix={quote(prefix)}")
            if token:
                url += f"&continuation-token={quote(token)}"
            async with s.get(url) as resp:
                if resp.status >= 300:
                    raise IOError(f"s3 list {prefix}: {resp.status}")
                text = await resp.text()
            keys.extend(re.findall(r"<Key>([^<]*)</Key>", text))
            m = re.search(r"<NextContinuationToken>([^<]*)"
                          r"</NextContinuationToken>", text)
            truncated = re.search(r"<IsTruncated>true</IsTruncated>", text)
            if not (truncated and m):
                return sorted(keys)
            token = m.group(1)

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class MinioStub:
    """In-process S3-compatible object server (the subset S3Store speaks):
    PUT/GET/DELETE ``/{bucket}/{key}`` and ListObjectsV2."""

    def __init__(self):
        self.buckets: Dict[str, Dict[str, bytes]] = {}
        self._runner = None
        self.port = 0

    async def start(self, port: int = 0) -> int:
        from aiohttp import web

        app = web.Application(client_max_size=1 << 30)
        app.router.add_get("/{bucket}", self._list)
        app.router.add_put("/{bucket}/{key:.+}", self._put)
        app.router.add_get("/{bucket}/{key:.+}", self._get)
        app.router.add_delete("/{bucket}/{key:.+}", self._delete)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, "127.0.0.1", port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # ------------------------------------------------------------------
    async def _put(self, req):
        from aiohttp import web

        b = self.buckets.setdefault(req.match_info["bucket"], {})
        b[req.match_info["key"]] = await req.read()
        return web.Response(text="")

    async def _get(self, req):
        from aiohttp import web

        b = self.buckets.get(req.match_info["bucket"], {})
        data = b.get(req.match_info["key"])
        if data is None:
            raise web.HTTPNotFound()
        return web.Response(body=data,
                            content_type="application/octet-stream")

    async def _delete(self, req):
        from aiohttp import web

        b = self.buckets.get(req.match_info["bucket"], {})
        b.pop(req.match_info["key"], None)
        return web.Response(status=204)

    async def _list(self, req):
        from aiohttp import web

        prefix = req.query.get("prefix", "")
        b = self.buckets.get(req.match_info["bucket"], {})
        keys = sorted(k for k in b if k.startswith(prefix))
        body = ("<?xml version=\"1.0\"?><ListBucketResult>"
                + "".join(f"<Contents><Key>{escape(k)}</Key></Contents>"
                          for k in keys)
                + "</ListBucketResult>")
        return web.Response(text=body, content_type="application/xml")
