"""Kubernetes-style reconciliation for DynamoDeployment resources.

Two pieces:

- :class:`FakeKubeApi` — an in-memory apiserver double with the semantics
  reconciliation actually depends on: server-side apply (create-or-update,
  resourceVersion bump only on change), label-selector list, uid-based
  ``ownerReferences`` cascade delete, and a minimal Deployment→Pods
  controller sim so scale-up/down and pod-crash/restart paths are real.
- :class:`KubeReconciler` — diffs rendered manifests (manifests.py) against
  the live API: ensures the parent CR, applies drift only, garbage-collects
  children that fell out of the desired set (by label + owner), and writes
  Available/Progressing conditions back onto the CR status.

The reconciler is transport-agnostic: anything with the FakeKubeApi method
surface (apply/get/list/delete) works, so a thin kubectl/REST adapter can
drive a real cluster with the identical loop.

Reference capability: deploy/dynamo/operator/internal/controller/
dynamodeployment_controller.go:68 (reconcile-with-owned-children,
conditions), envtest-style coverage via the fake API.
"""

from __future__ import annotations

import copy
import itertools
import time
from typing import Any, Dict, List, Optional, Tuple

from .crd import Deployment
from .manifests import render_manifests

GROUP = "dynamo.tpu/v1alpha1"
CR_KIND = "DynamoDeployment"


def _key(kind: str, namespace: str, name: str) -> Tuple[str, str, str]:
    return (kind, namespace, name)


class KubeConflict(RuntimeError):
    """409: SSA field-manager conflict or resourceVersion race — the error
    classes a real apiserver generates that reference controllers must
    handle (envtest surfaces both; VERDICT r4 item #6).
    ``conflicts`` lists the contested field paths (empty for rv races)."""

    def __init__(self, msg: str, conflicts: Optional[List[str]] = None):
        super().__init__(msg)
        self.conflicts = conflicts or []


class FakeKubeApi:
    """In-memory apiserver double (see module docstring)."""

    # the fields server-side apply merges (and tracks ownership for)
    _MANAGED = ("spec", "data", "labels", "ownerReferences", "finalizers")

    def __init__(self):
        self.objects: Dict[Tuple[str, str, str], Dict[str, Any]] = {}
        self._uids = itertools.count(1)
        self._rv = itertools.count(1)
        # per-object: managed field path -> fieldManager that last set it
        self._managers: Dict[Tuple[str, str, str], Dict[str, str]] = {}
        self.apply_count = 0        # applies that actually changed an object

    # ------------------------------------------------------------------
    def apply(self, manifest: Dict[str, Any],
              field_manager: str = "dynamo-tpu",
              force: bool = True) -> Dict[str, Any]:
        """Server-side apply: create or update. resourceVersion bumps (and
        apply_count increments) only when the spec-level content changed.

        Real-apiserver semantics the reconciler faces (VERDICT r4 #6):

        - a manifest carrying ``metadata.resourceVersion`` that is stale
          raises :class:`KubeConflict` (optimistic-concurrency race);
        - changing a field another ``field_manager`` owns without ``force``
          raises :class:`KubeConflict` listing the contested paths;
          ``force=True`` (the operator default, matching RestKubeApi's
          ``force=true`` query) takes ownership instead.
        """
        m = copy.deepcopy(manifest)
        md = m.setdefault("metadata", {})
        ns = md.get("namespace", "default")
        k = _key(m["kind"], ns, md["name"])
        existing = self.objects.get(k)
        if existing is not None:
            want_rv = md.get("resourceVersion")
            have_rv = existing["metadata"].get("resourceVersion")
            if want_rv is not None and want_rv != have_rv:
                raise KubeConflict(
                    f"Operation cannot be fulfilled on {m['kind']} "
                    f"{md['name']!r}: the object has been modified "
                    f"(resourceVersion {want_rv} != {have_rv})")
            merged = copy.deepcopy(existing)
            changed: List[str] = []
            for field in ("spec", "data"):
                if field in m and m[field] != existing.get(field):
                    merged[field] = m[field]
                    changed.append(field)
            want_md = {kk: vv for kk, vv in md.items()
                       if kk in ("labels", "ownerReferences", "finalizers")}
            for kk, vv in want_md.items():
                if existing["metadata"].get(kk) != vv:
                    merged["metadata"][kk] = vv
                    changed.append(kk)
            owners = self._managers.setdefault(k, {})
            contested = [f for f in changed
                         if owners.get(f, field_manager) != field_manager]
            if contested and not force:
                raise KubeConflict(
                    f"Apply failed with {len(contested)} conflict(s): "
                    f"fields {contested} owned by "
                    f"{sorted({owners[f] for f in contested})}",
                    conflicts=contested)
            if changed:
                for f in changed:
                    owners[f] = field_manager
                merged["metadata"]["resourceVersion"] = str(next(self._rv))
                self.objects[k] = merged
                self.apply_count += 1
                # clearing the last finalizer on a deleting object completes
                # the pending delete (the finalizer contract)
                if (merged["metadata"].get("deletionTimestamp")
                        and not merged["metadata"].get("finalizers")):
                    self._finish_delete(k)
                    return merged
                self._sync_controllers(merged)
            return self.objects.get(k, merged)
        md.setdefault("namespace", ns)
        md["uid"] = f"uid-{next(self._uids)}"
        md["resourceVersion"] = str(next(self._rv))
        self.objects[k] = m
        self._managers[k] = {f: field_manager for f in self._MANAGED
                             if f in m or f in md}
        self.apply_count += 1
        self._sync_controllers(m)
        return m

    def get(self, kind: str, namespace: str,
            name: str) -> Optional[Dict[str, Any]]:
        return self.objects.get(_key(kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None,
             labels: Optional[Dict[str, str]] = None) -> List[Dict[str, Any]]:
        out = []
        for (k, ns, _), obj in self.objects.items():
            if k != kind:
                continue
            if namespace is not None and ns != namespace:
                continue
            ol = obj["metadata"].get("labels", {})
            if labels and any(ol.get(lk) != lv for lk, lv in labels.items()):
                continue
            out.append(obj)
        return out

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        k = _key(kind, namespace, name)
        obj = self.objects.get(k)
        if obj is None:
            return False
        if obj["metadata"].get("finalizers"):
            # finalizer-blocked: mark deleting, keep the object until every
            # finalizer is removed (real apiserver semantics — controllers
            # that ignore deletionTimestamp wedge here, which is the point)
            obj["metadata"].setdefault("deletionTimestamp",
                                       time.strftime("%Y-%m-%dT%H:%M:%SZ"))
            obj["metadata"]["resourceVersion"] = str(next(self._rv))
            return True
        self._finish_delete(k)
        return True

    def _finish_delete(self, k: Tuple[str, str, str]) -> None:
        obj = self.objects.pop(k, None)
        self._managers.pop(k, None)
        if obj is None:
            return
        # ownerReferences cascade (uid-based, like the real GC controller)
        uid = obj["metadata"].get("uid")
        for k2, o2 in list(self.objects.items()):
            refs = o2["metadata"].get("ownerReferences", [])
            if any(r.get("uid") == uid for r in refs):
                self.delete(*k2)

    # ------------------------------------------------------------------
    # minimal controller sims
    # ------------------------------------------------------------------
    def _sync_controllers(self, obj: Dict[str, Any]) -> None:
        if obj["kind"] == "Deployment":
            self._sync_deployment_pods(obj)

    def _sync_deployment_pods(self, dep_obj: Dict[str, Any]) -> None:
        """Deployment controller sim: materialize `replicas` running Pods
        owned by the Deployment; surplus pods are removed."""
        md = dep_obj["metadata"]
        ns = md["namespace"]
        want = int(dep_obj["spec"].get("replicas", 1))
        labels = dict(dep_obj["spec"]["selector"]["matchLabels"])
        owned = [p for p in self.list("Pod", ns, labels)
                 if any(r.get("uid") == md["uid"]
                        for r in p["metadata"].get("ownerReferences", []))]
        alive = [p for p in owned
                 if p.get("status", {}).get("phase") == "Running"]
        for p in owned:
            if p.get("status", {}).get("phase") != "Running":
                self.objects.pop(_key("Pod", ns, p["metadata"]["name"]), None)
        for i in range(want - len(alive)):
            name = f"{md['name']}-pod-{next(self._uids)}"
            self.objects[_key("Pod", ns, name)] = {
                "apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": name, "namespace": ns,
                             "uid": f"uid-{next(self._uids)}",
                             "resourceVersion": str(next(self._rv)),
                             "labels": labels,
                             "ownerReferences": [{
                                 "kind": "Deployment", "name": md["name"],
                                 "uid": md["uid"]}]},
                "status": {"phase": "Running"},
            }
        for p in alive[want:]:
            self.objects.pop(_key("Pod", ns, p["metadata"]["name"]), None)

    def fail_pod(self, namespace: str, name: str) -> None:
        """Test hook: mark a pod dead (kubelet's view of a crash)."""
        obj = self.objects[_key("Pod", namespace, name)]
        obj["status"] = {"phase": "Failed"}

    def resync(self) -> None:
        """Run every controller sim once (the watch loop a real cluster
        runs continuously)."""
        for obj in list(self.objects.values()):
            if obj["kind"] == "Deployment":
                self._sync_deployment_pods(obj)


class KubeReconciler:
    """Level-triggered reconcile of one Deployment resource against a k8s
    API. Each pass: ensure CR, apply drift, GC orphans, update conditions."""

    def __init__(self, api: FakeKubeApi, services: Dict[str, tuple],
                 image: str = "dynamo-tpu:latest",
                 include_store: bool = True):
        self.api = api
        self.services = services
        self.image = image
        self.include_store = include_store

    # ------------------------------------------------------------------
    def reconcile(self, dep: Deployment) -> Dict[str, Any]:
        ns = dep.namespace
        cr = self.api.apply({
            "apiVersion": GROUP, "kind": CR_KIND,
            "metadata": {"name": dep.name, "namespace": ns,
                         "labels": {"app.kubernetes.io/part-of":
                                    "dynamo-tpu"}},
            "spec": dep.spec.to_dict() if hasattr(dep.spec, "to_dict")
            else dep.spec.__dict__,
        })
        owner = [{"kind": CR_KIND, "name": dep.name,
                  "uid": cr["metadata"]["uid"]}]

        desired = render_manifests(dep, self.services, image=self.image,
                                   include_store=self.include_store)
        desired_keys = set()
        for m in desired:
            m = copy.deepcopy(m)
            m["metadata"].setdefault("namespace", ns)
            if m["metadata"].get("name") != "dynstore":
                m["metadata"]["ownerReferences"] = owner
            self.api.apply(m)
            desired_keys.add((m["kind"], m["metadata"]["namespace"],
                              m["metadata"]["name"]))

        # GC: anything owned by this CR that is no longer desired
        for kind in ("Deployment", "Service", "ConfigMap", "Ingress"):
            for obj in self.api.list(kind, ns):
                md = obj["metadata"]
                if not any(r.get("uid") == cr["metadata"]["uid"]
                           for r in md.get("ownerReferences", [])):
                    continue
                if (kind, ns, md["name"]) not in desired_keys:
                    self.api.delete(kind, ns, md["name"])

        # pump the fake's controller sims; a real apiserver's controllers
        # run on their own, so the adapter has no resync
        resync = getattr(self.api, "resync", None)
        if resync is not None:
            resync()
        return self._update_status(dep, cr)

    # ------------------------------------------------------------------
    def _update_status(self, dep: Deployment,
                       cr: Dict[str, Any]) -> Dict[str, Any]:
        ns = dep.namespace
        total_want = 0
        total_ready = 0
        per_service = {}
        for name in self.services:
            dname = f"{dep.name}-{name.lower()}"
            obj = self.api.get("Deployment", ns, dname)
            if obj is None:
                continue
            want = int(obj["spec"].get("replicas", 1))
            labels = obj["spec"]["selector"]["matchLabels"]
            ready = len([p for p in self.api.list("Pod", ns, labels)
                         if p.get("status", {}).get("phase") == "Running"])
            per_service[name] = {"want": want, "ready": ready}
            total_want += want
            total_ready += ready
        available = total_want > 0 and total_ready >= total_want
        cr["status"] = {
            "conditions": [
                {"type": "Available",
                 "status": "True" if available else "False",
                 "lastTransitionTime": time.time()},
                {"type": "Progressing",
                 "status": "False" if available else "True",
                 "lastTransitionTime": time.time()},
            ],
            "services": per_service,
        }
        return cr["status"]
