"""Render a Deployment resource into Kubernetes manifests.

For a real cluster the operator's job is done by k8s itself: this module
turns one ``Deployment`` into the child resources the reference's Go
controller creates — a ConfigMap carrying per-service config, a k8s
Deployment + Service per graph service, and (once per namespace) the
dynstore coordination service. TPU workers request ``google.com/tpu``
resources with the standard TPU-VM node selectors.

Reference capability: deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go (Deployments/Services/ConfigMaps from the
CRD) and deploy/Kubernetes charts.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .crd import Deployment, ServiceSpec

DEFAULT_IMAGE = "dynamo-tpu:latest"
STORE_PORT = 4222


def _meta(name: str, namespace: str, labels: Dict[str, str]) -> Dict[str, Any]:
    return {"name": name, "namespace": namespace, "labels": labels}


def _labels(dep: Deployment, service: str) -> Dict[str, str]:
    return {"app.kubernetes.io/part-of": "dynamo-tpu",
            "dynamo.tpu/deployment": dep.name,
            "dynamo.tpu/service": service}


def store_manifests(namespace: str,
                    image: str = DEFAULT_IMAGE) -> List[Dict[str, Any]]:
    """dynstore (discovery/request/queue planes) as a single-replica
    Deployment + stable Service — the analogue of the reference's
    etcd+NATS dependency charts."""
    labels = {"app.kubernetes.io/part-of": "dynamo-tpu",
              "dynamo.tpu/service": "dynstore"}
    return [
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": _meta("dynstore", namespace, labels),
         "spec": {
             "replicas": 1,
             "selector": {"matchLabels": labels},
             "template": {
                 "metadata": {"labels": labels},
                 "spec": {"containers": [{
                     "name": "dynstore",
                     "image": image,
                     "command": ["python", "-m",
                                 "dynamo_tpu.runtime.store_server",
                                 "--port", str(STORE_PORT)],
                     "ports": [{"containerPort": STORE_PORT}],
                 }]},
             },
         }},
        {"apiVersion": "v1", "kind": "Service",
         "metadata": _meta("dynstore", namespace, labels),
         "spec": {"selector": labels,
                  "ports": [{"port": STORE_PORT,
                             "targetPort": STORE_PORT}]}},
    ]


def render_manifests(dep: Deployment,
                     services: Dict[str, tuple],
                     image: str = DEFAULT_IMAGE,
                     include_store: bool = True,
                     tpu_topology: Optional[str] = None) -> List[Dict[str, Any]]:
    """``services``: name -> (class import spec, default workers, default
    chips), the same mapping Operator._resolve_graph produces."""
    out: List[Dict[str, Any]] = []
    ns = dep.namespace
    ing0 = dep.spec.ingress
    if ing0 is not None and ing0.enabled and not any(
            n.lower() == ing0.service.lower() for n in services):
        # a typo'd frontend name would render an Ingress to a nonexistent
        # Service and blackhole external traffic with rc=0 — hard-fail
        # like every other config typo in this stack
        raise ValueError(
            f"ingress.service {ing0.service!r} matches no graph service "
            f"(have: {sorted(services)})")
    if include_store:
        out.extend(store_manifests(ns, image))

    config_name = f"{dep.name}-config"
    out.append({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": _meta(config_name, ns, _labels(dep, "config")),
        "data": {"service-config.json": json.dumps(
            {name: (dep.spec.services.get(name) or ServiceSpec()).config
             for name in services}, indent=2)},
    })

    store_addr = dep.spec.store or f"dynstore.{ns}.svc:{STORE_PORT}"
    for name, (class_spec, default_workers, default_chips) in services.items():
        sspec = dep.spec.services.get(name) or ServiceSpec(
            replicas=default_workers, tpu_chips=default_chips)
        labels = _labels(dep, name)
        container: Dict[str, Any] = {
            "name": name.lower(),
            "image": image,
            "command": ["python", "-m", "dynamo_tpu.sdk.serve_child",
                        class_spec, "--store", store_addr],
            "env": [{"name": "DYN_SERVICE_CONFIG_FILE",
                     "value": "/etc/dynamo/service-config.json"}]
            + [{"name": k, "value": v} for k, v in sspec.envs.items()],
            "volumeMounts": [{"name": "config",
                              "mountPath": "/etc/dynamo"}],
        }
        pod_spec: Dict[str, Any] = {
            "containers": [container],
            "volumes": [{"name": "config",
                         "configMap": {"name": config_name}}],
        }
        if sspec.tpu_chips > 0:
            container["resources"] = {
                "limits": {"google.com/tpu": sspec.tpu_chips}}
            sel = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}
            if tpu_topology:
                sel["cloud.google.com/gke-tpu-topology"] = tpu_topology
            pod_spec["nodeSelector"] = sel
        out.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta(f"{dep.name}-{name.lower()}", ns, labels),
            "spec": {
                "replicas": sspec.replicas,
                "selector": {"matchLabels": labels},
                "template": {"metadata": {"labels": labels},
                             "spec": pod_spec},
            },
        })
        ing = dep.spec.ingress
        # graph resolution lowercases service names; specs may carry the
        # class-cased form — match case-insensitively (manifest names are
        # lowercased everywhere anyway)
        is_frontend = (ing is not None and ing.enabled
                       and name.lower() == ing.service.lower())
        if is_frontend:
            # the ingress backend needs a routable port; peers still
            # discover each other through the store, so losing the
            # headless form here costs nothing
            if ing.envoy:
                out.append(_attach_envoy_sidecar(
                    pod_spec, container, dep, name, ing, ns))
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": _meta(f"{dep.name}-{name.lower()}", ns, labels),
                "spec": {"selector": labels,
                         "ports": [{"name": "http", "port": ing.port,
                                    "targetPort": ing.port}]},
            })
        else:
            out.append({
                "apiVersion": "v1", "kind": "Service",
                "metadata": _meta(f"{dep.name}-{name.lower()}", ns, labels),
                "spec": {"selector": labels, "clusterIP": "None"},
            })
    if dep.spec.ingress is not None and dep.spec.ingress.enabled:
        out.append(render_ingress(dep))
    return out


def render_ingress(dep: Deployment) -> Dict[str, Any]:
    """networking.k8s.io/v1 Ingress for the graph's HTTP frontend
    (reference renders ingress for deployed graphs via its Go operator,
    deploy/dynamo/operator/internal/envoy/envoy.go + controller)."""
    ing = dep.spec.ingress
    ns = dep.namespace
    backend = {"service": {"name": f"{dep.name}-{ing.service.lower()}",
                           "port": {"number": ing.port}}}
    rule: Dict[str, Any] = {
        "http": {"paths": [{"path": ing.path, "pathType": "Prefix",
                            "backend": backend}]}}
    if ing.host:
        rule["host"] = ing.host
    md = _meta(f"{dep.name}-ingress", ns, _labels(dep, "ingress"))
    if ing.annotations:
        md["annotations"] = dict(ing.annotations)
    spec: Dict[str, Any] = {"rules": [rule]}
    if ing.tls_secret:
        spec["tls"] = [{"hosts": [ing.host] if ing.host else [],
                        "secretName": ing.tls_secret}]
    return {"apiVersion": "networking.k8s.io/v1", "kind": "Ingress",
            "metadata": md, "spec": spec}


ENVOY_ADMIN_PORT = 9901


def render_envoy_config(listen_port: int, upstream_host: str,
                        upstream_port: int, debug_header: str,
                        debug_value: str, debug_host: str,
                        debug_port: int) -> Dict[str, Any]:
    """Envoy bootstrap: header-routed debug/production split in front of
    the HTTP frontend — requests carrying ``debug_header: debug_value`` go
    to the debug cluster, the rest to production. Same traffic semantics
    as the reference's template (internal/envoy/envoy.go:42-120),
    generated as a dict so callers can serialize or extend it."""
    def cluster(cname: str, host: str, port: int) -> Dict[str, Any]:
        return {
            "name": cname, "connect_timeout": "0.25s",
            "type": "strict_dns", "dns_lookup_family": "v4_only",
            "lb_policy": "round_robin",
            "load_assignment": {
                "cluster_name": cname,
                "endpoints": [{"lb_endpoints": [{"endpoint": {"address": {
                    "socket_address": {"address": host,
                                       "port_value": port}}}}]}]},
        }

    hcm = {
        "name": "envoy.filters.network.http_connection_manager",
        "typed_config": {
            "@type": ("type.googleapis.com/envoy.extensions.filters."
                      "network.http_connection_manager.v3."
                      "HttpConnectionManager"),
            "stat_prefix": "ingress_http",
            "access_log": [{
                "name": "envoy.access_loggers.stdout",
                "typed_config": {
                    "@type": ("type.googleapis.com/envoy.extensions."
                              "access_loggers.stream.v3."
                              "StdoutAccessLog")}}],
            "http_filters": [{
                "name": "envoy.filters.http.router",
                "typed_config": {
                    "@type": ("type.googleapis.com/envoy.extensions."
                              "filters.http.router.v3.Router")}}],
            "route_config": {
                "name": "local_route",
                "virtual_hosts": [{
                    "name": "backend", "domains": ["*"],
                    "routes": [
                        {"match": {"prefix": "/", "headers": [
                            {"name": debug_header,
                             "string_match": {"exact": debug_value}}]},
                         "route": {"cluster": "service_debug"}},
                        {"match": {"prefix": "/"},
                         "route": {"cluster": "service_production"}},
                    ]}]},
        }}
    return {
        "static_resources": {
            "listeners": [{
                "name": "listener_0",
                "address": {"socket_address": {"address": "0.0.0.0",
                                               "port_value": listen_port}},
                "filter_chains": [{"filters": [hcm]}],
            }],
            "clusters": [cluster("service_debug", debug_host, debug_port),
                         cluster("service_production", upstream_host,
                                 upstream_port)],
        },
        "admin": {"access_log_path": "/dev/null",
                  "address": {"socket_address": {
                      "address": "127.0.0.1",
                      "port_value": ENVOY_ADMIN_PORT}}},
    }


def _attach_envoy_sidecar(pod_spec: Dict[str, Any],
                          container: Dict[str, Any], dep, name: str,
                          ing, ns: str) -> Dict[str, Any]:
    """Front the app container with an Envoy sidecar: the Service port
    lands on Envoy; the app moves to port+1; debug traffic (by header)
    goes to the debug service, the rest to the local app. Returns the
    envoy.yaml ConfigMap manifest to ship alongside."""
    import yaml

    app_port = ing.port + 1
    debug_host = (f"{dep.name}-{ing.debug_service.lower()}.{ns}.svc"
                  if ing.debug_service else "127.0.0.1")
    debug_port = ing.port if ing.debug_service else app_port
    econf = render_envoy_config(ing.port, "127.0.0.1", app_port,
                                ing.debug_header, ing.debug_value,
                                debug_host, debug_port)
    pod_spec.setdefault("volumes", []).append({
        "name": "envoy-config",
        "configMap": {"name": f"{dep.name}-{name.lower()}-envoy"}})
    pod_spec["containers"].append({
        "name": "envoy",
        "image": "envoyproxy/envoy:v1.28-latest",
        "args": ["-c", "/etc/envoy/envoy.yaml"],
        "ports": [{"containerPort": ing.port}],
        "volumeMounts": [{"name": "envoy-config",
                          "mountPath": "/etc/envoy"}],
    })
    container.setdefault("env", []).append(
        {"name": "DYN_HTTP_PORT", "value": str(app_port)})
    return {
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": _meta(f"{dep.name}-{name.lower()}-envoy", ns,
                          _labels(dep, name)),
        "data": {"envoy.yaml": yaml.safe_dump(econf, sort_keys=False)},
    }


def to_yaml(manifests: List[Dict[str, Any]]) -> str:
    import yaml

    class _Plain(yaml.SafeDumper):
        def ignore_aliases(self, _data):
            return True   # repeated label dicts must render inline, not &id

    return "---\n".join(
        yaml.dump(m, Dumper=_Plain, sort_keys=False) for m in manifests)
