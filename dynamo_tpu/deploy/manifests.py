"""Render a Deployment resource into Kubernetes manifests.

For a real cluster the operator's job is done by k8s itself: this module
turns one ``Deployment`` into the child resources the reference's Go
controller creates — a ConfigMap carrying per-service config, a k8s
Deployment + Service per graph service, and (once per namespace) the
dynstore coordination service. TPU workers request ``google.com/tpu``
resources with the standard TPU-VM node selectors.

Reference capability: deploy/dynamo/operator/internal/controller/
dynamonimdeployment_controller.go (Deployments/Services/ConfigMaps from the
CRD) and deploy/Kubernetes charts.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from .crd import Deployment, ServiceSpec

DEFAULT_IMAGE = "dynamo-tpu:latest"
STORE_PORT = 4222


def _meta(name: str, namespace: str, labels: Dict[str, str]) -> Dict[str, Any]:
    return {"name": name, "namespace": namespace, "labels": labels}


def _labels(dep: Deployment, service: str) -> Dict[str, str]:
    return {"app.kubernetes.io/part-of": "dynamo-tpu",
            "dynamo.tpu/deployment": dep.name,
            "dynamo.tpu/service": service}


def store_manifests(namespace: str,
                    image: str = DEFAULT_IMAGE) -> List[Dict[str, Any]]:
    """dynstore (discovery/request/queue planes) as a single-replica
    Deployment + stable Service — the analogue of the reference's
    etcd+NATS dependency charts."""
    labels = {"app.kubernetes.io/part-of": "dynamo-tpu",
              "dynamo.tpu/service": "dynstore"}
    return [
        {"apiVersion": "apps/v1", "kind": "Deployment",
         "metadata": _meta("dynstore", namespace, labels),
         "spec": {
             "replicas": 1,
             "selector": {"matchLabels": labels},
             "template": {
                 "metadata": {"labels": labels},
                 "spec": {"containers": [{
                     "name": "dynstore",
                     "image": image,
                     "command": ["python", "-m",
                                 "dynamo_tpu.runtime.store_server",
                                 "--port", str(STORE_PORT)],
                     "ports": [{"containerPort": STORE_PORT}],
                 }]},
             },
         }},
        {"apiVersion": "v1", "kind": "Service",
         "metadata": _meta("dynstore", namespace, labels),
         "spec": {"selector": labels,
                  "ports": [{"port": STORE_PORT,
                             "targetPort": STORE_PORT}]}},
    ]


def render_manifests(dep: Deployment,
                     services: Dict[str, tuple],
                     image: str = DEFAULT_IMAGE,
                     include_store: bool = True,
                     tpu_topology: Optional[str] = None) -> List[Dict[str, Any]]:
    """``services``: name -> (class import spec, default workers, default
    chips), the same mapping Operator._resolve_graph produces."""
    out: List[Dict[str, Any]] = []
    ns = dep.namespace
    if include_store:
        out.extend(store_manifests(ns, image))

    config_name = f"{dep.name}-config"
    out.append({
        "apiVersion": "v1", "kind": "ConfigMap",
        "metadata": _meta(config_name, ns, _labels(dep, "config")),
        "data": {"service-config.json": json.dumps(
            {name: (dep.spec.services.get(name) or ServiceSpec()).config
             for name in services}, indent=2)},
    })

    store_addr = dep.spec.store or f"dynstore.{ns}.svc:{STORE_PORT}"
    for name, (class_spec, default_workers, default_chips) in services.items():
        sspec = dep.spec.services.get(name) or ServiceSpec(
            replicas=default_workers, tpu_chips=default_chips)
        labels = _labels(dep, name)
        container: Dict[str, Any] = {
            "name": name.lower(),
            "image": image,
            "command": ["python", "-m", "dynamo_tpu.sdk.serve_child",
                        class_spec, "--store", store_addr],
            "env": [{"name": "DYN_SERVICE_CONFIG_FILE",
                     "value": "/etc/dynamo/service-config.json"}]
            + [{"name": k, "value": v} for k, v in sspec.envs.items()],
            "volumeMounts": [{"name": "config",
                              "mountPath": "/etc/dynamo"}],
        }
        pod_spec: Dict[str, Any] = {
            "containers": [container],
            "volumes": [{"name": "config",
                         "configMap": {"name": config_name}}],
        }
        if sspec.tpu_chips > 0:
            container["resources"] = {
                "limits": {"google.com/tpu": sspec.tpu_chips}}
            sel = {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"}
            if tpu_topology:
                sel["cloud.google.com/gke-tpu-topology"] = tpu_topology
            pod_spec["nodeSelector"] = sel
        out.append({
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": _meta(f"{dep.name}-{name.lower()}", ns, labels),
            "spec": {
                "replicas": sspec.replicas,
                "selector": {"matchLabels": labels},
                "template": {"metadata": {"labels": labels},
                             "spec": pod_spec},
            },
        })
        out.append({
            "apiVersion": "v1", "kind": "Service",
            "metadata": _meta(f"{dep.name}-{name.lower()}", ns, labels),
            "spec": {"selector": labels, "clusterIP": "None"},
        })
    return out


def to_yaml(manifests: List[Dict[str, Any]]) -> str:
    import yaml

    class _Plain(yaml.SafeDumper):
        def ignore_aliases(self, _data):
            return True   # repeated label dicts must render inline, not &id

    return "---\n".join(
        yaml.dump(m, Dumper=_Plain, sort_keys=False) for m in manifests)
