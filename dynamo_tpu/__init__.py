"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

A from-scratch re-design of the capabilities of NVIDIA Dynamo
(reference: zifeng175mo/dynamo @ 2025-07-04) for TPU hardware:

- OpenAI-compatible HTTP frontend with SSE streaming (``dynamo_tpu.llm.http_service``)
- Distributed runtime: namespace/component/endpoint discovery with leases and
  watches, request plane + streaming response plane (``dynamo_tpu.runtime``)
- KV-cache-aware routing over a global radix index fed by worker KV events
  (``dynamo_tpu.llm.kv_router``)
- Disaggregated prefill/decode with a shared prefill queue and host-staged
  ICI/DCN KV block transfer (``dynamo_tpu.llm.disagg``, ``dynamo_tpu.llm.kvbm``)
- An in-tree JAX/XLA engine: pjit tensor parallelism over a device mesh,
  paged KV cache, bucketed continuous batching, Pallas attention kernels
  (``dynamo_tpu.engine``, ``dynamo_tpu.models``, ``dynamo_tpu.ops``)

The compute path is JAX/XLA/Pallas; the runtime is asyncio + a small native
data plane. Nothing here is a translation of the reference's CUDA/Rust code —
see SURVEY.md at the repo root for the capability map this build follows.
"""

__version__ = "0.1.0"
