"""Model deployment card (MDC): the canonical, serializable description of a
served model — where its artifacts live, which tokenizer/prompt template to
use, context length, and a checksum so distributed components can verify they
agree on the model.

Reference capability: lib/llm/src/model_card/model.rs:55-201 (ModelDeploymentCard,
mdcsum) and create.rs:41-143 (from_local_path).
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field, asdict
from typing import Any, Dict, List, Optional

# Default chat template used when the model dir has none (ChatML — a sane
# widely-understood default; models with their own template override it).
CHATML_TEMPLATE = (
    "{% if tools %}"
    "{{ '<|im_start|>system\nYou may call one of these tools by answering "
    "with JSON {\"name\": ..., \"parameters\": {...}}:\n' }}"
    "{% for tool in tools %}{{ tool['function'] | tojson }}{{ '\n' }}{% endfor %}"
    "{{ '<|im_end|>\n' }}"
    "{% endif %}"
    "{% for message in messages %}"
    "{{ '<|im_start|>' + message['role'] + '\n' + message['content'] + '<|im_end|>' + '\n' }}"
    "{% endfor %}"
    "{% if add_generation_prompt %}{{ '<|im_start|>assistant\n' }}{% endif %}"
)


@dataclass
class ModelDeploymentCard:
    name: str
    path: Optional[str] = None            # local dir with config/tokenizer/weights
    tokenizer: str = "byte"               # "byte" or a local tokenizer dir
    chat_template: Optional[str] = None   # jinja2 source
    context_length: int = 8192
    kv_block_size: int = 64
    eos_token_ids: List[int] = field(default_factory=list)
    bos_token_id: Optional[int] = None
    model_config: Dict[str, Any] = field(default_factory=dict)  # HF config.json
    model_type: str = "chat"              # "chat" | "completion" | "both"

    # ------------------------------------------------------------------
    @property
    def mdc_sum(self) -> str:
        """Stable checksum over the card's identifying fields."""
        ident = json.dumps(
            {
                "name": self.name,
                "tokenizer": self.tokenizer,
                "chat_template": self.chat_template,
                "context_length": self.context_length,
                "kv_block_size": self.kv_block_size,
                "eos": self.eos_token_ids,
            },
            sort_keys=True,
        )
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModelDeploymentCard":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})

    # ------------------------------------------------------------------
    @classmethod
    def resolve(cls, spec: str,
                name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from a local directory, a GGUF file, or a HF repo id
        (resolved from the local HF cache; networkless environments get a
        clear error instead of a retry storm).

        Reference capability: launch/dynamo-run/src/hub.rs (HF-repo auto-
        download when the model path is missing)."""
        if os.path.isfile(spec) and spec.endswith(".gguf"):
            return cls.from_gguf(spec, name)
        if os.path.exists(spec):
            return cls.from_local_path(spec, name)
        # an "org/name" shape (exactly one slash, relative) is a repo id
        if (spec.count("/") == 1 and not spec.startswith((".", "/"))
                and ".." not in spec):
            # offline unless EXPLICITLY disabled (HF_HUB_OFFLINE=0/false):
            # this deviates from huggingface_hub's online-by-default because
            # an unreachable hub turns every model load into a retry storm
            env = os.environ.get("HF_HUB_OFFLINE")
            offline = env is None or env.lower() not in ("0", "false", "")
            try:
                from huggingface_hub import snapshot_download

                local = snapshot_download(spec, local_files_only=offline)
            except Exception as e:
                raise FileNotFoundError(
                    f"model {spec!r} is neither a local path nor an "
                    f"HF repo available in the local cache: {e}") from e
            # a failure past this point is a real model problem (corrupt
            # config/tokenizer), not a cache miss — let it surface as-is
            return cls.from_local_path(local, name or spec.split("/")[-1])
        raise FileNotFoundError(f"model path {spec!r} does not exist")

    @classmethod
    def from_gguf(cls, path: str,
                  name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from a GGUF model file: config (context length,
        eos ids) comes from the GGUF metadata; the tokenizer uses an
        adjacent tokenizer.json when present, else the GGUF-embedded vocab
        (``llama`` → native SP unigram, ``gpt2`` → native byte-level BPE,
        matching ref gguf_tokenizer.rs:121-125; ``dynamo-byte`` → the raw
        byte tokenizer, our explicit export extension).  An unrecognized or
        missing ``tokenizer.ggml.model`` next to an embedded vocab is a
        hard error — serving a model through a wrong tokenizer is worse
        than failing (VERDICT r3 missing #2)."""
        from .gguf import read_gguf

        g = read_gguf(path)
        md = g.metadata
        arch = g.architecture() or "gguf"
        card = cls(name=name or os.path.splitext(os.path.basename(path))[0],
                   path=path)
        ctx = md.get(f"{arch}.context_length")
        if ctx:
            card.context_length = int(ctx)
        try:
            eos = md.get("tokenizer.ggml.eos_token_id")
            bos = md.get("tokenizer.ggml.bos_token_id")
            if bos is not None:
                card.bos_token_id = int(bos)
            tok_dir = os.path.dirname(os.path.abspath(path))
            tok_model = md.get("tokenizer.ggml.model")
            if os.path.exists(os.path.join(tok_dir, "tokenizer.json")):
                card.tokenizer = tok_dir
            elif tok_model in ("llama", "gpt2"):
                if not md.get("tokenizer.ggml.tokens"):
                    raise ValueError(
                        f"GGUF {path} declares tokenizer.ggml.model="
                        f"{tok_model!r} but carries no tokenizer.ggml.tokens "
                        "vocab and no adjacent tokenizer.json")
                # embedded vocab: SPM unigram for llama/mistral exports,
                # byte-level BPE (tokens+merges) for Qwen2/GPT-2 family
                kind = "gguf-sp" if tok_model == "llama" else "gguf-bpe"
                card.tokenizer = f"{kind}:{os.path.abspath(path)}"
            elif tok_model == "dynamo-byte":
                # our own export extension: an EXPLICIT declaration that the
                # model was trained on the raw-byte vocab (test fixtures,
                # tiny-byte presets); card.tokenizer keeps its byte default
                pass
            elif tok_model is not None or md.get("tokenizer.ggml.tokens"):
                # never silently degrade to the byte tokenizer: a served
                # model that mis-tokenizes with rc=0 is worse than failing
                raise ValueError(
                    f"unsupported tokenizer.ggml.model {tok_model!r} in "
                    f"{path} and no adjacent tokenizer.json; supported: "
                    "'llama' (SPM unigram), 'gpt2' (byte-level BPE)")
            if eos is not None:
                card.eos_token_ids = [int(eos)]
            else:
                # no eos in the container: the serving tokenizer's eos must
                # still stop generation, or every request runs to max_tokens
                from .tokenizer import load_tokenizer

                card.eos_token_ids = list(
                    load_tokenizer(card.tokenizer).eos_token_ids)
        finally:
            g.close()
        return card

    @classmethod
    def from_local_path(cls, path: str, name: Optional[str] = None) -> "ModelDeploymentCard":
        """Build a card from a local HF-style model directory."""
        name = name or os.path.basename(os.path.normpath(path))
        card = cls(name=name, path=path)
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                card.model_config = json.load(f)
            mpe = card.model_config.get("max_position_embeddings")
            if mpe:
                card.context_length = int(mpe)
        has_tokenizer = any(
            os.path.exists(os.path.join(path, f))
            for f in ("tokenizer.json", "tokenizer_config.json", "vocab.json",
                      "spiece.model", "tokenizer.model")
        )
        if has_tokenizer:
            card.tokenizer = path
            from .tokenizer import HfTokenizer

            tok = HfTokenizer(path)
            card.eos_token_ids = tok.eos_token_ids
            card.bos_token_id = tok.bos_token_id
        card.chat_template = _load_chat_template(path)
        return card

    @classmethod
    def synthetic(cls, name: str = "echo", **kw) -> "ModelDeploymentCard":
        """Card for the byte tokenizer / echo and test engines."""
        from .tokenizer import ByteTokenizer

        return cls(
            name=name,
            tokenizer="byte",
            chat_template=None,
            eos_token_ids=[ByteTokenizer.EOS],
            bos_token_id=ByteTokenizer.BOS,
            **kw,
        )


def _load_chat_template(path: str) -> Optional[str]:
    tc = os.path.join(path, "tokenizer_config.json")
    if os.path.exists(tc):
        with open(tc) as f:
            cfg = json.load(f)
        t = cfg.get("chat_template")
        if isinstance(t, str):
            return t
        if isinstance(t, list):  # named templates
            for entry in t:
                if entry.get("name") == "default":
                    return entry.get("template")
    sep = os.path.join(path, "chat_template.jinja")
    if os.path.exists(sep):
        with open(sep) as f:
            return f.read()
    return None
