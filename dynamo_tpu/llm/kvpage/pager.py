"""PageScheduler: stages demoted KV blocks back toward the device ahead
of the attention pass that needs them.

The paged forward consumes cold blocks as (layer, segment) items in a
fully deterministic order — the runner publishes that order, PER LANE,
as a :class:`PageinPlan` before each chunk/window forward. A background
thread walks the installed plans, assembling each segment's host staging
buffer (per-layer ``peek_layer`` copies out of the tier — deliberately
NOT ``lookup``, so page-in traffic never perturbs the LRU order that
serves admission restores) up to ``prefetch`` segments ahead of each
lane's consumer cursor.

With several decode lanes active the assembler ROUND-ROBINS one item at
a time across the lanes that still have claimable work: a lane with a
32x-budget context cannot starve a short-context neighbour, because
backpressure is per lane (``claimed - taken < prefetch``) — each lane
keeps its own double-buffer ahead of the forward, no more. The h2d
upload itself is issued by the runner (it owns the device queue), so by
the time attention for segment *s* dispatches, segment *s+1* is already
assembled and its upload enqueued: page-in overlaps compute, across
lanes as well as within one.

``take`` is the fault boundary: an item the thread already finished is
an async page-in (``dyn_kvpage_pageins_total``); an item that has to be
assembled inline on the engine thread — prefetch disabled, or a plan the
thread has not reached — is a *page fault*
(``dyn_kvpage_faults_total``): a counted synchronous upload, never a
crash. Faults are per take and therefore per LANE: one lane missing its
prefetch degrades that lane's take to a synchronous assembly while the
other lanes' prefetched buffers stay valid and their cursors untouched.
Time spent blocked on a scheduled-but-unfinished item lands in the
``dyn_kvpage_pagein_wait_seconds`` histogram; in steady-state decode
both the fault counter and that histogram should sit at zero, which the
long-context bench lane asserts.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ...utils.prometheus import stage_metrics

log = logging.getLogger("dynamo_tpu.kvpage")

#: one plan item: (layer, segment index within that layer)
ItemKey = Tuple[int, int]


class KvPageMiss(RuntimeError):
    """A cold block vanished from every tier mid-decode (the pin
    discipline failed) — fatal for the request, not the engine."""


@dataclass
class PageinPlan:
    """The deterministic page-in order of one paged forward: for each
    layer, the cold segments (tuples of block hashes) it will consume."""

    segments: List[List[Tuple[int, ...]]]   # [layer][seg] -> block hashes
    generation: int = 0

    def items(self) -> List[ItemKey]:
        return [(l, s) for l, segs in enumerate(self.segments)
                for s in range(len(segs))]

    def hashes(self, key: ItemKey) -> Tuple[int, ...]:
        return self.segments[key[0]][key[1]]


@dataclass
class _Assembled:
    k: Optional[np.ndarray]       # [seg_pages, Hkv, page, Dh]
    v: Optional[np.ndarray]
    n_valid: int
    ready: threading.Event = field(default_factory=threading.Event)
    error: Optional[Exception] = None
    #: wall seconds the tier->staging assembly took — folded into the
    #: runner's kvpage_pagein flow so the ledger's page-in seconds cover
    #: staging + upload, not just the h2d enqueue
    seconds: float = 0.0


@dataclass
class _LaneSched:
    """One lane's plan walk: the assembler's claim cursor (``next``) and
    the consumer's take cursor (``taken``) bound each other through the
    per-lane prefetch window."""

    plan: Optional[PageinPlan] = None
    order: List[ItemKey] = field(default_factory=list)
    built: Dict[ItemKey, _Assembled] = field(default_factory=dict)
    next: int = 0                 # thread's claim cursor into order
    taken: int = 0                # consumer's cursor (backpressure)


class PageScheduler:
    """Prefetches cold-block staging buffers ahead of the paged forward.

    Single consumer (the engine thread) + one assembler thread shared by
    every lane; the tier handles its own locking (``peek_layer`` copies
    under the tier lock), so the scheduler only guards its plan/ready
    bookkeeping. Lane 0 is the default so single-lane callers never name
    a lane.
    """

    def __init__(self, tiered, seg_pages: int, prefetch: int = 2):
        self.tiered = tiered
        self.seg_pages = int(seg_pages)
        self.prefetch = int(prefetch)
        self.faults = 0
        self.pageins = 0
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._lanes: Dict[int, _LaneSched] = {}
        self._rr = -1                 # last lane the assembler served
        self._gen = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        #: (lane, item) claim order, for interleave tests/debugging
        self.claim_log: Deque[Tuple[int, ItemKey]] = collections.deque(
            maxlen=1024)
        #: assemble seconds of the most recent take() — the runner (the
        #: single consumer) reads this right after each take to price
        #: the page-in flow it is about to upload
        self.last_assemble_s = 0.0

    # ------------------------------------------------------------------
    def begin(self, plan: PageinPlan, lane: int = 0) -> None:
        """Install one lane's next-forward page-in order; the assembler
        starts on it immediately (per-lane prefetch permitting)."""
        with self._wake:
            self._gen += 1
            plan.generation = self._gen
            st = self._lanes.setdefault(lane, _LaneSched())
            st.plan = plan
            st.order = plan.items()
            st.built = {}
            st.next = 0
            st.taken = 0
            self._wake.notify_all()
        if (self.prefetch > 0 and st.order and self._thread is None
                and not self._closed):
            self._thread = threading.Thread(
                target=self._run, name="kvpage-prefetch", daemon=True)
            self._thread.start()

    def end_lane(self, lane: int) -> None:
        """Drop a lane's plan state (its sequence released); in-flight
        assemblies for it finish into discarded entries."""
        with self._wake:
            self._lanes.pop(lane, None)
            self._wake.notify_all()

    def take(self, key: ItemKey, lane: int = 0
             ) -> Tuple[np.ndarray, np.ndarray, int]:
        """The staging buffer for one lane's plan item:
        (k, v, n_valid_blocks). Prefetched items count as page-ins (time
        blocked on an in-flight assembly lands in the wait histogram); an
        item the assembler will never deliver — prefetch disabled, thread
        gone — is assembled inline: a counted synchronous page fault,
        isolated to this lane (no other lane's cursors move)."""
        stage = stage_metrics()
        t0 = time.perf_counter()
        with self._wake:
            st = self._lanes.get(lane)
            ent = st.built.pop(key, None) if st is not None else None
            if (ent is None and st is not None and self.prefetch > 0
                    and self._thread is not None):
                # the assembler claims a lane's items strictly in plan
                # order; if it has not reached this one yet, it is about
                # to — wait for the claim instead of duplicating the
                # work inline
                try:
                    idx = st.order.index(key)
                except ValueError:
                    idx = -1
                while (ent is None and idx >= 0 and not self._closed
                       and st.plan is not None and st.next <= idx):
                    self._wake.wait(0.05)
                    ent = st.built.pop(key, None)
                if ent is None:
                    ent = st.built.pop(key, None)
            if ent is not None:
                st.taken += 1
                self._wake.notify_all()   # a prefetch slot freed up
        if ent is None:
            # the assembler will never deliver this item: synchronous
            # page-in on the engine thread
            self.faults += 1
            stage.kvpage_faults.inc()
            plan = st.plan if st is not None else None
            if plan is None:
                raise KvPageMiss(
                    f"take({key}) on lane {lane} with no active plan")
            ent = self._assemble(plan.hashes(key), layer=key[0])
            stage.kvpage_pagein_wait.observe(
                value=time.perf_counter() - t0)
            with self._wake:
                if st is not None:
                    st.taken += 1
                self._wake.notify_all()
            self.last_assemble_s = ent.seconds
            return ent.k, ent.v, ent.n_valid
        ent.ready.wait()
        if ent.error is not None:
            raise ent.error
        self.pageins += 1
        stage.kvpage_pageins.inc()
        stage.kvpage_pagein_wait.observe(value=time.perf_counter() - t0)
        self.last_assemble_s = ent.seconds
        return ent.k, ent.v, ent.n_valid

    def close(self) -> None:
        with self._wake:
            self._closed = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------------
    def _assemble(self, hashes: Tuple[int, ...], layer: int
                  ) -> _Assembled:
        """Stack one segment's per-layer block slices into a fixed-shape
        staging buffer (padded to ``seg_pages``)."""
        t0 = time.perf_counter()
        ks: List[np.ndarray] = []
        vs: List[np.ndarray] = []
        for h in hashes:
            got = self.tiered.peek_layer(h, layer)
            if got is None:
                raise KvPageMiss(
                    f"cold block {h:x} missing from every tier (layer "
                    f"{layer}); the pin discipline was violated")
            ks.append(got[0])
            vs.append(got[1])
        n = len(ks)
        pad = self.seg_pages - n
        if pad:
            z = np.zeros_like(ks[0])
            ks.extend([z] * pad)
            vs.extend([z] * pad)
        return _Assembled(np.stack(ks), np.stack(vs), n, ready=_DONE,
                          seconds=time.perf_counter() - t0)

    def _claimable(self, st: _LaneSched) -> bool:
        return (st.plan is not None and st.next < len(st.order)
                and st.next - st.taken < self.prefetch)

    def _pick_lane(self) -> Optional[int]:
        """Next lane to assemble for: round-robin starting after the
        last-served lane, skipping lanes that are plan-done or at their
        prefetch ceiling. One item per pick is the fairness unit."""
        lanes = sorted(self._lanes)
        if not lanes:
            return None
        start = 0
        for i, ln in enumerate(lanes):
            if ln > self._rr:
                start = i
                break
        for i in range(len(lanes)):
            ln = lanes[(start + i) % len(lanes)]
            if self._claimable(self._lanes[ln]):
                return ln
        return None

    def _run(self) -> None:
        while True:
            with self._wake:
                ln = self._pick_lane()
                while not self._closed and ln is None:
                    self._wake.wait()
                    ln = self._pick_lane()
                if self._closed:
                    return
                self._rr = ln
                st = self._lanes[ln]
                key = st.order[st.next]
                ent = _Assembled(None, None, 0)  # placeholder until built
                st.built[key] = ent
                st.next += 1
                self.claim_log.append((ln, key))
                self._wake.notify_all()   # a consumer may await the claim
                hashes = st.plan.hashes(key)
            try:
                built = self._assemble(hashes, layer=key[0])
                ent.k, ent.v, ent.n_valid = built.k, built.v, built.n_valid
                ent.seconds = built.seconds
                ent.error = None
            except Exception as e:  # noqa: BLE001 - delivered to take()
                ent.error = e
            finally:
                # if a new plan superseded this one mid-assembly, begin()
                # already discarded the stale entry — setting the
                # orphaned event is harmless
                ent.ready.set()


#: shared always-set event for inline (fault-path) assemblies
_DONE = threading.Event()
_DONE.set()
