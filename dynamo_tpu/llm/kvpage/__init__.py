"""KV paging: a virtual-memory subsystem for the decode working set.

Serves contexts far beyond the device KV pool by bounding device
residency to a page budget and streaming the cold tail through staged
host->device uploads, layer by layer, with online-softmax merging —
see :mod:`.runner` for the serving integration and
``docs/long_context.md`` for the operator-facing model.
"""

from .pager import PageScheduler, PageinPlan
from .runner import PagedEngine, PagedConfig

__all__ = ["PageScheduler", "PageinPlan", "PagedEngine", "PagedConfig"]
