"""PagedEngine: serve contexts far beyond the device KV pool.

The virtual-memory model (docs/long_context.md):

- **Chunked prefill with seal-and-demote.** Each prefill chunk writes its
  KV into device pages leased from the engine's pool; once the chunk's
  dispatch has been issued, full (sealed) blocks beyond the hot-window
  budget are demoted d2h into the host tier (``TieredKvCache``) — pinned,
  because a demoted decode working set is state, not cache — and their
  device pages return to the pool. Device residency therefore stays
  bounded at ``budget`` pages for ANY context length. The d2h gather is
  enqueued against the post-write pool arrays, so JAX sequences it after
  the writing dispatch by data dependency (a one-hop version of the
  cluster write-through's two-step ratchet: here the runner owns the
  issue order, so it demotes the moment the write is in the queue).
- **Decode over a windowed working set.** Attention runs hot-first over
  the resident tail through the pool, then merges one staged cold
  segment at a time (``programs.attn_cold``), while the
  :class:`~.pager.PageScheduler` assembles the next segment ahead of
  need and the runner enqueues its h2d upload before dispatching the
  current segment's attention — double-buffered, never blocking
  dispatch. Faults degrade to counted synchronous uploads.
- **Prefix reuse for free.** Demoted blocks carry their chained sequence
  hashes, so a repeated long prompt pins matching tier blocks at
  admission and skips recomputing them; at release the pins drop and the
  blocks become ordinary LRU tier content (servable to cluster peers).

The paged lane runs ONE sequence at a time (batch dim 1): long-context
requests queue behind each other rather than thrash one device budget.
"""

from __future__ import annotations

import collections
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...llm.kvbm.pool import OutOfBlocks
from ...llm.kvbm.tiers import OutOfTierSpace
from ...llm.protocols.common import BackendInput, FinishReason
from ...llm.tokens import TokenSequence, chain_hash, hash_tokens, \
    lora_chain_root
from ...utils.knobs import env_float as _env_float
from ...utils.prometheus import stage_metrics
from .pager import KvPageMiss, PageinPlan, PageScheduler
from .programs import PagedPrograms

log = logging.getLogger("dynamo_tpu.kvpage")


@dataclass
class PagedConfig:
    """Resolved ``DYN_KVPAGE_*`` surface (engine-config fields win over
    env knobs; a zero/unset budget disables the plane entirely)."""

    budget: int                 # device pages the paged lane may lease
    seg_pages: int              # blocks per cold staging segment
    prefetch: int               # segments assembled ahead (0 = sync)
    max_context: int            # paged-lane context ceiling, tokens

    @classmethod
    def resolve(cls, cfg) -> Optional["PagedConfig"]:
        budget = cfg.kvpage_budget
        if budget is None:
            budget = int(_env_float("DYN_KVPAGE_DEVICE_BUDGET", 0))
        if budget <= 0:
            return None
        seg = cfg.kvpage_seg_pages or int(
            _env_float("DYN_KVPAGE_SEG_PAGES", 8))
        prefetch = cfg.kvpage_prefetch
        if prefetch is None:
            prefetch = int(_env_float("DYN_KVPAGE_PREFETCH", 2))
        max_ctx = cfg.kvpage_max_context or int(
            _env_float("DYN_KVPAGE_MAX_CONTEXT", 131072))
        return cls(budget=int(budget), seg_pages=max(1, int(seg)),
                   prefetch=max(0, int(prefetch)),
                   max_context=int(max_ctx))


@dataclass
class _PagedSeq:
    seq_id: str
    request: BackendInput
    prompt: List[int]
    tokseq: TokenSequence
    # device pages for blocks [first_res, first_res + len(resident));
    # the resident span is always the contiguous tail of the context
    resident: List[int] = field(default_factory=list)
    first_res: int = 0
    pinned: List[int] = field(default_factory=list)   # demoted block hashes
    total_len: int = 0          # tokens written to the KV (pool or tier)
    prefill_done: int = 0
    generated: int = 0
    last_token: int = 0
    cum_logprob: float = 0.0
    cancelled: bool = False
    # per-sequence device sampling state (the paged lane does not occupy
    # an engine slot, so it carries its own key/penalty counts)
    key: Optional[jax.Array] = None
    counts: Optional[jax.Array] = None
    temp: Optional[np.ndarray] = None
    top_p: Optional[np.ndarray] = None
    top_k: Optional[np.ndarray] = None
    freq_pen: Optional[np.ndarray] = None
    pres_pen: Optional[np.ndarray] = None


class PagedEngine:
    """The paged lane of one :class:`~...engine.engine.EngineCore`.

    Driven from the engine thread: ``advance()`` performs exactly one
    unit of work (one prefill chunk or one decode token) so paged and
    normal traffic interleave at engine-step granularity.
    """

    def __init__(self, core, pcfg: PagedConfig):
        from ...engine.engine import StepOutput  # noqa: F401 (typing aid)

        self.core = core
        self.pcfg = pcfg
        cfg = core.cfg
        self.page = cfg.page_size
        m = cfg.model
        self.programs = PagedPrograms(cfg, core.mesh, core._rep_sharding,
                                      core.kv_sharding)
        self.pager = PageScheduler(core.tiered, pcfg.seg_pages,
                                   pcfg.prefetch)
        self.chunk = cfg.prefill_chunk
        self.chunk_pages = -(-self.chunk // self.page)
        # decode chaining: N tokens per host fetch, the dense path's
        # packed multi-step discipline — each sampled token feeds the
        # next forward as a device array, ONE packed fetch per window
        self.decode_chain = max(1, int(_env_float(
            "DYN_KVPAGE_DECODE_STEPS", cfg.decode_steps or 4)))
        if pcfg.budget < self.chunk_pages + 2:
            raise ValueError(
                f"kvpage budget of {pcfg.budget} pages cannot hold a "
                f"prefill chunk ({self.chunk_pages} pages) plus the hot "
                f"tail; need >= {self.chunk_pages + 2}")
        from ...models.llama import kv_block_bytes
        self.block_bytes = kv_block_bytes(m, self.page)
        # hot-window residency ceilings: during prefill the in-flight
        # chunk's pages ride inside the budget
        self.hot_keep = max(1, pcfg.budget - self.chunk_pages - 1)
        self.active: Optional[_PagedSeq] = None
        self.queue: Deque[Tuple[str, BackendInput]] = collections.deque()
        self._worker = str(os.getpid())
        # goodput accounting: paged dispatches feed the engine's shared
        # GoodputMeter so MFU/MBU stop under-reporting on long-context
        # traffic. The paged programs compile per (kind, hot-bucket)
        # shape with no instrument_compile wrapper, so first-use shapes
        # are tracked here and their work units excluded — same
        # compile-not-compute convention as the dense path's
        # _take_compiled_flag.
        self._accounted_shapes: set = set()
        # hot-span shape buckets (page multiples, powers of two) keep the
        # attn_hot program count logarithmic in the budget
        self.s_hot_buckets: List[int] = []
        b = self.page
        while b < pcfg.budget * self.page:
            self.s_hot_buckets.append(b)
            b *= 2
        self.s_hot_buckets.append(pcfg.budget * self.page)

    # ------------------------------------------------------------------
    @property
    def has_work(self) -> bool:
        return self.active is not None or bool(self.queue)

    def resident_bytes(self) -> Tuple[float, float]:
        """(device bytes, pinned host bytes) of the paged working set."""
        seq = self.active
        if seq is None:
            return 0.0, 0.0
        return (float(len(seq.resident) * self.block_bytes),
                float(len(seq.pinned) * self.block_bytes))

    def close(self) -> None:
        self.pager.close()

    def cancel(self, seq_id: str) -> None:
        if self.active is not None and self.active.seq_id == seq_id:
            self.active.cancelled = True
        else:
            self.queue = collections.deque(
                (s, r) for s, r in self.queue if s != seq_id)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def try_route(self, seq_id: str, req: BackendInput):
        """Accept the request into the paged lane (None) or explain why
        not (a typed ERROR StepOutput the engine emits as-is)."""
        from ...engine.engine import StepOutput

        prompt_len = len(req.token_ids)

        def err(msg, code, reason):
            return StepOutput(seq_id, 0, 0.0, FinishReason.ERROR,
                              error=msg, error_code=code,
                              error_stage="engine_admission",
                              error_reason=reason)

        if prompt_len >= self.pcfg.max_context:
            return err(
                f"prompt of {prompt_len} tokens exceeds the paged "
                f"context limit of {self.pcfg.max_context} "
                f"(DYN_KVPAGE_MAX_CONTEXT)", 400, "context_exceeded")
        if req.images:
            return err("image requests are not servable on the paged "
                       "long-context lane", 400, "unsupported")
        if self.core.dispatch_hook is not None:
            return err("KV paging does not run on multi-host engines",
                       400, "unsupported")
        max_new = req.stop.max_tokens or (self.pcfg.max_context
                                          - prompt_len)
        blocks = -(-(prompt_len + max_new) // self.page)
        host = self.core.tiered.host
        # byte-honest admission: the pinned working set must fit the host
        # tier next to what is already pinned, or this one request would
        # evict the pool's (and its neighbors') working sets
        if blocks + len(host.pinned) + 1 > host.num_blocks:
            return err(
                f"paged working set of {blocks} KV blocks "
                f"({blocks * self.block_bytes / 1e6:.0f} MB) does not fit "
                f"the host tier ({host.num_blocks} blocks, "
                f"{len(host.pinned)} already pinned)", 503,
                "kvpage_capacity")
        self.queue.append((seq_id, req))
        return None

    # ------------------------------------------------------------------
    # engine-step driver
    # ------------------------------------------------------------------
    def advance(self) -> List:
        """One unit of paged work: start a queued sequence, advance one
        prefill chunk, or decode one token."""
        from ...engine.engine import StepOutput

        out: List[StepOutput] = []
        seq = self.active
        if seq is not None and seq.cancelled:
            out.append(StepOutput(seq.seq_id, seq.last_token,
                                  seq.cum_logprob, FinishReason.CANCELLED))
            self._release(seq)
            seq = None
        if seq is None:
            if not self.queue:
                return out
            seq_id, req = self.queue.popleft()
            seq = self._start(seq_id, req)
        try:
            if seq.prefill_done < len(seq.prompt):
                self._prefill_chunk(seq, out)
            else:
                self._decode_step(seq, out)
        except Exception as e:  # noqa: BLE001 - a paged failure must kill
            # THIS request, never the engine: letting it escape would hit
            # step()'s catch-all, which errors every DENSE sequence and
            # never releases the paged lane — the engine would then retry
            # the same broken state forever. Capacity pressure is a
            # retryable 503; a KvPageMiss (pin discipline violated — a
            # data-loss bug, not load) and anything unexpected are 500s
            # with distinct reasons so dashboards can tell them apart.
            log.exception("paged sequence %s failed", seq.seq_id)
            if isinstance(e, (OutOfBlocks, OutOfTierSpace)):
                code, reason = 503, "kvpage_capacity"
            elif isinstance(e, KvPageMiss):
                code, reason = 500, "kvpage_miss"
            else:
                code, reason = 500, "kvpage_internal"
            out.append(StepOutput(
                seq.seq_id, seq.last_token, seq.cum_logprob,
                FinishReason.ERROR,
                error=f"paged serving failed: {e}", error_code=code,
                error_stage="engine", error_reason=reason))
            self._release(seq)
        return out

    # ------------------------------------------------------------------
    def _start(self, seq_id: str, req: BackendInput) -> _PagedSeq:
        prompt = list(req.token_ids)
        lora_id = getattr(req, "lora_id", 0)
        seq = _PagedSeq(seq_id, req, prompt,
                        TokenSequence(self.page, lora_id=lora_id))
        # prefix reuse against the tier: pin matching leading blocks and
        # skip recomputing them — they are cold context from token 0
        page = self.page
        usable = (len(prompt) - 1) // page
        parent = lora_chain_root(lora_id)
        matched = 0
        tiered = self.core.tiered
        for b in range(usable):
            blk = prompt[b * page:(b + 1) * page]
            sh = chain_hash(parent, hash_tokens(blk))
            if not tiered.pin(sh):
                break
            seq.pinned.append(sh)
            parent = sh
            matched += 1
        for t in prompt[:matched * page]:
            seq.tokseq.append(int(t))
        seq.first_res = matched
        seq.total_len = matched * page
        seq.prefill_done = matched * page
        self.core.last_prefix_hit = matched * page
        self.core.prefix_hit_tokens += matched * page
        self.core.prefix_query_tokens += len(prompt)

        # sampling state (lane-of-one mirrors of SamplingState)
        sp = req.sampling
        from ...engine.sampling import STATIC_K
        seq.temp = np.asarray([float(sp.temperature or 0.0)], np.float32)
        seq.top_p = np.asarray(
            [float(sp.top_p if sp.top_p is not None else 1.0)], np.float32)
        seq.top_k = np.asarray([int(min(sp.top_k or 0, STATIC_K))],
                               np.int32)
        seq.freq_pen = np.asarray([float(sp.frequency_penalty or 0.0)],
                                  np.float32)
        seq.pres_pen = np.asarray([float(sp.presence_penalty or 0.0)],
                                  np.float32)
        seed = sp.seed if sp.seed is not None else self.core.cfg.seed
        seq.key = jax.vmap(jax.random.key)(jnp.asarray([int(seed)]))
        seq.counts = jnp.zeros((1, self.core.cfg.model.vocab_size),
                               jnp.int32)
        self.active = seq
        self._set_gauges(seq)
        return seq

    def _release(self, seq: _PagedSeq) -> None:
        for page in seq.resident:
            self.core.pool.blocks.release(page)
        seq.resident = []
        tiered = self.core.tiered
        for h in seq.pinned:
            tiered.unpin(h)
        seq.pinned = []
        if self.active is seq:
            self.active = None
        g = stage_metrics().kvpage_resident_bytes
        g.set("device", self._worker, value=0.0)
        g.set("host", self._worker, value=0.0)

    def _set_gauges(self, seq: _PagedSeq) -> None:
        dev, host = self.resident_bytes()
        g = stage_metrics().kvpage_resident_bytes
        g.set("device", self._worker, value=dev)
        g.set("host", self._worker, value=host)

    # ------------------------------------------------------------------
    # residency
    # ------------------------------------------------------------------
    def _slot(self, seq: _PagedSeq, pos: int) -> int:
        """Pool token-slot of position ``pos`` (must be resident)."""
        blk = pos // self.page
        return (seq.resident[blk - seq.first_res] * self.page
                + pos % self.page)

    def _ensure_resident(self, seq: _PagedSeq, upto: int) -> None:
        """Lease device pages so every position < ``upto`` beyond the
        demoted prefix has a slot."""
        need_blocks = -(-upto // self.page)
        while seq.first_res + len(seq.resident) < need_blocks:
            seq.resident.append(self.core.pool.blocks.lease_new())

    def _demote(self, seq: _PagedSeq, keep: int) -> None:
        """Seal-and-demote the oldest resident blocks until at most
        ``keep`` stay resident. Only full (hashed) blocks demote; the
        d2h gather reads the post-write pool arrays, so it is ordered
        after the writing dispatch by data dependency."""
        sealed = len(seq.tokseq.blocks)
        n = 0
        while (len(seq.resident) - n > keep
               and seq.first_res + n < sealed):
            n += 1
        if n <= 0:
            return
        pages = seq.resident[:n]
        hashes = [seq.tokseq.blocks[seq.first_res + i].sequence_hash
                  for i in range(n)]
        k, v = self.core.copy_stream.d2h_pages(
            self.core.k_pool, self.core.v_pool, pages, pipeline=n > 4)
        tiered = self.core.tiered
        for i, h in enumerate(hashes):
            tiered.deposit_pinned(h, k[i], v[i])
            seq.pinned.append(h)
        for page in pages:
            self.core.pool.blocks.release(page)
        del seq.resident[:n]
        seq.first_res += n
        stage_metrics().kvpage_demotions.inc(amount=float(n))
        self._set_gauges(seq)

    def _cold_segments(self, seq: _PagedSeq) -> List[Tuple[int, ...]]:
        """The demoted prefix [0, first_res) grouped into staging
        segments of ``seg_pages`` blocks."""
        hashes = seq.pinned
        sp = self.pcfg.seg_pages
        return [tuple(hashes[i:i + sp]) for i in range(0, len(hashes), sp)]

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def _bucket_hot(self, n: int) -> int:
        for b in self.s_hot_buckets:
            if n <= b:
                return b
        return self.s_hot_buckets[-1]

    def _account(self, kind: str, S: int, flops: float, bytes_: float,
                 tokens: int, elapsed_s: float) -> None:
        """Feed one paged work unit into the engine's GoodputMeter —
        unless this (kind, hot-bucket) shape just compiled, in which
        case the wall time is XLA, not compute."""
        shape = (kind, S)
        if shape not in self._accounted_shapes:
            self._accounted_shapes.add(shape)
            return
        self.core.goodput.account(flops, bytes_, elapsed_s, tokens)

    def _upload(self, key) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Take one assembled staging segment and ENQUEUE its h2d upload;
        returns device arrays the attention dispatch consumes."""
        k, v, n = self.pager.take(key)
        dt = self.core.cfg.model.dtype
        valid = np.arange(self.pcfg.seg_pages * self.page) < n * self.page
        return (jnp.asarray(k, dt), jnp.asarray(v, dt), jnp.asarray(valid))

    def _forward(self, seq: _PagedSeq, tokens: np.ndarray,
                 positions: np.ndarray, write_idx: np.ndarray,
                 read_idx: np.ndarray, read_pos: np.ndarray,
                 read_valid: np.ndarray) -> jax.Array:
        """The segmented forward: per layer, qkv+write, hot partial
        attention through the pool, cold segments merged one staged
        upload at a time (next segment's upload enqueued before the
        current segment's attention dispatches), then the layer tail."""
        core = self.core
        prg = self.programs
        L = core.cfg.model.num_layers
        cold = self._cold_segments(seq)
        if cold:
            self.pager.begin(PageinPlan([list(cold)] * L))
        x = prg.embed(core.params, jnp.asarray(tokens))
        for l in range(L):
            li = np.int32(l)
            q, core.k_pool, core.v_pool = prg.qkv(
                core.params, li, x, positions, core.k_pool, core.v_pool,
                write_idx)
            o, m, d = prg.attn_hot(q, li, core.k_pool, core.v_pool,
                                   read_idx, read_pos, read_valid,
                                   positions)
            if cold:
                nxt = self._upload((l, 0))
                for s in range(len(cold)):
                    cur = nxt
                    nxt = (self._upload((l, s + 1))
                           if s + 1 < len(cold) else None)
                    o, m, d = prg.attn_cold(q, cur[0], cur[1], cur[2],
                                            o, m, d)
            x = prg.layer_out(core.params, li, x, o, m, d)
        return x

    def _sample(self, seq: _PagedSeq, x: jax.Array,
                last_i: int) -> Tuple[int, float]:
        prg = self.programs
        packed, seq.key, seq.counts = prg.head(
            self.core.params, x, np.asarray([last_i], np.int32),
            seq.temp, seq.top_p, seq.top_k, seq.key, seq.counts,
            seq.freq_pen, seq.pres_pen)
        # dynalint: ok(host-sync) THE designed paged-lane fetch: one
        # packed (token, logprob) pair per sampled token — the paged
        # path is synchronous per token by design (stop conditions and
        # the next feed depend on it)
        arr = np.asarray(packed)
        return int(arr[0, 0]), float(arr[0, 1])

    # ------------------------------------------------------------------
    def _hot_read(self, seq: _PagedSeq, upto: int, padded: int
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(slots, positions, valid) of static width ``padded`` covering
        the resident span [first_res*page, upto)."""
        start = seq.first_res * self.page
        n = upto - start
        slots = np.zeros(padded, np.int32)
        pos = np.zeros(padded, np.int32)
        valid = np.zeros(padded, bool)
        t = np.arange(start, upto)
        pages = np.asarray(seq.resident, np.int32)
        slots[:n] = (pages[t // self.page - seq.first_res] * self.page
                     + t % self.page)
        pos[:n] = t
        valid[:n] = True
        return slots[None], pos[None], valid[None]

    def _prefill_chunk(self, seq: _PagedSeq, out: List) -> None:
        from ...engine.engine import StepOutput

        t_disp = time.perf_counter()
        C = self.chunk
        prompt = seq.prompt
        start = seq.prefill_done
        count = min(C, len(prompt) - start)
        self._ensure_resident(seq, start + count)
        tokens = np.zeros((1, C), np.int32)
        positions = np.zeros((1, C), np.int32)
        write_idx = np.zeros((1, C), np.int32)    # pad -> scratch page 0
        tokens[0, :count] = prompt[start:start + count]
        positions[0, :count] = np.arange(start, start + count)
        write_idx[0, :count] = [self._slot(seq, p)
                                for p in range(start, start + count)]
        S = self._bucket_hot(start + count - seq.first_res * self.page)
        read_idx, read_pos, read_valid = self._hot_read(
            seq, start + count, S)
        x = self._forward(seq, tokens, positions, write_idx,
                          read_idx, read_pos, read_valid)
        for t in prompt[start:start + count]:
            seq.tokseq.append(int(t))
        seq.total_len = start + count
        seq.prefill_done = start + count
        is_last = seq.prefill_done >= len(prompt)
        # demote beyond the hot window now that the writes are enqueued
        self._demote(seq, self.hot_keep)
        if not is_last:
            from ...utils.roofline import prefill_cost

            fl, by, tk = prefill_cost(self.core.costs, [(start, count)])
            self._account("prefill", S, fl, by, tk,
                          time.perf_counter() - t_disp)
            return
        tok, lp = self._sample(seq, x, count - 1)
        from ...utils.roofline import prefill_cost

        fl, by, tk = prefill_cost(self.core.costs, [(start, count)])
        self._account("prefill", S, fl, by, tk,
                      time.perf_counter() - t_disp)
        seq.generated = 1
        seq.last_token = tok
        seq.cum_logprob = lp
        fin = self._finish(seq, tok)
        out.append(StepOutput(seq.seq_id, tok, seq.cum_logprob, fin,
                              prompt_tokens=len(prompt),
                              token_logprob=lp))
        if fin is not None:
            self._release(seq)

    def _window(self, seq: _PagedSeq) -> int:
        """Decode tokens to chain before the next host fetch: bounded by
        the chain knob, the request's remaining token budget and the
        paged context ceiling — overshoot past a mid-window EOS is the
        only speculative work (its writes die with the released pages)."""
        n = self.decode_chain
        if seq.request.stop.max_tokens:
            n = min(n, seq.request.stop.max_tokens - seq.generated)
        n = min(n, self.pcfg.max_context - len(seq.prompt) - seq.generated)
        return max(1, n)

    def _decode_step(self, seq: _PagedSeq, out: List) -> None:
        from ...engine.engine import StepOutput

        t_disp = time.perf_counter()
        N = self._window(seq)
        pos0 = seq.total_len
        # residency for the whole window up front: first_res (and thus
        # every token's read/write indexing) stays fixed across the
        # chained dispatches
        self._ensure_resident(seq, pos0 + N)
        if len(seq.resident) > self.pcfg.budget:
            self._demote(seq, self.pcfg.budget - 1)
        prg = self.programs
        packed_list: List[jax.Array] = []
        tokens = np.asarray([[seq.last_token]], np.int32)
        S_max = 0
        for i in range(N):
            pos = pos0 + i
            positions = np.asarray([[pos]], np.int32)
            write_idx = np.asarray([[self._slot(seq, pos)]], np.int32)
            S = self._bucket_hot(pos + 1 - seq.first_res * self.page)
            S_max = max(S_max, S)
            read_idx, read_pos, read_valid = self._hot_read(
                seq, pos + 1, S)
            x = self._forward(seq, tokens, positions, write_idx,
                              read_idx, read_pos, read_valid)
            packed, seq.key, seq.counts = prg.head(
                self.core.params, x, np.asarray([0], np.int32),
                seq.temp, seq.top_p, seq.top_k, seq.key, seq.counts,
                seq.freq_pen, seq.pres_pen)
            packed_list.append(packed)
            # chain: the sampled token feeds the next forward ON DEVICE —
            # no host round-trip between window steps
            tokens = packed[:, 0:1].astype(jnp.int32)
        # dynalint: ok(host-sync) THE designed paged-lane fetch, now one
        # packed (token, logprob) batch per N-token window instead of per
        # token — stop/stream detection runs host-side on the batch
        arrs = [np.asarray(p) for p in packed_list]
        from ...utils.roofline import decode_cost

        fl = by = tk = 0.0
        fin = None
        for i, arr in enumerate(arrs):
            seq.tokseq.append(int(seq.last_token))
            seq.total_len = pos0 + i + 1
            tok, lp = int(arr[0, 0]), float(arr[0, 1])
            f, b, t = decode_cost(self.core.costs, [pos0 + i], 1)
            fl, by, tk = fl + f, by + b, tk + t
            seq.generated += 1
            seq.last_token = tok
            seq.cum_logprob += lp
            fin = self._finish(seq, tok)
            out.append(StepOutput(seq.seq_id, tok, seq.cum_logprob, fin,
                                  token_logprob=lp))
            if fin is not None:
                # mid-window stop: tokens past it are discarded; their
                # page writes/sampler state die with the release below
                break
        self._account("decode", S_max, fl, by, tk,
                      time.perf_counter() - t_disp)
        if fin is not None:
            self._release(seq)

    def _finish(self, seq: _PagedSeq, token: int) -> Optional[FinishReason]:
        req = seq.request
        if not req.stop.ignore_eos:
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            if token in eos and seq.generated >= (req.stop.min_tokens or 0):
                return FinishReason.EOS
        if req.stop.max_tokens and seq.generated >= req.stop.max_tokens:
            return FinishReason.LENGTH
        if len(seq.prompt) + seq.generated >= self.pcfg.max_context:
            return FinishReason.LENGTH
        return None
